"""Headline benchmark: GPT-J-architecture training throughput + MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Baseline (BASELINE.md): the reference's GPT-J-6B DeepSpeed ZeRO-3
fine-tune ran at 146 tok/s per T4 GPU — ~8.3% MFU against the T4's 65
TFLOP/s fp16 peak (flops/token ~= 6N + attention ~= 3.7e10 for GPT-J-6B
at seq 512). We report model FLOPs utilization of a GPT-J-block-style
model training on this chip; ``vs_baseline`` is our MFU over the
reference's 8.3%.

On TPU the model is sized to the single benchmark chip (same architecture
as the gptj-6b flagship, fewer layers/width so full AdamW state fits one
chip's HBM); on CPU a tiny config keeps the harness runnable anywhere.

The detail JSON is attributable: it records the chosen remat policy (the
bench measures the candidate policies and keeps the winner), the fused-CE
chunk size, the (autotuned) flash block sizes, a per-phase breakdown
(compile time separated from steady state; fwd/bwd/opt split via a 3-way
jit split run once), and — when more than one device is visible — an
FSDP train-step MFU over all local devices (the MULTICHIP metric).

Env overrides: RAY_TPU_BENCH_REMAT (comma list of policies to try, e.g.
"dots,full"), RAY_TPU_BENCH_CE_CHUNK (fused-CE chunk size; 0 = unfused),
RAY_TPU_BENCH_MC_VARIANTS (comma list restricting the multichip
grad-transport/weight-update matrix, e.g. "fp32_replicated,int8_sharded").

`python bench.py --pipeline [--smoke]` runs the PIPELINE metric instead:
MPMD actor pipeline (1F1B, streamed activations) vs serial actors vs
single-program SPMD GPipe — tokens/s, measured + analytic bubble
fractions, and MPMD-vs-single-program loss parity. See pipeline_main.

`python bench.py --data [--smoke]` runs the DATA metric: the
generator-fed streaming executor vs the staged-serial baseline on a
2-fused-stage pipeline at equal task counts (end-to-end rows/s +
stage-overlap fraction), the `iter_batches` prefetch hit rate, and the
rollout→train dataflow (streaming vs epoch-barriered consumer bubble,
plus a mid-epoch runner SIGKILL leg proving exactly-once lineage
replay). See data_main.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

BASELINE_MFU_PCT = 8.3


def _sync(state, metrics):
    # Host-side scalar fetches of values that depend on the FULL step
    # (optimizer update included): the state's step counter is only
    # ready once donation/apply finished, and grad_norm depends on the
    # backward pass. (block_until_ready has proven unreliable on
    # experimental tunnel platforms.)
    int(state["step"])
    float(metrics["grad_norm"])
    return float(metrics["loss"])


def _measure_mfu(cfg, batch: int, seq: int, steps: int, warmup: int,
                 devices=None, phase_split: bool = False,
                 grad_transport: str = "fp32",
                 shard_weight_update: bool = False) -> dict:
    """Train-step MFU of one config at one sequence length.

    ``devices``: None = first local device; a list enables the FSDP
    multichip measurement (mesh fsdp=len(devices)).
    ``grad_transport`` / ``shard_weight_update`` select the gradient
    communication path (see ``models.training.make_train_step``).
    """
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import make_train_step
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh, chip_spec

    devices = devices or jax.devices()[:1]
    n_dev = len(devices)
    spec = MeshSpec(fsdp=n_dev) if n_dev > 1 else MeshSpec()
    mesh = build_mesh(spec, devices)
    # live telemetry off: its interval sync would serialize the
    # dispatch-ahead timing loop (bench records these numbers itself)
    bundle = make_train_step(cfg, mesh, learning_rate=1e-4,
                             grad_transport=grad_transport,
                             shard_weight_update=shard_weight_update,
                             telemetry_interval_s=0)
    state = bundle.init(seed=0)
    ids = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                             cfg.vocab_size)
    batch_d = {"input_ids": ids,
               "loss_mask": jnp.ones((batch, seq), jnp.float32)}

    t0 = time.perf_counter()
    state, metrics = bundle.step(state, batch_d)
    _sync(state, metrics)
    compile_s = time.perf_counter() - t0
    for _ in range(max(warmup - 1, 0)):
        state, metrics = bundle.step(state, batch_d)
    _sync(state, metrics)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = bundle.step(state, batch_d)
    final_loss = _sync(state, metrics)
    dt = time.perf_counter() - t0

    tokens_per_s = batch * seq * steps / dt
    achieved = tokens_per_s * cfg.flops_per_token(seq)
    mfu_pct = 100.0 * achieved / (chip_spec().bf16_flops * n_dev)
    out = {"mfu_pct": round(mfu_pct, 2),
           "tokens_per_s": round(tokens_per_s, 1),
           "step_ms": round(dt / steps * 1e3, 2),
           "loss": final_loss,
           "compile_s": round(compile_s, 2)}
    if phase_split:
        out["phases_ms"] = _phase_breakdown(
            cfg, bundle, state, batch_d, step_ms=dt / steps * 1e3)
    return out


def _phase_breakdown(cfg, bundle, state, batch_d, step_ms,
                     iters: int = 5) -> dict:
    """fwd/bwd/opt attribution via a 3-way jit split run once: time a
    forward-only jit and a value_and_grad jit; bwd = grad - fwd, opt =
    full step - grad. (Separate programs, so the split is approximate but
    attributable — XLA can't overlap across these boundaries.)"""
    import jax
    from ray_tpu.models.transformer import lm_loss

    def loss_of(p, b):
        return lm_loss(cfg, p, b, mesh=bundle.mesh, rules=bundle.rules)[0]

    fwd = jax.jit(loss_of)
    fwdbwd = jax.jit(jax.value_and_grad(loss_of))

    def time_it(fn, fetch):
        r = fn(state["params"], batch_d)
        fetch(r)                               # compile + settle
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(state["params"], batch_d)
        fetch(r)
        return (time.perf_counter() - t0) / iters * 1e3

    fwd_ms = time_it(fwd, lambda r: float(r))
    grad_ms = time_it(
        fwdbwd, lambda r: float(r[1]["final_norm"]["scale"][0]))
    return {"fwd_ms": round(fwd_ms, 2),
            "bwd_ms": round(max(grad_ms - fwd_ms, 0.0), 2),
            "opt_ms": round(max(step_ms - grad_ms, 0.0), 2),
            "step_ms": round(step_ms, 2)}


def _pick_remat_policy(cfg, batch, seq, steps, warmup):
    """Measure the candidate remat policies and keep the winner (its
    measurement IS the headline — no re-measure). The phase breakdown
    rides the first candidate that succeeds.

    OOM/compile failures just disqualify a candidate (e.g. "dots" when
    the saved matmul outputs don't fit HBM) — the bench must always
    produce a number.
    """
    policies = [p.strip() for p in os.environ.get(
        "RAY_TPU_BENCH_REMAT", "dots,full").split(",") if p.strip()]
    results, best = {}, None
    split_done = False
    for policy in policies:
        c = dataclasses.replace(cfg, remat=None, remat_policy=policy)
        try:
            r = _measure_mfu(c, batch, seq, steps, warmup,
                             phase_split=not split_done)
        except Exception as e:  # noqa: BLE001
            results[policy] = {"error": str(e)[:120]}
            continue
        split_done = True
        results[policy] = r
        if best is None or r["mfu_pct"] > results[best]["mfu_pct"]:
            best = policy
    if best is None:  # every candidate failed — surface the errors
        raise RuntimeError(f"no remat policy succeeded: {results}")
    return best, results


def main() -> None:
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import TransformerConfig
    from ray_tpu.ops import autotune_flash_blocks
    from ray_tpu.parallel.mesh import chip_spec

    on_tpu = jax.default_backend() == "tpu"
    ce_chunk = int(os.environ.get("RAY_TPU_BENCH_CE_CHUNK", "512"))
    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32768, d_model=2048, n_layers=10, n_heads=16,
            head_dim=128, d_ff=8192, max_seq_len=1024, rotary_dim=64,
            block_style="gptj", ce_chunk_size=ce_chunk)
        batch, seq, steps, warmup = 4, 1024, 10, 2
    else:
        cfg = TransformerConfig(
            vocab_size=1024, d_model=128, n_layers=2, n_heads=4,
            head_dim=32, d_ff=512, max_seq_len=256, rotary_dim=16,
            block_style="gptj", dtype=jnp.float32, remat=False,
            ce_chunk_size=ce_chunk)
        batch, seq, steps, warmup = 4, 256, 4, 1

    if on_tpu:
        # One-shot flash block autotune (cached per chip/seq/head_dim),
        # then measure candidate remat policies; the winner's own
        # measurement is the headline.
        bq, bk = autotune_flash_blocks(seq, cfg.head_dim, batch=batch,
                                       heads=cfg.n_heads)
        cfg = dataclasses.replace(cfg, attn_block_q=bq, attn_block_k=bk)
        policy, policy_results = _pick_remat_policy(
            cfg, batch, seq, steps, warmup)
        cfg = dataclasses.replace(cfg, remat=None, remat_policy=policy)
        head = policy_results[policy]
    else:
        policy = cfg.resolved_remat_policy
        policy_results = None
        head = _measure_mfu(cfg, batch, seq, steps, warmup,
                            phase_split=True)
    mfu_pct = head["mfu_pct"]

    detail = {
        "tokens_per_s": head["tokens_per_s"],
        "model_params": cfg.num_params,
        "backend": jax.default_backend(),
        "chip": chip_spec().name,
        "loss": head["loss"],
        "seq1024_mfu_pct": mfu_pct,
        "compile_s": head["compile_s"],
        "phases_ms": head.get("phases_ms") or next(
            (r["phases_ms"] for r in (policy_results or {}).values()
             if isinstance(r, dict) and r.get("phases_ms")), None),
        "remat_policy": policy,
        "ce_chunk_size": cfg.ce_chunk_size,
        "flash_blocks": [cfg.attn_block_q, cfg.attn_block_k],
    }
    if policy_results:
        detail["remat_policies"] = policy_results

    if on_tpu:
        # Long-sequence end-to-end MFU: the SAME model at seq 4096,
        # where the chunked CE and the Pallas flash backward dominate
        # the memory/compute picture. Same tokens/step as the headline
        # (batch 1 x 4096).
        bq4, bk4 = autotune_flash_blocks(4096, cfg.head_dim, batch=1,
                                         heads=cfg.n_heads)
        cfg4k = dataclasses.replace(cfg, max_seq_len=4096,
                                    attn_block_q=bq4, attn_block_k=bk4)
        try:
            detail["seq4096"] = _measure_mfu(cfg4k, 1, 4096, 6, 2)
            detail["seq4096"]["flash_blocks"] = [bq4, bk4]
        except Exception as e:  # noqa: BLE001
            try:  # policy fallback: "full" always fits
                cfg4k = dataclasses.replace(cfg4k, remat_policy="full")
                detail["seq4096"] = _measure_mfu(cfg4k, 1, 4096, 6, 2)
                detail["seq4096"]["remat_policy"] = "full"
            except Exception as e2:  # noqa: BLE001
                detail["seq4096"] = {"error": str(e)[:120],
                                     "error_full": str(e2)[:120]}
        try:
            detail["flash_bwd_4k"] = _flash_bwd_compare(jax, jnp)
        except Exception as e:  # noqa: BLE001
            detail["flash_bwd_4k"] = {"error": str(e)[:120]}

    if len(jax.devices()) > 1:
        detail["multichip"] = _measure_multichip(
            cfg, batch, seq, max(steps // 2, 2), warmup,
            single_tokens_per_s=head["tokens_per_s"])

    print(json.dumps({
        "metric": "gptj_train_mfu_single_chip",
        "value": round(mfu_pct, 2),
        "unit": "%MFU",
        "vs_baseline": round(mfu_pct / BASELINE_MFU_PCT, 3),
        "detail": detail,
    }))


# ------------------------------------------------------------ PIPELINE
# `python bench.py --pipeline` measures the PIPELINE metric: the
# 2-stage MPMD actor pipeline (parallel/mpmd_pipeline.py) driven by the
# 1F1B scheduler vs (a) the same actors driven serially with no overlap
# and (b) the single-program SPMD GPipe (ops/pipeline.py) at equal
# microbatches on local devices, plus the TRAIN variant: the full
# fwd+bwd+fused-per-stage-optimizer pipeline over the interleave
# matrix v in {1, 2} (virtual stages), with the measured bubble next
# to the analytic (S-1)/(v*M+S-1) and the make_train_step loss-
# trajectory parity (<= 1e-5 over 20 steps). Reports tokens/s, the
# MEASURED bubble fraction of every mode, the ANALYTIC bubbles next to
# them, and the forward/loss parity of the MPMD split against the
# single-program model. Gated by `tools/perf_gate.py --metric
# pipeline` (PIPELINE_r*.json).


def _pipeline_config(on_tpu: bool, smoke: bool):
    import jax.numpy as jnp
    from ray_tpu.models import TransformerConfig
    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32768, d_model=1024, n_layers=8, n_heads=8,
            head_dim=128, d_ff=4096, max_seq_len=1024, rotary_dim=64,
            block_style="gptj", ce_chunk_size=512)
        return cfg, 8, 1024, 4, 2, 6   # batch, seq, microbatches, S, steps
    cfg = TransformerConfig(
        vocab_size=1024, d_model=128, n_layers=4, n_heads=4,
        head_dim=32, d_ff=512, max_seq_len=256, rotary_dim=16,
        block_style="gptj", dtype=jnp.float32, remat=False,
        ce_chunk_size=128)
    if smoke:
        return cfg, 4, 64, 2, 2, 2
    return cfg, 8, 128, 4, 2, 8


def _pipeline_train_config(on_tpu: bool, smoke: bool):
    """The train-variant matrix config: deeper than the fwd+bwd leg
    (8 layers, longer sequences) so a v=2 chunk still carries real
    compute — interleaving wins exactly when per-chunk compute
    dominates per-op overhead, which is the TPU regime the CPU record
    has to approximate. Returns (cfg, batch, seq, M, train_steps)."""
    import dataclasses as _dc

    cfg, batch, seq, M, S, _ = _pipeline_config(on_tpu, smoke)
    if smoke:
        # shared tiny config: the smoke contract is wall-clock (< 60s
        # on CPU), not bubble ordering
        return cfg, batch, seq, M, 3
    if on_tpu:
        return cfg, batch, seq, M, 19
    return (_dc.replace(cfg, n_layers=8, max_seq_len=256), 8, 256, 4,
            19)


def _measure_mpmd(pipe, batch_d, steps: int) -> dict:
    """Steady-state tokens/s + measured bubble of an MPMDPipeline
    (first step is the compile step, excluded; per-step timing with
    the MEDIAN step reported — CPU bench boxes share cores, and one
    descheduled step would otherwise poison the whole window)."""
    import statistics

    pipe.step(batch_d)                # compile
    res = pipe.step(batch_d)          # warm (workers, event rings)
    dts, bubbles = [], []
    for _ in range(steps):
        t0 = time.perf_counter()
        res = pipe.step(batch_d)
        dts.append(time.perf_counter() - t0)
        bubbles.append(res.bubble_fraction)
    med = statistics.median(dts)
    b, s = batch_d["input_ids"].shape
    return {"tokens_per_s": round(b * s / med, 1),
            "step_ms": round(med * 1e3, 2),
            "bubble_fraction": round(sum(bubbles) / len(bubbles), 4),
            "loss": res.loss,
            "stage_busy_ms": [round(st["busy_s"] * 1e3, 2)
                              for st in res.stage_stats]}


def _measure_plan(plan, cfg, batch_d, steps: int,
                  lr: float = 1e-3, stage_mesh=None) -> dict:
    """Measure one ParallelPlan lowering: compile step, then
    ``steps`` timed steps (median — shared CPU bench boxes deschedule).
    Returns tokens/s, step wall, measured bubble (pipeline lowerings)
    and the loss trajectory (entry 0 = the compile step)."""
    import statistics

    prog = plan.build(cfg, learning_rate=lr, seed=0,
                      stage_mesh=stage_mesh) \
        if plan.pp > 1 else \
        plan.build(cfg, learning_rate=lr, seed=0,
                   telemetry_interval_s=0)
    res = prog.step(batch_d)          # compile
    losses = [res.loss]
    dts, bubbles = [], []
    for _ in range(steps):
        t0 = time.perf_counter()
        res = prog.step(batch_d)
        dts.append(time.perf_counter() - t0)
        losses.append(res.loss)
        if res.bubble_fraction is not None:
            bubbles.append(res.bubble_fraction)
    med = statistics.median(dts)
    b, s = batch_d["input_ids"].shape
    out = {"tokens_per_s": round(b * s / med, 1),
           "step_ms": round(med * 1e3, 2),
           "losses": [round(l, 8) for l in losses]}
    if bubbles:
        out["bubble_fraction"] = round(sum(bubbles) / len(bubbles), 4)
    if res.grad_norm is not None:
        out["grad_norm"] = round(res.grad_norm, 6)
    out["_result"] = res
    out["_program"] = prog
    return out


def _measure_train(cfg, batch_d, S: int, M: int, v: int, steps: int,
                   lr: float = 1e-3) -> dict:
    """Train-variant measurement at one interleave factor: the full
    fwd+bwd+fused-per-stage-opt pipeline (grads/params/opt state
    resident on the stages; the driver only reduces the scalar grad
    norm), lowered through ``ParallelPlan`` like everything else.
    Returns steady-state tokens/s, the measured bubble, the analytic
    interleaved bubble (S-1)/(v*M+S-1) next to it, and the loss
    trajectory (entry 0 = the compile step)."""
    from ray_tpu.parallel.mpmd_pipeline import analytic_bubble
    from ray_tpu.parallel.plan import ParallelPlan

    row = _measure_plan(
        ParallelPlan(pp=S, virtual=v, n_microbatches=M),
        cfg, batch_d, steps, lr=lr)
    res, prog = row.pop("_result"), row.pop("_program")
    prog.shutdown()
    row["analytic_bubble"] = round(analytic_bubble(S, M, v), 4)
    row["stage_busy_ms"] = [round(st["busy_s"] * 1e3, 2)
                            for st in res.detail.stage_stats]
    row["stage_opt_ms"] = [round(st["opt_s"] * 1e3, 2)
                           for st in res.detail.stage_stats]
    return row


def _train_reference_losses(cfg, batch_d, n: int,
                            lr: float = 1e-3) -> list:
    """The single-program make_train_step loss trajectory the pipeline
    train variants are gated against (<= 1e-5 parity) — the SPMD
    lowering of the same ParallelPlan surface."""
    from ray_tpu.parallel.plan import ParallelPlan

    prog = ParallelPlan(pp=1).build(cfg, learning_rate=lr, seed=0,
                                    telemetry_interval_s=0)
    return [prog.step(batch_d).loss for _ in range(n)]


def _stage_reduce_wire(cfg, n_stages: int, dp: int) -> dict:
    """Measured wire accounting of the per-stage gradient reduction:
    lower the SAME ``collective.psum_tree`` program a dp-mesh stage
    compiles for one stage's gradient slab, and sum the payload bytes
    of every cross-device collective in the compiled HLO (all-reduce
    counted twice: it is reduce-scatter + all-gather fused). The int8
    row's all-gather really is ``s8[...]`` in the compiled module —
    int8 values + per-block f32 scales on the wire, not error
    injection. Wall clock of the reduction rides along; on the CPU
    backend the "wire" is shared memory, so the byte column is the
    backend-independent signal there."""
    import re

    import numpy as np

    import jax
    from jax.sharding import PartitionSpec as P

    from ray_tpu.models.transformer import (
        init_params, stage_slice_params)
    from ray_tpu.parallel import collective as coll
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.parallel.quantization import compression_ratio
    from ray_tpu.util.jax_compat import shard_map

    shapes = jax.eval_shape(
        lambda: stage_slice_params(
            cfg, init_params(cfg, jax.random.PRNGKey(0)), 0, n_stages))
    n = sum(int(np.prod(l.shape))
            for l in jax.tree.leaves(shapes))
    mesh = build_mesh(MeshSpec(dp=dp), jax.devices()[:dp])
    x = np.zeros((dp, n), np.float32)
    dt_bytes = {"f64": 8, "f32": 4, "u32": 4, "s32": 4, "bf16": 2,
                "f16": 2, "s8": 1, "u8": 1, "pred": 1}
    out = {"grad_numel": n, "dp": dp}
    for tr in ("fp32", "int8"):
        def body(xl, _tr=tr):
            return coll.psum_tree({"g": xl[0]}, ("dp", "fsdp"), dp,
                                  transport=_tr)["g"]
        f = jax.jit(shard_map(body, mesh=mesh, in_specs=P(("dp",)),
                              out_specs=P(), check_vma=False))
        txt = f.lower(x).compile().as_text()
        total = 0
        for m in re.finditer(
                r"=\s*(\w+)\[([\d,]*)\][^=\n]*?\s"
                r"(all-gather|all-reduce|reduce-scatter|"
                r"collective-permute|all-to-all)\(", txt):
            dt, dims, op = m.group(1), m.group(2), m.group(3)
            numel = 1
            for d in dims.split(","):
                if d:
                    numel *= int(d)
            nbytes = numel * dt_bytes.get(dt, 4)
            total += 2 * nbytes if op == "all-reduce" else nbytes
        r = f(x)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(10):
            r = f(x)
        jax.block_until_ready(r)
        out[tr] = {"collective_bytes": total,
                   "reduce_ms": round(
                       (time.perf_counter() - t0) / 10 * 1e3, 3)}
    fb = out["fp32"]["collective_bytes"]
    ib = out["int8"]["collective_bytes"]
    out["measured_comm_reduction"] = round(1.0 - ib / max(fb, 1), 4)
    out["analytic_compression"] = round(compression_ratio(n), 2)
    return out


def _measure_plan3d(cfg, batch_d, S: int, M: int, steps: int,
                    ref_losses: list) -> dict:
    """The 3D matrix: nested pp×dp lowerings of one ParallelPlan —
    each PipelineStage hosts a shard_map'd dp program over its own
    mesh, grads reduced once per step by the real fp32/int8 collective
    and applied under the cross-replica flat-sharded update. The
    ``pp_dp1_reference`` row runs the SAME shard_map'd stage programs
    on a 1-device stage mesh (identical recompute backward, zero
    cross-rank comm), so each variant's step excess over it is
    attributable to stage-mesh communication. fp32 rows must track the
    single-program ``make_train_step`` trajectory to <= 1e-5; the
    int8 rows additionally carry the measured collective-byte
    reduction of the stage's gradient wire (``wire``)."""
    from ray_tpu.parallel.plan import ParallelPlan

    dp = 2

    def parity(losses):
        return round(max(abs(a - b)
                         for a, b in zip(losses, ref_losses)), 9)

    def run(plan):
        row = _measure_plan(plan, cfg, batch_d, steps, stage_mesh=True)
        row.pop("_result")
        row.pop("_program").shutdown()
        row["loss_parity_abs"] = parity(row["losses"])
        return row

    base = run(ParallelPlan(pp=S, dp=1, n_microbatches=M))
    variants = {}
    for gt in ("fp32", "int8"):
        name = f"pp{S}_dp{dp}_{gt}"
        row = run(ParallelPlan(pp=S, dp=dp, n_microbatches=M,
                               grad_transport=gt,
                               shard_weight_update=True))
        row["comm_split_ms"] = {
            "compute_ms": base["step_ms"],
            "comm_ms": round(max(row["step_ms"] - base["step_ms"],
                                 0.0), 2)}
        variants[name] = row
    wire = _stage_reduce_wire(cfg, S, dp)
    return {
        "grid": {"pp": S, "dp": dp, "fsdp": 1, "virtual": 1,
                 "n_microbatches": M},
        "pp_dp1_reference": base,
        "variants": variants,
        "wire": wire,
        "loss_parity_3d_abs": variants[f"pp{S}_dp{dp}_fp32"][
            "loss_parity_abs"],
        "int8_wire_reduction": wire["measured_comm_reduction"],
    }


def _measure_spmd_gpipe(cfg, batch: int, seq: int, n_microbatches: int,
                        n_stages: int, steps: int) -> dict:
    """The single-program GPipe comparison: embed + pipeline_apply over
    a pp mesh + fused head loss, fwd+bwd via value_and_grad — same
    model, same microbatches, one shared compile."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ray_tpu.models.transformer import (
        init_params, run_layers, stage_layer_ranges, stage_loss,
        _final_norm)
    from ray_tpu.ops.pipeline import pipeline_apply, stack_stage_params

    devices = jax.devices()[:n_stages]
    if len(devices) < n_stages:
        return {"error": f"needs {n_stages} local devices"}
    mesh = Mesh(np.array(devices), ("pp",))
    params = init_params(cfg, jax.random.PRNGKey(0))
    ranges = stage_layer_ranges(cfg.n_layers, n_stages)
    stacked = stack_stage_params([
        jax.tree.map(lambda a: a[lo:hi], params["layers"])
        for lo, hi in ranges])

    def stage_fn(lp, x):
        return run_layers(cfg, lp, x)[0].astype(x.dtype)

    def loss_fn(p, ids, mask):
        x = jnp.take(p["embed"], ids, axis=0).astype(cfg.dtype)
        x = pipeline_apply(stage_fn, p["stacked"], x, mesh,
                           n_microbatches)
        x = _final_norm(cfg, p, x)
        tail = {"lm_head": p["lm_head"]}
        return stage_loss(cfg, tail, x, ids, mask)[0]

    p = {"embed": params["embed"], "stacked": stacked,
         "final_norm": params["final_norm"],
         "lm_head": params["lm_head"]}
    step = jax.jit(jax.value_and_grad(loss_fn))
    ids = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                             cfg.vocab_size)
    mask = jnp.ones((batch, seq), jnp.float32)
    loss, grads = step(p, ids, mask)
    jax.block_until_ready(grads)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, grads = step(p, ids, mask)
    jax.block_until_ready(grads)
    dt = time.perf_counter() - t0
    return {"tokens_per_s": round(batch * seq * steps / dt, 1),
            "step_ms": round(dt / steps * 1e3, 2),
            "loss": float(loss)}


def pipeline_main(smoke: bool = False) -> None:
    # the SPMD comparison needs >= 2 local devices; on CPU force the
    # virtual split BEFORE jax initializes its backend
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("RAY_TPU_JAX_PLATFORM",
                          os.environ.get("JAX_PLATFORMS", ""))

    import numpy as np

    import jax
    import ray_tpu
    from ray_tpu.models.transformer import init_params, lm_loss
    from ray_tpu.parallel.mpmd_pipeline import (
        MPMDPipeline, analytic_gpipe_bubble)
    from ray_tpu.parallel.mesh import chip_spec
    from ray_tpu.util.state import list_task_events

    on_tpu = jax.default_backend() == "tpu"
    cfg, batch, seq, M, S, steps = _pipeline_config(on_tpu, smoke)
    ids = np.array(jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size))
    batch_d = {"input_ids": ids,
               "loss_mask": np.ones((batch, seq), np.float32)}

    ray_tpu.init(num_cpus=max(2 * S + 2, 6),
                 _num_initial_workers=S + 1)
    try:
        pipe = MPMDPipeline(cfg, n_stages=S, n_microbatches=M, seed=0)
        mpmd = _measure_mpmd(pipe, batch_d, steps)
        serial = MPMDPipeline(cfg, n_stages=S, n_microbatches=M,
                              seed=0, serial=True)
        ser = _measure_mpmd(serial, batch_d, max(steps // 2, 1))
        pipe.shutdown()
        serial.shutdown()
        # forward/loss parity vs the single-program model (exact same
        # seed -> bit-identical weights; must agree to <= 1e-5)
        ref_loss = float(lm_loss(
            cfg, init_params(cfg, jax.random.PRNGKey(0)), batch_d)[0])
        parity = abs(ref_loss - mpmd["loss"])
        spmd = _measure_spmd_gpipe(cfg, batch, seq, M, S, steps)
        # train variant: fwd+bwd+fused per-stage opt over the
        # interleave matrix v in {1, 2}, plus the make_train_step loss-
        # trajectory parity (20 steps full, shrunk in smoke)
        tcfg, tb, tseq, tM, train_steps = _pipeline_train_config(
            on_tpu, smoke)
        tids = np.array(jax.random.randint(
            jax.random.PRNGKey(1), (tb, tseq), 0, tcfg.vocab_size))
        tbatch = {"input_ids": tids,
                  "loss_mask": np.ones((tb, tseq), np.float32)}
        t_train = time.perf_counter()
        train = {f"v{v}": _measure_train(tcfg, tbatch, S, tM, v,
                                         train_steps)
                 for v in (1, 2)}
        ref_losses = _train_reference_losses(tcfg, tbatch,
                                             train_steps + 1)
        train["n_microbatches"] = tM
        train["model_params"] = tcfg.num_params
        train["parity_steps"] = train_steps + 1
        train["loss_parity_train_abs"] = round(max(
            abs(a - b)
            for key in ("v1", "v2")
            for a, b in zip(train[key]["losses"], ref_losses)), 9)
        train["wall_s"] = round(time.perf_counter() - t_train, 2)
        # 3D matrix: nested pp×dp stage meshes with real fp32/int8
        # grad collectives + sharded update, gated against the same
        # make_train_step reference trajectory (smoke shrinks steps;
        # the recorded full run carries the 20-step parity)
        p3_steps = 2 if smoke else train_steps
        plan3d = _measure_plan3d(tcfg, tbatch, S, tM, p3_steps,
                                 ref_losses[:p3_steps + 1])
        ticks = len(list_task_events(filters=[("ev", "=", "STAGE_TICK")]))
    finally:
        ray_tpu.shutdown()

    detail = {
        "backend": jax.default_backend(),
        "chip": chip_spec().name,
        "n_stages": S,
        "n_microbatches": M,
        "model_params": cfg.num_params,
        "mpmd_1f1b": mpmd,
        "serial": ser,
        "spmd_gpipe": spmd,
        "train": train,
        "plan3d": plan3d,
        "analytic_gpipe_bubble": round(analytic_gpipe_bubble(S, M), 4),
        "loss_parity_abs": round(parity, 9),
        "single_program_loss": ref_loss,
        "stage_tick_events": ticks,
    }
    print(json.dumps({
        "metric": "pipeline_tokens_per_s",
        "value": mpmd["tokens_per_s"],
        "unit": "tok/s",
        "vs_serial": round(mpmd["tokens_per_s"]
                           / max(ser["tokens_per_s"], 1e-9), 3),
        "detail": detail,
    }))


# ----------------------------------------------------------------- DATA
# `python bench.py --data` measures the DATA metric: the generator-fed
# streaming executor (data/_internal/plan.py) against the staged-serial
# baseline (same pipeline, same task counts, materialize barrier
# between stages), the iter_batches prefetch hit rate, and the
# rollout→train dataflow bubble (rllib/rollout_stream.py) streaming vs
# epoch-barriered — with a chaos leg SIGKILLing one runner mid-epoch
# and asserting exactly-once block delivery. Gated by
# `tools/perf_gate.py --metric data` (DATA_r*.json).


def _data_config(smoke: bool) -> dict:
    if smoke:
        return dict(n_blocks=8, rows_per_block=200, t1=0.12, t2=0.12,
                    pool=2, runners=2, r_blocks=2, r_steps=16,
                    minibatch=8, epochs=2)
    return dict(n_blocks=24, rows_per_block=2000, t1=0.25, t2=0.25,
                pool=4, runners=2, r_blocks=8, r_steps=32,
                minibatch=8, epochs=4)


def _data_pipeline(cfg: dict):
    """The measured 2-fused-stage pipeline: read+map fuse into stage 1
    (generator tasks), the actor-pool map is stage 2. Each stage costs
    a fixed sleep per block, so the serialized stage time is known and
    overlap shows up directly in the wall clock."""
    from ray_tpu import data as rd
    t1, t2 = cfg["t1"], cfg["t2"]

    def stage1(batch):
        time.sleep(t1)
        return {"x": batch["id"] * 2}

    class Stage2:
        def __call__(self, batch):
            time.sleep(t2)
            return {"x": batch["x"] + 1}

    n_rows = cfg["n_blocks"] * cfg["rows_per_block"]
    return (rd.range(n_rows, parallelism=cfg["n_blocks"])
            .map_batches(stage1, batch_size=None)
            .map_batches(Stage2, batch_size=None,
                         compute=rd.ActorPoolStrategy(cfg["pool"])))


class _DataCtx:
    """Scoped DataContext override (restores on exit)."""

    def __init__(self, **overrides):
        self.overrides = overrides

    def __enter__(self):
        from ray_tpu.data.context import DataContext
        self.ctx = DataContext.get_current()
        self.saved = {k: getattr(self.ctx, k) for k in self.overrides}
        for k, v in self.overrides.items():
            setattr(self.ctx, k, v)
        return self.ctx

    def __exit__(self, *exc):
        for k, v in self.saved.items():
            setattr(self.ctx, k, v)


def _measure_data_mode(cfg: dict, mode: str) -> dict:
    """rows/s of the 2-stage pipeline under one execution mode, at
    equal task counts: ``pool`` streaming generator members per stage
    vs a ``pool``-task in-order window (and a ``pool``-actor stage)
    in the staged baseline. The streaming credit window keeps its
    default — it bounds buffered OUTPUT blocks, not compute
    concurrency."""
    overrides = dict(execution_mode=mode, preserve_order=False,
                     streaming_stage_parallelism=cfg["pool"])
    if mode == "staged":
        overrides["max_tasks_in_flight_per_operator"] = cfg["pool"]
    with _DataCtx(**overrides):
        ds = _data_pipeline(cfg)
        rows = 0
        t0 = time.perf_counter()
        for b in ds.iter_blocks():
            rows += b.num_rows
        wall = time.perf_counter() - t0
    return {"rows": rows, "wall_s": round(wall, 3),
            "rows_per_s": round(rows / wall, 1)}


def _measure_prefetch(cfg: dict) -> dict:
    """Prefetch hit rate of the shard consumer edge: a consumer doing
    per-batch 'train-step' work while the background prefetcher keeps
    the next blocks resolved."""
    from ray_tpu import data as rd
    t1 = cfg["t1"]

    def stage(batch):
        time.sleep(t1 / 2)
        return {"x": batch["id"]}

    with _DataCtx(execution_mode="streaming", preserve_order=False,
                  max_tasks_in_flight_per_operator=cfg["pool"],
                  streaming_stage_parallelism=cfg["pool"]):
        n_rows = cfg["n_blocks"] * cfg["rows_per_block"]
        ds = rd.range(n_rows, parallelism=cfg["n_blocks"]) \
            .map_batches(stage, batch_size=None)
        it = ds.streaming_split(1, equal=False)[0]
        rows = 0
        for batch in it.iter_batches(batch_size=cfg["rows_per_block"],
                                     prefetch_batches=2):
            rows += len(batch["x"])
            time.sleep(t1 / 2)  # the consumer's own per-batch work
    stats = it.prefetch_stats()
    total = max(stats["hits"] + stats["misses"], 1)
    return {"rows": rows, "hits": stats["hits"],
            "misses": stats["misses"],
            "hit_rate": round(stats["hits"] / total, 4)}


def _measure_rollout_train(cfg: dict, chaos: bool = False) -> dict:
    """The rollout→train dataflow: N generator-task runners stream
    GAE'd blocks into the learner. Streaming consumes minibatches as
    blocks arrive; the epoch-barriered baseline gathers every block
    before training. Bubble = fraction of the consume wall the learner
    sat idle waiting on rollouts. ``chaos`` SIGKILLs runner 0 mid-epoch
    and asserts exactly-once delivery after lineage replay."""
    import tempfile

    import ray_tpu
    from ray_tpu.rllib.learner import Learner
    from ray_tpu.rllib.ppo import ppo_loss
    from ray_tpu.rllib.rl_module import RLModuleSpec
    from ray_tpu.rllib.rollout_stream import (
        RandomEnv, RolloutBlockStream, block_uid, make_rollout_streams)

    import numpy as np

    OBS_DIM = 32
    spec = RLModuleSpec(observation_dim=OBS_DIM, num_actions=4,
                        hiddens=(256, 256))
    learner = Learner(spec, ppo_loss, learning_rate=1e-3)
    weights = ray_tpu.put(learner.get_weights())
    runners, blocks, steps = cfg["runners"], cfg["r_blocks"], cfg["r_steps"]
    expected_rows = runners * blocks * steps

    def _warm_update(n):
        # compile both jitted update shapes outside the measured walls
        learner.update_from_batch({
            "obs": np.zeros((n, OBS_DIM), np.float32),
            "actions": np.zeros((n,), np.int64),
            "logp": np.zeros((n,), np.float32),
            "value_targets": np.zeros((n,), np.float32),
            "advantages": np.ones((n,), np.float32),
            "block_uid": np.zeros((n,), np.int64)})

    _warm_update(cfg["minibatch"])
    _warm_update(expected_rows)
    expected_uids = sorted(block_uid(w, b) for w in range(runners)
                           for b in range(blocks))

    def streams(faults=None, n=None, nb=None, ns=None):
        return make_rollout_streams(
            lambda: RandomEnv(OBS_DIM, 4, 25, seed=7), spec, weights,
            n or runners, nb or blocks, ns or steps, seed=11,
            faults=faults)

    # Warm the rollout path on (nearly) every worker: the first rollout
    # block on a cold worker pays module import + the policy-forward
    # jit compile, which must not bias whichever leg lands there.
    warm_stream = RolloutBlockStream(
        streams(n=max(runners * 3, 6), nb=1, ns=2))
    for _ in warm_stream.iter_blocks():
        pass

    def run_streaming(faults=None):
        stream = RolloutBlockStream(streams(faults), collect=True)
        t0 = time.perf_counter()
        n_updates = 0
        for mb in stream.iter_batches(cfg["minibatch"], drop_last=True):
            learner.update_from_batch(mb)
            n_updates += 1
        for _ in range(cfg["epochs"] - 1):
            learner.update_from_batch(stream.full_batch())
        wall = time.perf_counter() - t0
        st = stream.stats()
        return {"rows": st["rows"], "wall_s": round(wall, 3),
                "rows_per_s": round(st["rows"] / wall, 1),
                "idle_s": round(st["wait_s"], 3),
                "bubble": round(st["wait_s"] / wall, 4),
                "updates": n_updates,
                "uids": sorted(stream.delivered_uids())}

    # streaming (overlapped) epoch
    sm = run_streaming()
    # epoch-barriered baseline: gather every block, then train
    gens = streams()
    t0 = time.perf_counter()
    barrier = RolloutBlockStream(gens, collect=True)
    for _ in barrier.iter_blocks():
        pass  # gather everything before the first update
    rollout_s = time.perf_counter() - t0
    batch = barrier.full_batch()
    n = len(batch["obs"])
    mbs = cfg["minibatch"]
    for _ in range(cfg["epochs"]):
        for s in range(0, n - mbs + 1, mbs):
            learner.update_from_batch(
                {k: v[s:s + mbs] for k, v in batch.items()})
    wall = time.perf_counter() - t0
    bar = {"rows": n, "wall_s": round(wall, 3),
           "rows_per_s": round(n / wall, 1),
           "idle_s": round(rollout_s, 3),
           "bubble": round(rollout_s / wall, 4)}

    out = {
        "streaming": {k: v for k, v in sm.items() if k != "uids"},
        "epoch_barriered": bar,
        # seconds the learner sat with nothing to train on, streaming
        # vs the epoch barrier — same workload, absolute idle time
        "consumer_idle_reduction": round(
            1.0 - sm["idle_s"] / max(bar["idle_s"], 1e-9), 4),
    }
    if chaos:
        marker = tempfile.mktemp()
        ch = run_streaming(
            faults={0: {"die_at_block": max(1, blocks // 2),
                        "marker": marker}})
        killed = os.path.exists(marker)
        out["chaos"] = {
            "runner_killed": killed,
            "rows_delivered": ch["rows"],
            "rows_expected": expected_rows,
            "exactly_once": killed and ch["rows"] == expected_rows
            and ch["uids"] == expected_uids,
        }
    return out


def data_main(smoke: bool = False) -> None:
    os.environ.setdefault("RAY_TPU_JAX_PLATFORM",
                          os.environ.get("JAX_PLATFORMS", ""))
    import jax
    import ray_tpu
    from ray_tpu.parallel.mesh import chip_spec

    cfg = _data_config(smoke)
    n_cpus = 2 * cfg["pool"] + cfg["runners"] + 4
    ray_tpu.init(num_cpus=n_cpus,
                 _num_initial_workers=2 * cfg["pool"] + 2)
    try:
        # Warm every worker first (cold workers pay the pyarrow /
        # data-layer import on their first block task — a one-time
        # cost that must not land in either measured wall): one
        # concurrent import task per CPU pins each idle worker.
        def _warm_worker():
            import time as _t

            import ray_tpu.data.block  # noqa: F401 — the import IS the warmup
            _t.sleep(0.3)
            return True

        warm_fn = ray_tpu.remote(num_cpus=1)(_warm_worker)
        ray_tpu.get([warm_fn.remote() for _ in range(n_cpus)])
        # and warm both executor paths end to end on a tiny pipeline
        warm = dict(cfg, n_blocks=2 * cfg["pool"], rows_per_block=10,
                    t1=0.0, t2=0.0)
        _measure_data_mode(warm, "streaming")
        _measure_data_mode(warm, "staged")
        # best-of-2 per mode (symmetric): one straggler scheduling
        # hiccup must not decide the record
        streaming = max((_measure_data_mode(cfg, "streaming")
                         for _ in range(2)),
                        key=lambda r: r["rows_per_s"])
        staged = max((_measure_data_mode(cfg, "staged")
                      for _ in range(2)),
                     key=lambda r: r["rows_per_s"])
        prefetch = _measure_prefetch(cfg)
        rollout = _measure_rollout_train(cfg, chaos=True)
    finally:
        ray_tpu.shutdown()

    expected_rows = cfg["n_blocks"] * cfg["rows_per_block"]
    # the staged-serial wall IS the serialized stage time at equal task
    # counts; overlap is the fraction of it the streaming executor hid
    overlap = max(0.0, 1.0 - streaming["wall_s"] / staged["wall_s"])
    detail = {
        "backend": jax.default_backend(),
        "chip": chip_spec().name,
        "n_blocks": cfg["n_blocks"],
        "rows_per_block": cfg["rows_per_block"],
        "stage_sleep_s": [cfg["t1"], cfg["t2"]],
        "pool": cfg["pool"],
        "rows_expected": expected_rows,
        "exactly_once_rows": streaming["rows"] == expected_rows
        and staged["rows"] == expected_rows,
        "streaming": streaming,
        "staged": staged,
        "stage_overlap_fraction": round(overlap, 4),
        "serialized_stage_s_analytic": round(
            cfg["n_blocks"] * (cfg["t1"] + cfg["t2"]) / cfg["pool"], 3),
        "prefetch": prefetch,
        "rollout_train": rollout,
    }
    print(json.dumps({
        "metric": "data_rows_per_s",
        "value": streaming["rows_per_s"],
        "unit": "rows/s",
        "vs_staged": round(streaming["rows_per_s"]
                           / max(staged["rows_per_s"], 1e-9), 3),
        "detail": detail,
    }))


MULTICHIP_VARIANTS = (("fp32", False), ("int8", False),
                      ("fp32", True), ("int8", True))


def _measure_multichip(cfg, batch: int, seq: int, steps: int, warmup: int,
                       single_tokens_per_s: float) -> dict:
    """FSDP train-step MFU over all local devices (MULTICHIP metric),
    measured for the gradient-transport x weight-update matrix:
    fp32 vs int8 grad transport, replicated vs cross-replica-sharded
    weight update. Same per-device token load as the headline.

    Each variant carries a comm/compute split: compute is the
    single-chip step time at the same per-device load (from the headline
    measurement), comm is the multichip step-time excess over it —
    attributable, since the only thing the multichip step adds is the
    gradient/param communication the variant is designed to shrink.

    Env override: RAY_TPU_BENCH_MC_VARIANTS (comma list like
    "fp32_replicated,int8_sharded") restricts the matrix.
    """
    import jax

    n = len(jax.devices())
    single_step_ms = batch * seq / single_tokens_per_s * 1e3
    want = os.environ.get("RAY_TPU_BENCH_MC_VARIANTS")
    want = {v.strip() for v in want.split(",")} if want else None
    variants = {}
    for gt, swu in MULTICHIP_VARIANTS:
        name = f"{gt}_{'sharded' if swu else 'replicated'}"
        if want is not None and name not in want:
            continue
        try:
            v = _measure_mfu(cfg, batch * n, seq, steps, warmup,
                             devices=jax.devices(), grad_transport=gt,
                             shard_weight_update=swu)
            v["comm_split_ms"] = {
                "compute_ms": round(single_step_ms, 2),
                "comm_ms": round(max(v["step_ms"] - single_step_ms, 0.0),
                                 2)}
        except Exception as e:  # noqa: BLE001
            v = {"error": str(e)[:120]}
        variants[name] = v
    ok = {k: v for k, v in variants.items() if "mfu_pct" in v}
    if not ok:
        return {"n_devices": n, "variants": variants,
                "error": "no multichip variant succeeded"}
    # Headline multichip fields stay the fp32 replicated baseline (the
    # pre-existing metric shape); the matrix rides in "variants".
    mc = dict(ok.get("fp32_replicated") or next(iter(ok.values())))
    mc["n_devices"] = n
    mc["best_variant"] = max(ok, key=lambda k: ok[k]["mfu_pct"])
    mc["variants"] = variants
    return mc


def _flash_bwd_compare(jax, jnp, seq: int = 4096) -> dict:
    """Long-sequence attention-gradient timing: the Pallas dq/dk/dv
    kernels (with the fused delta-precompute kernel and autotuned block
    sizes) vs the lax.scan backward they replaced."""
    from ray_tpu.ops.flash_attention import flash_attention

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 16, seq, 128),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), q.shape, jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.bfloat16)

    out = {}
    for mode in ("pallas", "xla"):
        @jax.jit
        def g(q, k, v, _mode=mode):
            def f(q, k, v):
                return jnp.sum(flash_attention(
                    q, k, v, causal=True, backward=_mode
                ).astype(jnp.float32))
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        r = g(q, k, v)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(8):
            r = g(q, k, v)
        jax.block_until_ready(r)
        out[mode + "_ms"] = round((time.perf_counter() - t0) / 8 * 1e3, 2)
    out["speedup"] = round(out["xla_ms"] / out["pallas_ms"], 2)
    return out


# -------------------------------------------------------------- ELASTIC
# `python bench.py --elastic` measures the ELASTIC metric: an
# ElasticTrainer driven through the full recovery gauntlet — a seeded
# stage-actor kill mid-train-step (failure path: snapshot rollback +
# replay, steps-lost ≤ 1), then a chaos-scheduled maintenance notice
# that drains the only slice (notice path: live in-memory snapshot →
# fold pp→spmd, 0 steps lost), then a scale-up regrow back to the
# pipeline grid — with step-for-step loss-trajectory parity against an
# uninterrupted SPMD run the whole way. Gated by
# `tools/perf_gate.py --metric elastic` (ELASTIC_r*.json).


class _ElasticStubScheduler:
    def __init__(self):
        self.draining = {}

    def set_draining(self, node_id, flag):
        self.draining[node_id.binary()] = flag


class _ElasticStubController:
    """Clusterless SliceManager backing for the bench: the fake slices
    are synthetic capacity signals — the real local cluster only hosts
    the stage actors."""

    def __init__(self):
        from ray_tpu.core.events import FlightRecorder
        self.scheduler = _ElasticStubScheduler()
        self.rescheduled = []
        self.recorder = FlightRecorder("bench", capacity=4096)

    def call_on_loop(self, fn, timeout=None):
        return fn()

    def _reschedule_pgs_on_nodes(self, node_bs):
        self.rescheduled.append(set(node_bs))
        return 1

    def _maybe_schedule(self, force=False):
        pass


def elastic_main(smoke: bool = False) -> None:
    import random
    import threading

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("RAY_TPU_JAX_PLATFORM",
                          os.environ.get("JAX_PLATFORMS", ""))

    import numpy as np

    import jax
    import ray_tpu
    from ray_tpu.autoscaler.node_provider import FakeSliceProvider
    from ray_tpu.autoscaler.slices import SliceManager, SliceTypeConfig
    from ray_tpu.core.chaos import ChaosConfig
    from ray_tpu.parallel.elastic import ElasticTrainer
    from ray_tpu.parallel.mesh import chip_spec
    from ray_tpu.parallel.plan import ParallelPlan

    on_tpu = jax.default_backend() == "tpu"
    cfg, batch, seq, M, S, _ = _pipeline_config(on_tpu, smoke)
    pre_steps, post_steps = (2, 5) if smoke else (3, 20)
    ids = np.array(jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size))
    batch_d = {"input_ids": ids,
               "loss_mask": np.ones((batch, seq), np.float32)}

    # the schedule's delay is past the slice-UP reconcile (which runs
    # immediately) but well inside phase 1's compile wall, so the
    # notice fires at the phase-2 update — deterministically
    rng = random.Random(101)
    chaos = ChaosConfig(seed=101, maintenance=[
        {"after_s": 2.0, "slice_index": 0}])
    os.environ.update(chaos.env())

    ray_tpu.init(num_cpus=8, _num_initial_workers=4)
    try:
        ctrl = _ElasticStubController()
        provider = FakeSliceProvider(provider_config={"max_slices": 1})
        mgr = SliceManager(
            ctrl, provider,
            [SliceTypeConfig("pod", "2x4", {"CPU": 1})],
            idle_timeout_s=3600.0, drain_deadline_s=1.0)
        sid = mgr.acquire_slice("pod")
        host_ids = provider.internal_ids(sid)

        def snap():
            return {"demand": [], "slice_demand": [],
                    "busy_nodes": set(host_ids),
                    "alive_nodes": set(host_ids)}

        mgr.update(snap())

        trainer = ElasticTrainer(
            ParallelPlan(pp=S, n_microbatches=M), cfg,
            learning_rate=1e-3, slice_manager=mgr)
        losses = []

        # --- phase 1: warm steps (step 0 compiles), then a seeded
        # stage-actor kill landing mid-train-step: failure path
        for _ in range(pre_steps):
            losses.append(trainer.step(batch_d).loss)
        victim = trainer.program.pipeline.stages[
            rng.randrange(S)]
        threading.Timer(0.05, lambda: ray_tpu.kill(victim)).start()
        losses.append(trainer.step(batch_d).loss)  # absorbs the kill
        kill_reports = list(trainer.recoveries)
        steps_lost_kill = sum(r.steps_lost for r in kill_reports)
        kill_recovery_s = sum(r.total_s for r in kill_reports)

        # --- phase 2: provider maintenance notice drains the only
        # slice -> capacity 0 -> fold pp -> spmd from a live snapshot
        mgr.update(snap())     # chaos schedule fires, drain -> notice
        t_notice = time.perf_counter()
        for _ in range(post_steps):
            losses.append(trainer.step(batch_d).loss)
        notice_wall_s = time.perf_counter() - t_notice
        notice_reports = trainer.recoveries[len(kill_reports):]
        assert notice_reports, "maintenance notice never consumed"
        recovery_s = sum(r.total_s for r in notice_reports)
        steps_lost_notice = sum(r.steps_lost for r in notice_reports)
        folded_plan = trainer.plan.describe()
        assert trainer.plan.lowering == "spmd", trainer.plan

        # --- phase 3: capacity comes back -> regrow the grid
        deadline = time.monotonic() + 30
        while mgr.slices[sid].state != "RELEASED":
            assert time.monotonic() < deadline, "drain never released"
            time.sleep(0.2)
            mgr.update(snap())     # past drain_deadline_s -> release
        sid2 = mgr.acquire_slice("pod")
        assert sid2, "released capacity not re-acquirable"
        host_ids = provider.internal_ids(sid2)
        mgr.update(snap())
        trainer.regrow()
        regrow_report = trainer.recoveries[-1]
        assert trainer.plan.pp == S
        for _ in range(2):
            losses.append(trainer.step(batch_d).loss)

        # --- parity: the whole trajectory, interruptions and all,
        # matches an uninterrupted single-program run step for step
        ref_losses = _train_reference_losses(cfg, batch_d, len(losses))
        parity_all = max(abs(a - b)
                         for a, b in zip(losses, ref_losses))
        parity_post = max(
            abs(a - b) for a, b in zip(losses[-(post_steps + 2):],
                                       ref_losses[-(post_steps + 2):]))
        mgr.shutdown()
        provider.shutdown()
        trainer.shutdown()
    finally:
        ray_tpu.shutdown()

    detail = {
        "backend": jax.default_backend(),
        "chip": chip_spec().name,
        "n_stages": S,
        "n_microbatches": M,
        "model_params": cfg.num_params,
        "steps_total": len(losses),
        "parity_steps": post_steps,
        "loss_parity_abs": round(parity_post, 9),
        "loss_parity_all_abs": round(parity_all, 9),
        "steps_lost_kill": steps_lost_kill,
        "steps_lost_notice": steps_lost_notice,
        "steps_lost_max": max(steps_lost_kill, steps_lost_notice),
        "kill_recovery_s": round(kill_recovery_s, 4),
        "notice_recovery_s": round(recovery_s, 4),
        "notice_window_wall_s": round(notice_wall_s, 4),
        "regrow_s": round(regrow_report.total_s, 4),
        "folded_plan": folded_plan,
        "recoveries": [r.asdict() for r in
                       (kill_reports + notice_reports
                        + [regrow_report])],
    }
    print(json.dumps({
        "metric": "elastic_recovery_s",
        "value": round(recovery_s, 4),
        "unit": "s",
        "detail": detail,
    }))


# ------------------------------------------------------------- COLOCATE
# `python bench.py --colocate` measures the COLOCATE metric: a train
# and a serve fleet sharing one slice pool under a diurnal serve
# spike, arbitrated live by the SliceArbiter. The training side is a
# REAL ElasticTrainer (real fold/regrow wall-clock, real tokens/s,
# real loss-trajectory parity); the serve side is a deterministic
# fluid queue (arrivals vs per-slice service rate) whose gauges feed
# the arbiter, so the serve-capacity timeline — and therefore the TTFT
# record — is exactly the arbiter's borrow window. The static-
# partition baseline replays the SAME arrival trace with the serve
# fleet pinned to its own slice (no borrowing): the headline is spike
# p99 TTFT with arbitration, which must beat the static partition
# while training throughput degrades only to the folded grid (and
# recovers after the return). Gated by `tools/perf_gate.py --metric
# colocate` (COLOCATE_r*.json).


def _serve_queue_sim(ticks, dt_s, arrival_fn, capacity_fn,
                     service_per_slice=6.0, base_ttft_ms=50.0):
    """Deterministic fluid queue: per tick the backlog grows by
    arrivals minus drained capacity and every arriving request's TTFT
    is the backlog drain time at the CURRENT capacity. Returns
    (ttft_samples_ms weighted by arrivals, final_backlog)."""
    q = 0.0
    samples = []
    for i in range(ticks):
        t = i * dt_s
        lam = arrival_fn(t)
        c = max(1e-9, capacity_fn(t, q) * service_per_slice)
        q = max(0.0, q + (lam - c) * dt_s)
        ttft_ms = base_ttft_ms + (q / c) * 1000.0
        samples.extend([ttft_ms] * max(1, int(round(lam * dt_s))))
    return samples, q


def _p99(samples):
    s = sorted(samples)
    return s[min(len(s) - 1, int(len(s) * 0.99))]


def colocate_main(smoke: bool = False) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("RAY_TPU_JAX_PLATFORM",
                          os.environ.get("JAX_PLATFORMS", ""))

    import numpy as np

    import jax
    import ray_tpu
    from ray_tpu.autoscaler.arbiter import ArbiterPolicy, SliceArbiter
    from ray_tpu.autoscaler.node_provider import FakeSliceProvider
    from ray_tpu.autoscaler.slices import (RELEASED, UP, SliceManager,
                                           SliceTypeConfig)
    from ray_tpu.parallel.elastic import ElasticTrainer
    from ray_tpu.parallel.mesh import chip_spec
    from ray_tpu.parallel.plan import ParallelPlan

    on_tpu = jax.default_backend() == "tpu"
    cfg, batch, seq, _M, _S, _ = _pipeline_config(on_tpu, smoke)
    steps_phase = 2 if smoke else 5
    # the tail must cover the backlog drain (the borrowed window ends
    # with a queue that empties at ~10 req/s) plus ebb_s hysteresis
    calm_s, spike_s, tail_s = (4.0, 8.0, 14.0) if smoke \
        else (10.0, 20.0, 24.0)
    dt_s = 0.5
    lam_calm, lam_spike = 2.0, 20.0

    def arrivals(t):
        return lam_spike if calm_s <= t < calm_s + spike_s else lam_calm

    ids = np.array(jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size))
    batch_d = {"input_ids": ids,
               "loss_mask": np.ones((batch, seq), np.float32)}
    tokens_per_step = batch * seq

    ray_tpu.init(num_cpus=8, _num_initial_workers=4)
    try:
        ctrl = _ElasticStubController()
        provider = FakeSliceProvider(provider_config={"max_slices": 2})
        mgr = SliceManager(
            ctrl, provider,
            [SliceTypeConfig("pod", "2x4", {"CPU": 1})],
            idle_timeout_s=3600.0, drain_deadline_s=0.5)

        class _Clock:
            t = 1000.0

            def __call__(self):
                return self.t

        clock = _Clock()
        gauges = {"queue_depth": 0.0, "ttft_p99_ms": 100.0}
        arb = SliceArbiter(
            mgr,
            policy=ArbiterPolicy(
                queue_high=4.0, queue_low=1.0,
                ttft_p99_high_ms=2000.0, ttft_p99_low_ms=1000.0,
                sustain_s=2.0, ebb_s=4.0),
            gauges_fn=lambda: dict(gauges), now_fn=clock)
        train_sid = mgr.acquire_slice("pod")
        arb.claim(train_sid, owner="train-job", kind="train",
                  priority=0)
        clock.t += 0.1
        serve_sid = mgr.acquire_slice("pod")
        arb.claim(serve_sid, owner="serve-fleet", kind="serve",
                  priority=10)
        owned = {train_sid}
        arb.register_on_return(
            lambda info: owned.add(info["slice_id"]))

        def pump(busy=True):
            alive = [h for sid, i in mgr.slices.items()
                     if i.state != RELEASED
                     for h in provider.internal_ids(sid)]
            mgr.update({"demand": [], "slice_demand": [],
                        "busy_nodes": set(alive) if busy else set(),
                        "alive_nodes": set(alive)})

        pump()
        trainer = ElasticTrainer(
            ParallelPlan(dp=2), cfg, learning_rate=1e-3,
            telemetry_interval_s=0, slice_manager=mgr,
            slice_filter=lambda sid: sid in owned)
        losses = []

        def timed_steps(n):
            losses.append(trainer.step(batch_d).loss)  # warm/absorb
            t0 = time.perf_counter()
            for _ in range(n):
                losses.append(trainer.step(batch_d).loss)
            return n / (time.perf_counter() - t0)

        # --- phase A: full-grid training rate before the spike
        steps_s_full = timed_steps(steps_phase)

        # --- arbitrated serve-capacity timeline: the fluid queue
        # drives the REAL arbiter tick by tick; serve capacity follows
        # the borrow window the arbiter actually opens. The sim is
        # interleaved with the training record so each training
        # measurement sees exactly the capacity state a colocated
        # cluster would: full grid -> folded while borrowed -> regrown
        # after the return.
        ttft_arb = []
        state = {"q": 0.0, "i": 0}
        ticks = int((calm_s + spike_s + tail_s) / dt_s)

        def run_ticks(stop_on=None):
            """Advance the sim until `stop_on` appears in the
            arbiter's actions (or the trace ends). Returns the sim
            time of the stopping action, else None."""
            while state["i"] < ticks:
                t = state["i"] * dt_s
                state["i"] += 1
                lam = arrivals(t)
                c = (1 + len(arb.borrowed)) * 6.0
                state["q"] = max(0.0, state["q"] + (lam - c) * dt_s)
                ttft_ms = 50.0 + (state["q"] / c) * 1000.0
                ttft_arb.extend(
                    [ttft_ms] * max(1, int(round(lam * dt_s))))
                gauges["queue_depth"] = state["q"]
                gauges["ttft_p99_ms"] = ttft_ms
                clock.t += dt_s
                out = arb.update()
                if stop_on and any(a.startswith(stop_on)
                                   for a in out["actions"]):
                    return t
            return None

        borrow_at_s = run_ticks(stop_on="preempt")
        assert borrow_at_s is not None, "spike never tripped the arbiter"
        pump(busy=False)           # drain completes, slice frees

        # --- phase B: the preempt's drain notice folds dp=2 -> dp=1
        # at the next step boundary; record the fold step wall-clock
        # and the folded-grid rate
        t0 = time.perf_counter()
        losses.append(trainer.step(batch_d).loss)
        fold_step_s = time.perf_counter() - t0
        assert trainer.plan.dp == 1, trainer.plan
        steps_s_folded = timed_steps(steps_phase)

        return_at_s = run_ticks(stop_on="return")
        assert return_at_s is not None, "ebb never returned the slice"
        pump()                     # replacement slice comes UP

        # --- phase C: the next step boundary auto-regrows the grid
        t0 = time.perf_counter()
        losses.append(trainer.step(batch_d).loss)
        regrow_step_s = time.perf_counter() - t0
        assert trainer.plan.dp == 2, trainer.plan
        steps_s_regrown = timed_steps(steps_phase)
        run_ticks()                # drain the rest of the trace
        spike_samples = [s for s in ttft_arb if s > 50.0] or ttft_arb
        arb_p99 = _p99(ttft_arb)

        # --- static-partition baseline: same trace, serve pinned to
        # its own slice, training never interrupted
        ttft_static, _ = _serve_queue_sim(
            ticks, dt_s, arrivals, lambda t, q: 1.0)
        static_p99 = _p99(ttft_static)

        recoveries = list(trainer.recoveries)
        fold_recovery_s = sum(r.total_s for r in recoveries
                              if r.trigger == "notice")
        regrow_s = sum(r.total_s for r in recoveries
                       if r.trigger == "regrow")
        steps_lost = trainer.steps_lost_total

        ref_losses = _train_reference_losses(cfg, batch_d, len(losses))
        parity = max(abs(a - b) for a, b in zip(losses, ref_losses))

        arb_stats = {"preemptions": arb.preemptions,
                     "returns": arb.returns}
        mgr.shutdown()
        provider.shutdown()
        trainer.shutdown()
    finally:
        ray_tpu.shutdown()

    detail = {
        "backend": jax.default_backend(),
        "chip": chip_spec().name,
        "model_params": cfg.num_params,
        "steps_total": len(losses),
        "loss_parity_abs": round(parity, 9),
        "steps_lost": steps_lost,
        "static_spike_ttft_p99_ms": round(static_p99, 3),
        "ttft_p99_improvement": round(static_p99 / max(arb_p99, 1e-9),
                                      3),
        "spike_ttft_max_ms": round(max(spike_samples), 3),
        "borrow_at_s": borrow_at_s,
        "return_at_s": return_at_s,
        "borrowed_sim_s": round(return_at_s - borrow_at_s, 3),
        "train_tokens_per_s_full": round(
            steps_s_full * tokens_per_step, 2),
        "train_tokens_per_s_folded": round(
            steps_s_folded * tokens_per_step, 2),
        "train_tokens_per_s_regrown": round(
            steps_s_regrown * tokens_per_step, 2),
        "fold_step_s": round(fold_step_s, 4),
        "fold_recovery_s": round(fold_recovery_s, 4),
        "regrow_step_s": round(regrow_step_s, 4),
        "regrow_s": round(regrow_s, 4),
        "arbiter": arb_stats,
        "recoveries": [r.asdict() for r in recoveries],
    }
    print(json.dumps({
        "metric": "colocate_spike_ttft_p99_ms",
        "value": round(arb_p99, 3),
        "unit": "ms",
        "detail": detail,
    }))


def rl_main(smoke: bool = False) -> None:
    """Closed-loop RLHF record (``--rl``): N PPO rounds of serve-engine
    rollouts feeding a 2-learner sharded streaming group with in-flight
    int8 weight republish after every gradient round. Headline: rollout
    tokens/s through the closed loop. The detail rows the gate reads:
    learner rounds/s, weight-sync staleness p50/p99 (policy-version lag
    observed at rollout admission), the rollout prefix-cache hit rate
    (every request shares the system prompt — the radix trie must keep
    paying), int8 wire compression, and ``decode_stall_s`` which must
    be EXACTLY 0 — the swap is a step-boundary pointer exchange, never
    a drain."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("RAY_TPU_JAX_PLATFORM",
                          os.environ.get("JAX_PLATFORMS", ""))

    import jax
    import ray_tpu
    from ray_tpu.parallel.mesh import chip_spec
    from ray_tpu.rlhf import RLHFConfig, RLHFTrainer

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model = dict(vocab_size=2048, d_model=256, n_layers=4,
                     n_heads=8, head_dim=32, d_ff=1024,
                     max_seq_len=256, rotary_dim=32,
                     dtype="bfloat16", remat_policy="none")
    else:
        model = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                     head_dim=16, d_ff=128, max_seq_len=128,
                     rotary_dim=16, dtype="float32",
                     remat_policy="none")
    rounds = 2 if smoke else 4
    cfg = RLHFConfig(
        placement="anakin",
        num_learners=2,
        num_engines=1 if smoke else 2,
        rollouts_per_round=6 if smoke else 12,
        max_new_tokens=8 if smoke else 16,
        system_prompt=tuple(range(2, 50)),
        prompt_len=64,
        minibatch_size=2,
        sync_every_updates=1,
        model=model,
        engine=dict(decode_slots=4, kv_block_size=4, prefill_chunk=16))

    ray_tpu.init(num_cpus=8, _num_initial_workers=4)
    try:
        trainer = RLHFTrainer(cfg)
        trainer.train_round()     # warm the jit caches off the record
        t0 = time.perf_counter()
        history = trainer.train(rounds)
        wall = time.perf_counter() - t0
        rstats = trainer.rollout.stats()
        pstats = trainer.publisher.stats()
        warm_tokens = trainer.history[0]["rollout_tokens"]
        tokens = rstats["tokens_total"] - warm_tokens
        updates = sum(m.get("stream_updates", 0.0) for m in history)
        last = history[-1]
        trainer.shutdown()
    finally:
        ray_tpu.shutdown()

    detail = {
        "backend": jax.default_backend(),
        "chip": chip_spec().name,
        "placement": cfg.placement,
        "slice_strategy": cfg.slice_strategy,
        "num_learners": cfg.num_learners,
        "num_engines": cfg.num_engines,
        "rounds": rounds,
        "trajectories": rstats["trajectories"],
        "rollout_tokens": tokens,
        "learner_steps_per_s": round(updates / wall, 3),
        "learners_used": last.get("learners_used"),
        "weight_syncs": pstats["publishes"],
        "weight_version": rstats["weight_version"],
        "wire_compression": pstats["compression"],
        "staleness_p50": rstats["staleness_p50"],
        "staleness_p99": rstats["staleness_p99"],
        "staleness_max": rstats["staleness_max"],
        "decode_stall_s": rstats["sync_stall_s"],
        "weight_swap_wall_s": rstats["weight_swap_wall_s"],
        "prefix_hit_rate": rstats["prefix_hit_rate"],
        "total_loss": last.get("total_loss"),
        "approx_kl": last.get("approx_kl"),
    }
    print(json.dumps({
        "metric": "rl_rollout_tokens_per_s",
        "value": round(tokens / wall, 2),
        "unit": "tokens/s",
        "detail": detail,
    }))


if __name__ == "__main__":
    import sys
    if "--pipeline" in sys.argv:
        pipeline_main(smoke="--smoke" in sys.argv)
    elif "--data" in sys.argv:
        data_main(smoke="--smoke" in sys.argv)
    elif "--elastic" in sys.argv:
        elastic_main(smoke="--smoke" in sys.argv)
    elif "--colocate" in sys.argv:
        colocate_main(smoke="--smoke" in sys.argv)
    elif "--rl" in sys.argv:
        rl_main(smoke="--smoke" in sys.argv)
    else:
        main()
