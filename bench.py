"""Headline benchmark: GPT-J-architecture training throughput + MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): the reference's GPT-J-6B DeepSpeed ZeRO-3
fine-tune ran at 146 tok/s per T4 GPU — ~8.3% MFU against the T4's 65
TFLOP/s fp16 peak (flops/token ~= 6N + attention ~= 3.7e10 for GPT-J-6B
at seq 512). We report model FLOPs utilization of a GPT-J-block-style
model training on this chip; ``vs_baseline`` is our MFU over the
reference's 8.3%.

On TPU the model is sized to the single benchmark chip (same architecture
as the gptj-6b flagship, fewer layers/width so full AdamW state fits one
chip's HBM); on CPU a tiny config keeps the harness runnable anywhere.
"""

from __future__ import annotations

import json
import time

BASELINE_MFU_PCT = 8.3


def _measure_mfu(cfg, batch: int, seq: int, steps: int,
                 warmup: int) -> dict:
    """Train-step MFU of one config at one sequence length."""
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import make_train_step
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh, chip_spec

    devices = jax.devices()[:1]
    mesh = build_mesh(MeshSpec(), devices)
    bundle = make_train_step(cfg, mesh, learning_rate=1e-4)
    state = bundle.init(seed=0)
    ids = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                             cfg.vocab_size)
    batch_d = {"input_ids": ids,
               "loss_mask": jnp.ones((batch, seq), jnp.float32)}

    def sync(state, metrics):
        # Host-side scalar fetches of values that depend on the FULL step
        # (optimizer update included): the state's step counter is only
        # ready once donation/apply finished, and grad_norm depends on the
        # backward pass. (block_until_ready has proven unreliable on
        # experimental tunnel platforms.)
        int(state["step"])
        float(metrics["grad_norm"])
        return float(metrics["loss"])

    for _ in range(warmup):
        state, metrics = bundle.step(state, batch_d)
    sync(state, metrics)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = bundle.step(state, batch_d)
    final_loss = sync(state, metrics)
    dt = time.perf_counter() - t0

    tokens_per_s = batch * seq * steps / dt
    achieved = tokens_per_s * cfg.flops_per_token(seq)
    mfu_pct = 100.0 * achieved / chip_spec().bf16_flops
    return {"mfu_pct": round(mfu_pct, 2),
            "tokens_per_s": round(tokens_per_s, 1),
            "loss": final_loss}


def main() -> None:
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import TransformerConfig
    from ray_tpu.parallel.mesh import chip_spec

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32768, d_model=2048, n_layers=10, n_heads=16,
            head_dim=128, d_ff=8192, max_seq_len=1024, rotary_dim=64,
            block_style="gptj", remat=True)
        batch, seq, steps, warmup = 4, 1024, 10, 2
    else:
        cfg = TransformerConfig(
            vocab_size=1024, d_model=128, n_layers=2, n_heads=4,
            head_dim=32, d_ff=512, max_seq_len=256, rotary_dim=16,
            block_style="gptj", dtype=jnp.float32, remat=False)
        batch, seq, steps, warmup = 4, 256, 4, 1

    head = _measure_mfu(cfg, batch, seq, steps, warmup)
    mfu_pct = head["mfu_pct"]

    detail = {
        "tokens_per_s": head["tokens_per_s"],
        "model_params": cfg.num_params,
        "backend": jax.default_backend(),
        "chip": chip_spec().name,
        "loss": head["loss"],
        "seq1024_mfu_pct": mfu_pct,
    }
    if on_tpu:
        # Long-sequence end-to-end MFU (VERDICT r4 #7): the SAME model
        # at seq 4096 with remat, where the Pallas flash backward is the
        # attention-gradient path — what the 1.29x kernel speedup buys
        # in train MFU, not just kernel ms. Same tokens/step as the
        # headline (batch 1 x 4096).
        import dataclasses
        cfg4k = dataclasses.replace(cfg, max_seq_len=4096)
        try:
            detail["seq4096"] = _measure_mfu(cfg4k, 1, 4096, 6, 2)
        except Exception as e:  # noqa: BLE001
            detail["seq4096"] = {"error": str(e)[:120]}
        try:
            detail["flash_bwd_4k"] = _flash_bwd_compare(jax, jnp)
        except Exception as e:  # noqa: BLE001
            detail["flash_bwd_4k"] = {"error": str(e)[:120]}

    print(json.dumps({
        "metric": "gptj_train_mfu_single_chip",
        "value": round(mfu_pct, 2),
        "unit": "%MFU",
        "vs_baseline": round(mfu_pct / BASELINE_MFU_PCT, 3),
        "detail": detail,
    }))


def _flash_bwd_compare(jax, jnp, seq: int = 4096) -> dict:
    """Long-sequence attention-gradient timing: the Pallas dq/dk/dv
    kernels vs the lax.scan backward they replaced (VERDICT r3 weak #7:
    the XLA backward caps training MFU at long seq)."""
    from ray_tpu.ops.flash_attention import flash_attention

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 16, seq, 128),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), q.shape, jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.bfloat16)

    out = {}
    for mode in ("pallas", "xla"):
        @jax.jit
        def g(q, k, v, _mode=mode):
            def f(q, k, v):
                return jnp.sum(flash_attention(
                    q, k, v, causal=True, backward=_mode
                ).astype(jnp.float32))
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        r = g(q, k, v)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(8):
            r = g(q, k, v)
        jax.block_until_ready(r)
        out[mode + "_ms"] = round((time.perf_counter() - t0) / 8 * 1e3, 2)
    out["speedup"] = round(out["xla_ms"] / out["pallas_ms"], 2)
    return out


if __name__ == "__main__":
    main()
