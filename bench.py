"""Headline benchmark: GPT-J-architecture training throughput + MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Baseline (BASELINE.md): the reference's GPT-J-6B DeepSpeed ZeRO-3
fine-tune ran at 146 tok/s per T4 GPU — ~8.3% MFU against the T4's 65
TFLOP/s fp16 peak (flops/token ~= 6N + attention ~= 3.7e10 for GPT-J-6B
at seq 512). We report model FLOPs utilization of a GPT-J-block-style
model training on this chip; ``vs_baseline`` is our MFU over the
reference's 8.3%.

On TPU the model is sized to the single benchmark chip (same architecture
as the gptj-6b flagship, fewer layers/width so full AdamW state fits one
chip's HBM); on CPU a tiny config keeps the harness runnable anywhere.

The detail JSON is attributable: it records the chosen remat policy (the
bench measures the candidate policies and keeps the winner), the fused-CE
chunk size, the (autotuned) flash block sizes, a per-phase breakdown
(compile time separated from steady state; fwd/bwd/opt split via a 3-way
jit split run once), and — when more than one device is visible — an
FSDP train-step MFU over all local devices (the MULTICHIP metric).

Env overrides: RAY_TPU_BENCH_REMAT (comma list of policies to try, e.g.
"dots,full"), RAY_TPU_BENCH_CE_CHUNK (fused-CE chunk size; 0 = unfused),
RAY_TPU_BENCH_MC_VARIANTS (comma list restricting the multichip
grad-transport/weight-update matrix, e.g. "fp32_replicated,int8_sharded").

`python bench.py --pipeline [--smoke]` runs the PIPELINE metric instead:
MPMD actor pipeline (1F1B, streamed activations) vs serial actors vs
single-program SPMD GPipe — tokens/s, measured + analytic bubble
fractions, and MPMD-vs-single-program loss parity. See pipeline_main.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

BASELINE_MFU_PCT = 8.3


def _sync(state, metrics):
    # Host-side scalar fetches of values that depend on the FULL step
    # (optimizer update included): the state's step counter is only
    # ready once donation/apply finished, and grad_norm depends on the
    # backward pass. (block_until_ready has proven unreliable on
    # experimental tunnel platforms.)
    int(state["step"])
    float(metrics["grad_norm"])
    return float(metrics["loss"])


def _measure_mfu(cfg, batch: int, seq: int, steps: int, warmup: int,
                 devices=None, phase_split: bool = False,
                 grad_transport: str = "fp32",
                 shard_weight_update: bool = False) -> dict:
    """Train-step MFU of one config at one sequence length.

    ``devices``: None = first local device; a list enables the FSDP
    multichip measurement (mesh fsdp=len(devices)).
    ``grad_transport`` / ``shard_weight_update`` select the gradient
    communication path (see ``models.training.make_train_step``).
    """
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import make_train_step
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh, chip_spec

    devices = devices or jax.devices()[:1]
    n_dev = len(devices)
    spec = MeshSpec(fsdp=n_dev) if n_dev > 1 else MeshSpec()
    mesh = build_mesh(spec, devices)
    bundle = make_train_step(cfg, mesh, learning_rate=1e-4,
                             grad_transport=grad_transport,
                             shard_weight_update=shard_weight_update)
    state = bundle.init(seed=0)
    ids = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                             cfg.vocab_size)
    batch_d = {"input_ids": ids,
               "loss_mask": jnp.ones((batch, seq), jnp.float32)}

    t0 = time.perf_counter()
    state, metrics = bundle.step(state, batch_d)
    _sync(state, metrics)
    compile_s = time.perf_counter() - t0
    for _ in range(max(warmup - 1, 0)):
        state, metrics = bundle.step(state, batch_d)
    _sync(state, metrics)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = bundle.step(state, batch_d)
    final_loss = _sync(state, metrics)
    dt = time.perf_counter() - t0

    tokens_per_s = batch * seq * steps / dt
    achieved = tokens_per_s * cfg.flops_per_token(seq)
    mfu_pct = 100.0 * achieved / (chip_spec().bf16_flops * n_dev)
    out = {"mfu_pct": round(mfu_pct, 2),
           "tokens_per_s": round(tokens_per_s, 1),
           "step_ms": round(dt / steps * 1e3, 2),
           "loss": final_loss,
           "compile_s": round(compile_s, 2)}
    if phase_split:
        out["phases_ms"] = _phase_breakdown(
            cfg, bundle, state, batch_d, step_ms=dt / steps * 1e3)
    return out


def _phase_breakdown(cfg, bundle, state, batch_d, step_ms,
                     iters: int = 5) -> dict:
    """fwd/bwd/opt attribution via a 3-way jit split run once: time a
    forward-only jit and a value_and_grad jit; bwd = grad - fwd, opt =
    full step - grad. (Separate programs, so the split is approximate but
    attributable — XLA can't overlap across these boundaries.)"""
    import jax
    from ray_tpu.models.transformer import lm_loss

    def loss_of(p, b):
        return lm_loss(cfg, p, b, mesh=bundle.mesh, rules=bundle.rules)[0]

    fwd = jax.jit(loss_of)
    fwdbwd = jax.jit(jax.value_and_grad(loss_of))

    def time_it(fn, fetch):
        r = fn(state["params"], batch_d)
        fetch(r)                               # compile + settle
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(state["params"], batch_d)
        fetch(r)
        return (time.perf_counter() - t0) / iters * 1e3

    fwd_ms = time_it(fwd, lambda r: float(r))
    grad_ms = time_it(
        fwdbwd, lambda r: float(r[1]["final_norm"]["scale"][0]))
    return {"fwd_ms": round(fwd_ms, 2),
            "bwd_ms": round(max(grad_ms - fwd_ms, 0.0), 2),
            "opt_ms": round(max(step_ms - grad_ms, 0.0), 2),
            "step_ms": round(step_ms, 2)}


def _pick_remat_policy(cfg, batch, seq, steps, warmup):
    """Measure the candidate remat policies and keep the winner (its
    measurement IS the headline — no re-measure). The phase breakdown
    rides the first candidate that succeeds.

    OOM/compile failures just disqualify a candidate (e.g. "dots" when
    the saved matmul outputs don't fit HBM) — the bench must always
    produce a number.
    """
    policies = [p.strip() for p in os.environ.get(
        "RAY_TPU_BENCH_REMAT", "dots,full").split(",") if p.strip()]
    results, best = {}, None
    split_done = False
    for policy in policies:
        c = dataclasses.replace(cfg, remat=None, remat_policy=policy)
        try:
            r = _measure_mfu(c, batch, seq, steps, warmup,
                             phase_split=not split_done)
        except Exception as e:  # noqa: BLE001
            results[policy] = {"error": str(e)[:120]}
            continue
        split_done = True
        results[policy] = r
        if best is None or r["mfu_pct"] > results[best]["mfu_pct"]:
            best = policy
    if best is None:  # every candidate failed — surface the errors
        raise RuntimeError(f"no remat policy succeeded: {results}")
    return best, results


def main() -> None:
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import TransformerConfig
    from ray_tpu.ops import autotune_flash_blocks
    from ray_tpu.parallel.mesh import chip_spec

    on_tpu = jax.default_backend() == "tpu"
    ce_chunk = int(os.environ.get("RAY_TPU_BENCH_CE_CHUNK", "512"))
    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32768, d_model=2048, n_layers=10, n_heads=16,
            head_dim=128, d_ff=8192, max_seq_len=1024, rotary_dim=64,
            block_style="gptj", ce_chunk_size=ce_chunk)
        batch, seq, steps, warmup = 4, 1024, 10, 2
    else:
        cfg = TransformerConfig(
            vocab_size=1024, d_model=128, n_layers=2, n_heads=4,
            head_dim=32, d_ff=512, max_seq_len=256, rotary_dim=16,
            block_style="gptj", dtype=jnp.float32, remat=False,
            ce_chunk_size=ce_chunk)
        batch, seq, steps, warmup = 4, 256, 4, 1

    if on_tpu:
        # One-shot flash block autotune (cached per chip/seq/head_dim),
        # then measure candidate remat policies; the winner's own
        # measurement is the headline.
        bq, bk = autotune_flash_blocks(seq, cfg.head_dim, batch=batch,
                                       heads=cfg.n_heads)
        cfg = dataclasses.replace(cfg, attn_block_q=bq, attn_block_k=bk)
        policy, policy_results = _pick_remat_policy(
            cfg, batch, seq, steps, warmup)
        cfg = dataclasses.replace(cfg, remat=None, remat_policy=policy)
        head = policy_results[policy]
    else:
        policy = cfg.resolved_remat_policy
        policy_results = None
        head = _measure_mfu(cfg, batch, seq, steps, warmup,
                            phase_split=True)
    mfu_pct = head["mfu_pct"]

    detail = {
        "tokens_per_s": head["tokens_per_s"],
        "model_params": cfg.num_params,
        "backend": jax.default_backend(),
        "chip": chip_spec().name,
        "loss": head["loss"],
        "seq1024_mfu_pct": mfu_pct,
        "compile_s": head["compile_s"],
        "phases_ms": head.get("phases_ms") or next(
            (r["phases_ms"] for r in (policy_results or {}).values()
             if isinstance(r, dict) and r.get("phases_ms")), None),
        "remat_policy": policy,
        "ce_chunk_size": cfg.ce_chunk_size,
        "flash_blocks": [cfg.attn_block_q, cfg.attn_block_k],
    }
    if policy_results:
        detail["remat_policies"] = policy_results

    if on_tpu:
        # Long-sequence end-to-end MFU: the SAME model at seq 4096,
        # where the chunked CE and the Pallas flash backward dominate
        # the memory/compute picture. Same tokens/step as the headline
        # (batch 1 x 4096).
        bq4, bk4 = autotune_flash_blocks(4096, cfg.head_dim, batch=1,
                                         heads=cfg.n_heads)
        cfg4k = dataclasses.replace(cfg, max_seq_len=4096,
                                    attn_block_q=bq4, attn_block_k=bk4)
        try:
            detail["seq4096"] = _measure_mfu(cfg4k, 1, 4096, 6, 2)
            detail["seq4096"]["flash_blocks"] = [bq4, bk4]
        except Exception as e:  # noqa: BLE001
            try:  # policy fallback: "full" always fits
                cfg4k = dataclasses.replace(cfg4k, remat_policy="full")
                detail["seq4096"] = _measure_mfu(cfg4k, 1, 4096, 6, 2)
                detail["seq4096"]["remat_policy"] = "full"
            except Exception as e2:  # noqa: BLE001
                detail["seq4096"] = {"error": str(e)[:120],
                                     "error_full": str(e2)[:120]}
        try:
            detail["flash_bwd_4k"] = _flash_bwd_compare(jax, jnp)
        except Exception as e:  # noqa: BLE001
            detail["flash_bwd_4k"] = {"error": str(e)[:120]}

    if len(jax.devices()) > 1:
        detail["multichip"] = _measure_multichip(
            cfg, batch, seq, max(steps // 2, 2), warmup,
            single_tokens_per_s=head["tokens_per_s"])

    print(json.dumps({
        "metric": "gptj_train_mfu_single_chip",
        "value": round(mfu_pct, 2),
        "unit": "%MFU",
        "vs_baseline": round(mfu_pct / BASELINE_MFU_PCT, 3),
        "detail": detail,
    }))


# ------------------------------------------------------------ PIPELINE
# `python bench.py --pipeline` measures the PIPELINE metric: the
# 2-stage MPMD actor pipeline (parallel/mpmd_pipeline.py) driven by the
# 1F1B scheduler vs (a) the same actors driven serially with no overlap
# and (b) the single-program SPMD GPipe (ops/pipeline.py) at equal
# microbatches on local devices. Reports tokens/s, the MEASURED bubble
# fraction of both actor modes, the ANALYTIC GPipe bubble
# (S-1)/(M+S-1) next to them, and the forward/loss parity of the MPMD
# split against the single-program model. Gated by
# `tools/perf_gate.py --metric pipeline` (PIPELINE_r*.json).


def _pipeline_config(on_tpu: bool, smoke: bool):
    import jax.numpy as jnp
    from ray_tpu.models import TransformerConfig
    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32768, d_model=1024, n_layers=8, n_heads=8,
            head_dim=128, d_ff=4096, max_seq_len=1024, rotary_dim=64,
            block_style="gptj", ce_chunk_size=512)
        return cfg, 8, 1024, 4, 2, 6   # batch, seq, microbatches, S, steps
    cfg = TransformerConfig(
        vocab_size=1024, d_model=128, n_layers=4, n_heads=4,
        head_dim=32, d_ff=512, max_seq_len=256, rotary_dim=16,
        block_style="gptj", dtype=jnp.float32, remat=False,
        ce_chunk_size=128)
    if smoke:
        return cfg, 4, 64, 2, 2, 2
    return cfg, 8, 128, 4, 2, 4


def _measure_mpmd(pipe, batch_d, steps: int) -> dict:
    """Steady-state tokens/s + measured bubble of an MPMDPipeline
    (first step is the compile step, excluded)."""
    res = pipe.step(batch_d)          # compile
    t0 = time.perf_counter()
    bubbles = []
    for _ in range(steps):
        res = pipe.step(batch_d)
        bubbles.append(res.bubble_fraction)
    dt = time.perf_counter() - t0
    b, s = batch_d["input_ids"].shape
    return {"tokens_per_s": round(b * s * steps / dt, 1),
            "step_ms": round(dt / steps * 1e3, 2),
            "bubble_fraction": round(sum(bubbles) / len(bubbles), 4),
            "loss": res.loss,
            "stage_busy_ms": [round(st["busy_s"] * 1e3, 2)
                              for st in res.stage_stats]}


def _measure_spmd_gpipe(cfg, batch: int, seq: int, n_microbatches: int,
                        n_stages: int, steps: int) -> dict:
    """The single-program GPipe comparison: embed + pipeline_apply over
    a pp mesh + fused head loss, fwd+bwd via value_and_grad — same
    model, same microbatches, one shared compile."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ray_tpu.models.transformer import (
        init_params, run_layers, stage_layer_ranges, stage_loss,
        _final_norm)
    from ray_tpu.ops.pipeline import pipeline_apply, stack_stage_params

    devices = jax.devices()[:n_stages]
    if len(devices) < n_stages:
        return {"error": f"needs {n_stages} local devices"}
    mesh = Mesh(np.array(devices), ("pp",))
    params = init_params(cfg, jax.random.PRNGKey(0))
    ranges = stage_layer_ranges(cfg.n_layers, n_stages)
    stacked = stack_stage_params([
        jax.tree.map(lambda a: a[lo:hi], params["layers"])
        for lo, hi in ranges])

    def stage_fn(lp, x):
        return run_layers(cfg, lp, x)[0].astype(x.dtype)

    def loss_fn(p, ids, mask):
        x = jnp.take(p["embed"], ids, axis=0).astype(cfg.dtype)
        x = pipeline_apply(stage_fn, p["stacked"], x, mesh,
                           n_microbatches)
        x = _final_norm(cfg, p, x)
        tail = {"lm_head": p["lm_head"]}
        return stage_loss(cfg, tail, x, ids, mask)[0]

    p = {"embed": params["embed"], "stacked": stacked,
         "final_norm": params["final_norm"],
         "lm_head": params["lm_head"]}
    step = jax.jit(jax.value_and_grad(loss_fn))
    ids = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                             cfg.vocab_size)
    mask = jnp.ones((batch, seq), jnp.float32)
    loss, grads = step(p, ids, mask)
    jax.block_until_ready(grads)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, grads = step(p, ids, mask)
    jax.block_until_ready(grads)
    dt = time.perf_counter() - t0
    return {"tokens_per_s": round(batch * seq * steps / dt, 1),
            "step_ms": round(dt / steps * 1e3, 2),
            "loss": float(loss)}


def pipeline_main(smoke: bool = False) -> None:
    # the SPMD comparison needs >= 2 local devices; on CPU force the
    # virtual split BEFORE jax initializes its backend
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("RAY_TPU_JAX_PLATFORM",
                          os.environ.get("JAX_PLATFORMS", ""))

    import numpy as np

    import jax
    import ray_tpu
    from ray_tpu.models.transformer import init_params, lm_loss
    from ray_tpu.parallel.mpmd_pipeline import (
        MPMDPipeline, analytic_gpipe_bubble)
    from ray_tpu.parallel.mesh import chip_spec
    from ray_tpu.util.state import list_task_events

    on_tpu = jax.default_backend() == "tpu"
    cfg, batch, seq, M, S, steps = _pipeline_config(on_tpu, smoke)
    ids = np.array(jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size))
    batch_d = {"input_ids": ids,
               "loss_mask": np.ones((batch, seq), np.float32)}

    ray_tpu.init(num_cpus=max(2 * S + 2, 6),
                 _num_initial_workers=S + 1)
    try:
        pipe = MPMDPipeline(cfg, n_stages=S, n_microbatches=M, seed=0)
        mpmd = _measure_mpmd(pipe, batch_d, steps)
        serial = MPMDPipeline(cfg, n_stages=S, n_microbatches=M,
                              seed=0, serial=True)
        ser = _measure_mpmd(serial, batch_d, max(steps // 2, 1))
        # forward/loss parity vs the single-program model (exact same
        # seed -> bit-identical weights; must agree to <= 1e-5)
        ref_loss = float(lm_loss(
            cfg, init_params(cfg, jax.random.PRNGKey(0)), batch_d)[0])
        parity = abs(ref_loss - mpmd["loss"])
        spmd = _measure_spmd_gpipe(cfg, batch, seq, M, S, steps)
        ticks = len(list_task_events(filters=[("ev", "=", "STAGE_TICK")]))
    finally:
        ray_tpu.shutdown()

    detail = {
        "backend": jax.default_backend(),
        "chip": chip_spec().name,
        "n_stages": S,
        "n_microbatches": M,
        "model_params": cfg.num_params,
        "mpmd_1f1b": mpmd,
        "serial": ser,
        "spmd_gpipe": spmd,
        "analytic_gpipe_bubble": round(analytic_gpipe_bubble(S, M), 4),
        "loss_parity_abs": round(parity, 9),
        "single_program_loss": ref_loss,
        "stage_tick_events": ticks,
    }
    print(json.dumps({
        "metric": "pipeline_tokens_per_s",
        "value": mpmd["tokens_per_s"],
        "unit": "tok/s",
        "vs_serial": round(mpmd["tokens_per_s"]
                           / max(ser["tokens_per_s"], 1e-9), 3),
        "detail": detail,
    }))


MULTICHIP_VARIANTS = (("fp32", False), ("int8", False),
                      ("fp32", True), ("int8", True))


def _measure_multichip(cfg, batch: int, seq: int, steps: int, warmup: int,
                       single_tokens_per_s: float) -> dict:
    """FSDP train-step MFU over all local devices (MULTICHIP metric),
    measured for the gradient-transport x weight-update matrix:
    fp32 vs int8 grad transport, replicated vs cross-replica-sharded
    weight update. Same per-device token load as the headline.

    Each variant carries a comm/compute split: compute is the
    single-chip step time at the same per-device load (from the headline
    measurement), comm is the multichip step-time excess over it —
    attributable, since the only thing the multichip step adds is the
    gradient/param communication the variant is designed to shrink.

    Env override: RAY_TPU_BENCH_MC_VARIANTS (comma list like
    "fp32_replicated,int8_sharded") restricts the matrix.
    """
    import jax

    n = len(jax.devices())
    single_step_ms = batch * seq / single_tokens_per_s * 1e3
    want = os.environ.get("RAY_TPU_BENCH_MC_VARIANTS")
    want = {v.strip() for v in want.split(",")} if want else None
    variants = {}
    for gt, swu in MULTICHIP_VARIANTS:
        name = f"{gt}_{'sharded' if swu else 'replicated'}"
        if want is not None and name not in want:
            continue
        try:
            v = _measure_mfu(cfg, batch * n, seq, steps, warmup,
                             devices=jax.devices(), grad_transport=gt,
                             shard_weight_update=swu)
            v["comm_split_ms"] = {
                "compute_ms": round(single_step_ms, 2),
                "comm_ms": round(max(v["step_ms"] - single_step_ms, 0.0),
                                 2)}
        except Exception as e:  # noqa: BLE001
            v = {"error": str(e)[:120]}
        variants[name] = v
    ok = {k: v for k, v in variants.items() if "mfu_pct" in v}
    if not ok:
        return {"n_devices": n, "variants": variants,
                "error": "no multichip variant succeeded"}
    # Headline multichip fields stay the fp32 replicated baseline (the
    # pre-existing metric shape); the matrix rides in "variants".
    mc = dict(ok.get("fp32_replicated") or next(iter(ok.values())))
    mc["n_devices"] = n
    mc["best_variant"] = max(ok, key=lambda k: ok[k]["mfu_pct"])
    mc["variants"] = variants
    return mc


def _flash_bwd_compare(jax, jnp, seq: int = 4096) -> dict:
    """Long-sequence attention-gradient timing: the Pallas dq/dk/dv
    kernels (with the fused delta-precompute kernel and autotuned block
    sizes) vs the lax.scan backward they replaced."""
    from ray_tpu.ops.flash_attention import flash_attention

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 16, seq, 128),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), q.shape, jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.bfloat16)

    out = {}
    for mode in ("pallas", "xla"):
        @jax.jit
        def g(q, k, v, _mode=mode):
            def f(q, k, v):
                return jnp.sum(flash_attention(
                    q, k, v, causal=True, backward=_mode
                ).astype(jnp.float32))
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        r = g(q, k, v)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(8):
            r = g(q, k, v)
        jax.block_until_ready(r)
        out[mode + "_ms"] = round((time.perf_counter() - t0) / 8 * 1e3, 2)
    out["speedup"] = round(out["xla_ms"] / out["pallas_ms"], 2)
    return out


if __name__ == "__main__":
    import sys
    if "--pipeline" in sys.argv:
        pipeline_main(smoke="--smoke" in sys.argv)
    else:
        main()
