"""Workflow execution + storage.

Reference: ``python/ray/workflow/api.py`` (run/resume/get_output),
``workflow_storage.py:229`` (checkpointed task results keyed by
workflow_id + task_id), ``task_executor.py:50``. Execution walks the
DAG bottom-up; each FunctionNode gets a deterministic task id from its
topological position, its result is checkpointed after the remote task
finishes, and a cached result short-circuits re-execution on
resume/re-run.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag.nodes import DAGNode, FunctionNode, _ExecutionContext

_storage_dir: Optional[str] = None
_async_pool = None

RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"


def init_storage(path: str) -> None:
    global _storage_dir
    _storage_dir = os.path.abspath(os.path.expanduser(path))
    os.makedirs(_storage_dir, exist_ok=True)


def _storage() -> str:
    global _storage_dir
    if _storage_dir is None:
        init_storage(os.environ.get(
            "RAY_TPU_WORKFLOW_STORAGE", "~/ray_tpu_workflows"))
    return _storage_dir


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


class _WorkflowStorage:
    """Reference ``WorkflowStorage`` :229 — per-workflow task results."""

    def __init__(self, workflow_id: str, create: bool = True):
        self.dir = _wf_dir(workflow_id)
        if create:
            os.makedirs(os.path.join(self.dir, "tasks"), exist_ok=True)

    def has(self, task_id: str) -> bool:
        return os.path.exists(self._task_path(task_id))

    def load(self, task_id: str) -> Any:
        with open(self._task_path(task_id), "rb") as f:
            return pickle.load(f)

    def save(self, task_id: str, value: Any) -> None:
        tmp = self._task_path(task_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self._task_path(task_id))

    def save_dag(self, dag_bytes: bytes) -> None:
        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            f.write(dag_bytes)

    def load_dag(self) -> Optional[bytes]:
        p = os.path.join(self.dir, "dag.pkl")
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()

    def set_meta(self, **kwargs) -> None:
        meta = self.meta()
        meta.update(kwargs)
        with open(os.path.join(self.dir, "meta.json"), "w") as f:
            json.dump(meta, f)

    def meta(self) -> Dict[str, Any]:
        p = os.path.join(self.dir, "meta.json")
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def save_output(self, value: Any) -> None:
        with open(os.path.join(self.dir, "output.pkl"), "wb") as f:
            pickle.dump(value, f)

    def load_output(self) -> Any:
        with open(os.path.join(self.dir, "output.pkl"), "rb") as f:
            return pickle.load(f)

    def has_output(self) -> bool:
        return os.path.exists(os.path.join(self.dir, "output.pkl"))

    def _task_path(self, task_id: str) -> str:
        return os.path.join(self.dir, "tasks", f"{task_id}.pkl")


def _task_ids(root: DAGNode) -> Dict[int, str]:
    """Deterministic task ids: depth-first position + function name."""
    ids: Dict[int, str] = {}
    counter = [0]

    def walk(node):
        if not isinstance(node, DAGNode) or id(node) in ids:
            return
        for dep in node._deps():
            walk(dep)
        if isinstance(node, FunctionNode):
            name = getattr(node._fn, "__name__", None) or getattr(
                getattr(node._fn, "_fn", None), "__name__", "task")
            ids[id(node)] = f"{counter[0]:04d}_{name}"
        counter[0] += 1

    walk(root)
    return ids


def _execute_durable(root: DAGNode, storage: _WorkflowStorage,
                     args, kwargs) -> Any:
    """Two phases. Submit: walk bottom-up; checkpointed tasks are seeded
    as values, the rest are submitted immediately with upstream REFS as
    args — independent branches run in parallel, exactly like plain
    ``dag.execute``. Checkpoint: persist each task's output in
    completion order, so everything that finished before a failure is
    durable for ``resume``."""
    from ray_tpu.dag.nodes import _resolve
    ids = _task_ids(root)
    ctx = _ExecutionContext(args, kwargs)
    submitted = {}  # ref -> (task_id, cache_key)

    def visit(node):
        if not isinstance(node, DAGNode):
            return
        for dep in node._deps():
            visit(dep)
        if isinstance(node, FunctionNode) and id(node) not in ctx.cache:
            task_id = ids[id(node)]
            if storage.has(task_id):
                ctx.cache[id(node)] = storage.load(task_id)
            else:
                ref = _resolve(node, ctx)  # submit; args may be refs
                submitted[ref] = (task_id, id(node))

    visit(root)
    first_error: Optional[BaseException] = None
    pending = list(submitted)
    while pending:
        done, pending = ray_tpu.wait(pending, num_returns=1)
        ref = done[0]
        task_id, key = submitted[ref]
        try:
            value = ray_tpu.get(ref)
        except BaseException as e:
            first_error = first_error or e
            continue
        storage.save(task_id, value)
        ctx.cache[key] = value
    if first_error is not None:
        raise first_error
    out = _resolve(root, ctx)
    if isinstance(out, list):
        out = [ray_tpu.get(o) if _is_ref(o) else o for o in out]
    elif _is_ref(out):
        out = ray_tpu.get(out)
    return out


def _is_ref(x) -> bool:
    from ray_tpu.core.object_ref import ObjectRef
    return isinstance(x, ObjectRef)


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None,
        **kwargs) -> Any:
    """Execute durably; re-running a finished workflow returns the
    stored output without re-executing."""
    workflow_id = workflow_id or f"wf_{uuid.uuid4().hex[:12]}"
    storage = _WorkflowStorage(workflow_id)
    if storage.has_output():
        return storage.load_output()
    storage.set_meta(status=RUNNING, workflow_id=workflow_id,
                     start_time=time.time())
    if storage.load_dag() is None:
        import cloudpickle
        try:
            storage.save_dag(cloudpickle.dumps((dag, args, kwargs)))
        except Exception:
            pass  # unpicklable DAG: resumable only by re-passing it
    try:
        out = _execute_durable(dag, storage, args, kwargs)
    except BaseException as e:
        storage.set_meta(status=FAILED, error=repr(e),
                         end_time=time.time())
        raise
    storage.save_output(out)
    storage.set_meta(status=SUCCESSFUL, end_time=time.time())
    return out


def run_async(dag: DAGNode, *args, workflow_id: Optional[str] = None,
              **kwargs):
    """Returns an ObjectRef-like future via a thread (the reference
    returns an ObjectRef from the workflow management actor)."""
    global _async_pool
    import concurrent.futures
    if _async_pool is None:
        _async_pool = concurrent.futures.ThreadPoolExecutor(8)
    return _async_pool.submit(
        run, dag, *args, workflow_id=workflow_id, **kwargs)


def resume(workflow_id: str) -> Any:
    """Re-run an interrupted workflow; completed tasks are skipped."""
    storage = _WorkflowStorage(workflow_id)
    if storage.has_output():
        return storage.load_output()
    dag_bytes = storage.load_dag()
    if dag_bytes is None:
        raise ValueError(
            f"Workflow {workflow_id!r} cannot be resumed: no stored DAG "
            f"(pass the dag to `run` with the same workflow_id instead)")
    import cloudpickle
    dag, args, kwargs = cloudpickle.loads(dag_bytes)
    return run(dag, *args, workflow_id=workflow_id, **kwargs)


def get_status(workflow_id: str) -> Optional[str]:
    return _WorkflowStorage(workflow_id, create=False).meta().get("status")


def get_metadata(workflow_id: str) -> Dict[str, Any]:
    return _WorkflowStorage(workflow_id, create=False).meta()


def get_output(workflow_id: str) -> Any:
    storage = _WorkflowStorage(workflow_id, create=False)
    if not storage.has_output():
        raise ValueError(f"Workflow {workflow_id!r} has no output yet")
    return storage.load_output()


def list_all(status_filter: Optional[str] = None) -> List[tuple]:
    out = []
    base = _storage()
    for wf_id in sorted(os.listdir(base)):
        if not os.path.isdir(os.path.join(base, wf_id)):
            continue
        meta = _WorkflowStorage(wf_id, create=False).meta()
        status = meta.get("status")
        if status and (status_filter is None or status == status_filter):
            out.append((wf_id, status))
    return out


def delete(workflow_id: str) -> None:
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
