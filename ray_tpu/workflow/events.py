"""Workflow events: durable DAGs that block on external signals.

Reference: ``python/ray/workflow/event_listener.py`` (``EventListener``
ABC + ``TimerListener``) and ``http_event_provider.py`` (an HTTP
endpoint delivering events to waiting workflows). TPU-build shape: an
event is a DURABLE record under the workflow storage root — the HTTP
provider writes it there, so an event delivered while the cluster (or
the workflow) is down is simply found on resume; a ``wait_for_event``
node is an ordinary workflow task whose body polls for the record, so
its result checkpoints like any other task output and a resumed
workflow never re-waits a received event.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Optional

__all__ = ["EventListener", "TimerListener", "HTTPListener",
           "wait_for_event", "deliver_event",
           "start_http_event_provider"]


def _events_dir() -> str:
    from ray_tpu.workflow.api import _storage
    d = os.path.join(_storage(), "_events")
    os.makedirs(d, exist_ok=True)
    return d


def _event_path(event_key: str) -> str:
    import hashlib
    # readable prefix + hash of the RAW key: lossy sanitization alone
    # would collide distinct keys ('job/done' vs 'job_done') and
    # cross-deliver their events
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in event_key)[:48]
    digest = hashlib.sha1(event_key.encode()).hexdigest()[:12]
    return os.path.join(_events_dir(), f"{safe}.{digest}.json")


def deliver_event(event_key: str, payload: Any = None) -> None:
    """Durably record an event (what the HTTP provider does for POSTs).
    Delivery is idempotent: the first payload wins."""
    path = _event_path(event_key)
    if os.path.exists(path):
        return
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump({"event_key": event_key, "payload": payload,
                   "delivered_at": time.time()}, f)
    try:
        # atomic first-wins: link fails with EEXIST if a concurrent
        # delivery landed first (os.replace would let the last win)
        os.link(tmp, path)
    except FileExistsError:
        pass
    finally:
        os.unlink(tmp)


class EventListener:
    """Reference: event_listener.py:EventListener — implement
    ``poll_for_event`` (blocking) for a custom event source."""

    def poll_for_event(self, *args, **kwargs) -> Any:
        raise NotImplementedError

    def event_checkpointed(self, event: Any) -> None:
        """Hook for exactly-once sources to ack consumption
        (reference: event_listener.py). The built-in task-based
        ``wait_for_event`` intentionally does NOT call this — the task
        result is only durable once the workflow executor checkpoints
        it, which happens after the task returns; acking earlier could
        lose the event on a crash in between. Call it from a custom
        executor that knows the checkpoint landed."""


class TimerListener(EventListener):
    """Fires at an absolute unix timestamp (reference TimerListener)."""

    def poll_for_event(self, timestamp: float) -> float:
        delay = timestamp - time.time()
        if delay > 0:
            time.sleep(delay)
        return timestamp


class HTTPListener(EventListener):
    """Waits for a durable event record keyed by ``event_key`` —
    written by :func:`deliver_event` / the HTTP provider."""

    def __init__(self, poll_interval_s: float = 0.5):
        self.poll_interval_s = poll_interval_s

    def poll_for_event(self, event_key: str,
                       timeout_s: Optional[float] = None) -> Any:
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        path = _event_path(event_key)
        while True:
            if os.path.exists(path):
                with open(path) as f:
                    return json.load(f)["payload"]
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no event {event_key!r} within {timeout_s}s")
            time.sleep(self.poll_interval_s)


def wait_for_event(listener_cls=HTTPListener, *args, **kwargs):
    """A workflow node that completes when the listener's event
    arrives (reference: ``workflow.wait_for_event``). The node is an
    ordinary durable task: its (checkpointed) output is the event
    payload, so resumes skip already-received events."""
    import ray_tpu
    if not (isinstance(listener_cls, type)
            and issubclass(listener_cls, EventListener)):
        raise TypeError(
            f"wait_for_event takes an EventListener subclass first, "
            f"got {listener_cls!r} — e.g. "
            f"wait_for_event(HTTPListener, 'my-key')")
    from ray_tpu.workflow.api import _storage
    storage_root = _storage()   # resolve DRIVER-side: the executing
    # worker must poll the same event store the provider writes to

    @ray_tpu.remote
    def _wait_for_event(*a, **kw):
        from ray_tpu.workflow.api import init_storage
        init_storage(storage_root)
        listener = listener_cls()
        return listener.poll_for_event(*a, **kw)

    _wait_for_event.__name__ = f"event_{listener_cls.__name__}"
    return _wait_for_event.bind(*args, **kwargs)


class _EventHTTPServer:
    """POST /event/<event_key> with a JSON body delivers that payload
    durably (reference: http_event_provider.py's endpoint, minus the
    serve dependency)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) != 2 or parts[0] != "event":
                    self._reply(404, {"error": "POST /event/<key>"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n)) if n \
                        else None
                    deliver_event(parts[1], payload)
                    self._reply(200, {"delivered": parts[1]})
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error": str(e)})

            def _reply(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.address = (f"http://{host}:"
                        f"{self.server.server_address[1]}")
        self._thread = threading.Thread(
            target=self.server.serve_forever,
            name="workflow-events-http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()


def start_http_event_provider(host: str = "127.0.0.1",
                              port: int = 0) -> _EventHTTPServer:
    """Start the HTTP event endpoint; returns a handle with
    ``.address`` and ``.stop()``."""
    return _EventHTTPServer(host, port)
