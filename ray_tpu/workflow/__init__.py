"""ray_tpu.workflow: durable DAG execution
(reference: ``python/ray/workflow/``).

``workflow.run(dag, workflow_id=...)`` executes a task DAG with every
task output checkpointed to storage (``task_executor.py:50``,
``WorkflowStorage`` :229); re-running or ``resume()`` after a crash
skips completed tasks and replays only the rest.
"""

from ray_tpu.workflow.events import (
    EventListener,
    HTTPListener,
    TimerListener,
    deliver_event,
    start_http_event_provider,
    wait_for_event,
)
from ray_tpu.workflow.api import (
    delete,
    get_metadata,
    get_output,
    get_status,
    init_storage,
    list_all,
    resume,
    run,
    run_async,
)

__all__ = [
    "EventListener",
    "HTTPListener",
    "TimerListener",
    "delete",
    "deliver_event",
    "start_http_event_provider",
    "wait_for_event",
    "get_metadata",
    "get_output",
    "get_status",
    "init_storage",
    "list_all",
    "resume",
    "run",
    "run_async",
]
