"""Parallelism layer: meshes, sharding, collectives.

The TPU-native replacement for the reference's NCCL/GLOO collective layer
(``python/ray/util/collective/``) and the parallelism strategies inventoried
in SURVEY.md §2.5: device meshes with named axes (dp/fsdp/tp/sp/ep/pp),
GSPMD sharding rules, and in-graph XLA collectives.
"""

from ray_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
    local_mesh,
    chip_spec,
    ChipSpec,
)
from ray_tpu.parallel.sharding import (
    ShardingRules,
    logical_to_mesh_axes,
    shard_params,
    batch_sharding,
    constrain,
)
from ray_tpu.parallel import collective
from ray_tpu.parallel import quantization

__all__ = [
    "mpmd_pipeline",
    "ParallelPlan",
    "ElasticTrainer",
    "MeshSpec",
    "build_mesh",
    "local_mesh",
    "chip_spec",
    "ChipSpec",
    "ShardingRules",
    "logical_to_mesh_axes",
    "shard_params",
    "batch_sharding",
    "constrain",
    "collective",
    "quantization",
]


def __getattr__(name):
    # mpmd_pipeline / plan import lazily: they pull in the
    # actor/runtime and model layers, which plain sharding users
    # shouldn't pay for at import time
    if name == "mpmd_pipeline":
        import importlib
        return importlib.import_module("ray_tpu.parallel.mpmd_pipeline")
    if name == "ParallelPlan":
        from ray_tpu.parallel.plan import ParallelPlan
        return ParallelPlan
    if name == "ElasticTrainer":
        from ray_tpu.parallel.elastic import ElasticTrainer
        return ElasticTrainer
    raise AttributeError(name)
