"""ParallelPlan: one config, every parallelism.

The composition layer ROADMAP item 4 asks for — a single declarative
grid

    ParallelPlan(pp=S, virtual=v, dp=D, fsdp=F,
                 grad_transport="fp32"/"int8",
                 shard_weight_update=..., slice_strategy=...)

that **lowers** to whichever runtime shape the grid implies, behind one
``TrainProgram`` interface (``step`` / ``save_checkpoint`` /
``load_checkpoint`` / ``shutdown``):

- ``pp == 1`` → the **SPMD** GSPMD train step
  (``models.training.make_train_step`` over a dp×fsdp mesh: in-graph
  collectives, int8 transport modeled by ``fake_quant``, cross-replica
  flat 1/N sharded weight update);
- ``pp >= 2, dp == fsdp == 1`` → the **MPMD** interleaved pipeline
  (``parallel.mpmd_pipeline.MPMDPipeline(train=True)``: actor-hosted
  stages, streamed activations, per-stage fused optimizer);
- ``pp >= 2, dp*fsdp >= 2`` → **both nested** (the Megatron-LM 3D
  recipe, arXiv:1909.08053, composed with EQuARX int8 collectives,
  arXiv:2506.17615): every pipeline stage actor hosts a shard_map'd
  dp×fsdp program over its own device mesh, with the stage's gradient
  reduction carrying REAL int8 bytes (values + per-block f32 scales in
  the all-gather leg) when ``grad_transport="int8"``, and the fused
  clip+adamw step running under the cross-replica sharded-update path.

``slice_strategy`` ("SLICE_SPREAD"/"SLICE_PACK") reserves a gang
placement group — one bundle per pipeline stage on the distinct hosts
of ONE TPU slice (``util/placement_group.py``) — and schedules each
stage actor onto its bundle; when no slice capacity (or no runtime) is
available within ``placement_timeout_s`` the plan falls back to local
devices, so the same script runs on a laptop and on a gang-scheduled
slice.

Checkpoints are **lowering-independent**: every program saves/loads the
same canonical single-program layout ``{"params", "opt_state", "step"}``
(the treedef of plain AdamW state — the pipeline's merge target), so a
state saved under ``(pp=2, v=2, dp=2)`` reloads into ``(pp=1, dp=1)``
and vice versa with exact value AND treedef parity.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, Optional, Sequence

from ray_tpu.models.training import GRAD_TRANSPORTS

logger = logging.getLogger(__name__)

__all__ = ["ParallelPlan", "PlanStepResult", "TrainProgram",
           "SLICE_STRATEGIES"]

SLICE_STRATEGIES = ("SLICE_PACK", "SLICE_SPREAD")


@dataclasses.dataclass
class PlanStepResult:
    """Uniform per-step result across lowerings."""
    loss: float
    grad_norm: Optional[float]
    step: Optional[int]
    wall_s: float
    n_tokens: Optional[float] = None
    #: measured pipeline bubble (MPMD lowerings; None for SPMD)
    bubble_fraction: Optional[float] = None
    #: the native result object (PipelineStepResult / metrics dict)
    detail: Any = None


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    """Declarative parallelism grid; ``build()`` lowers it.

    ``pp`` pipeline stages × ``virtual`` interleaved chunks per stage,
    each stage running a ``dp`` × ``fsdp`` data-parallel program on its
    own devices. ``n_microbatches`` is the 1F1B microbatch count
    (ignored by the SPMD lowering). ``grad_transport`` /
    ``shard_weight_update`` / ``quant_*`` pick the gradient byte path
    (PR-6 knobs, now honored by every lowering). ``slice_strategy``
    asks for a gang placement group over one TPU slice's hosts."""
    pp: int = 1
    virtual: int = 1
    dp: int = 1
    fsdp: int = 1
    n_microbatches: int = 4
    grad_transport: str = "fp32"
    shard_weight_update: bool = False
    slice_strategy: Optional[str] = None
    quant_block_size: Optional[int] = None
    quant_stochastic: bool = False

    def __post_init__(self):
        if min(self.pp, self.virtual, self.dp, self.fsdp,
               self.n_microbatches) < 1:
            raise ValueError(
                f"every ParallelPlan axis must be >= 1, got {self}")
        if self.virtual > 1 and self.pp < 2:
            raise ValueError(
                f"virtual={self.virtual} needs pp >= 2 (interleaved "
                f"chunks are a pipeline concept)")
        if self.grad_transport not in GRAD_TRANSPORTS:
            raise ValueError(
                f"grad_transport must be one of {GRAD_TRANSPORTS}, "
                f"got {self.grad_transport!r}")
        if self.slice_strategy is not None and \
                self.slice_strategy not in SLICE_STRATEGIES:
            raise ValueError(
                f"slice_strategy must be one of {SLICE_STRATEGIES} "
                f"or None, got {self.slice_strategy!r}")

    # ------------------------------------------------------- queries
    @property
    def lowering(self) -> str:
        """"spmd" (pp=1), "mpmd" (pp>=2, dp=fsdp=1) or "mpmd3d"."""
        if self.pp == 1:
            return "spmd"
        return "mpmd" if self.dp * self.fsdp == 1 else "mpmd3d"

    @property
    def stage_world(self) -> int:
        """Devices per pipeline stage (dp × fsdp)."""
        return self.dp * self.fsdp

    @property
    def world_size(self) -> int:
        """Total devices the plan wants (pp × dp × fsdp)."""
        return self.pp * self.stage_world

    def describe(self) -> str:
        bits = []
        if self.pp > 1:
            bits.append(f"pp={self.pp}" + (f"(v={self.virtual})"
                                           if self.virtual > 1 else "")
                        + f" M={self.n_microbatches}")
        if self.dp > 1:
            bits.append(f"dp={self.dp}")
        if self.fsdp > 1:
            bits.append(f"fsdp={self.fsdp}")
        if not bits:
            bits.append("single-device")
        bits.append(self.grad_transport)
        if self.shard_weight_update:
            bits.append("sharded-update")
        if self.slice_strategy:
            bits.append(self.slice_strategy)
        return f"{self.lowering}[" + " ".join(bits) + "]"

    def validate_batch(self, batch_rows: int) -> None:
        """Fail fast on a batch the grid cannot split evenly."""
        per_mb = batch_rows
        if self.pp > 1:
            if batch_rows % self.n_microbatches:
                raise ValueError(
                    f"batch {batch_rows} not divisible by "
                    f"{self.n_microbatches} microbatches")
            per_mb = batch_rows // self.n_microbatches
        if per_mb % self.stage_world:
            raise ValueError(
                f"{'microbatch' if self.pp > 1 else 'batch'} rows "
                f"({per_mb}) not divisible by dp*fsdp = "
                f"{self.stage_world}")

    def validate_config(self, config) -> None:
        if self.pp > 1 and self.pp * self.virtual > config.n_layers:
            raise ValueError(
                f"pp*virtual = {self.pp * self.virtual} chunks need at "
                f"least that many layers, model has {config.n_layers}")

    # -------------------------------------------------------- lowering
    def build(self, config, *,
              learning_rate: float = 1e-5,
              weight_decay: float = 0.0,
              clip_norm: Optional[float] = 1.0,
              seed: int = 0,
              devices: Optional[Sequence] = None,
              actor_options: Optional[Dict[str, Any]] = None,
              step_timeout_s: float = 300.0,
              placement_bundle: Optional[Dict[str, float]] = None,
              placement_timeout_s: float = 60.0,
              stage_mesh: Optional[bool] = None,
              telemetry_interval_s: float = 0.5) -> "TrainProgram":
        """Lower the plan against ``config`` into a live
        :class:`TrainProgram`. SPMD lowers in-process; MPMD lowerings
        spawn one stage actor per ``pp`` (requires a running
        ``ray_tpu`` cluster), gang-scheduled onto a slice placement
        group when ``slice_strategy`` is set and capacity exists."""
        self.validate_config(config)
        if self.pp == 1:
            return _SPMDProgram(
                self, config, learning_rate=learning_rate,
                weight_decay=weight_decay, clip_norm=clip_norm,
                seed=seed, devices=devices,
                telemetry_interval_s=telemetry_interval_s)
        return _PipelineProgram(
            self, config, learning_rate=learning_rate,
            weight_decay=weight_decay, clip_norm=clip_norm, seed=seed,
            actor_options=actor_options, step_timeout_s=step_timeout_s,
            placement_bundle=placement_bundle,
            placement_timeout_s=placement_timeout_s,
            stage_mesh=stage_mesh)


# ------------------------------------------------------------ programs
class TrainProgram:
    """What every lowering exposes: step / checkpoint / shutdown."""

    plan: ParallelPlan
    config: Any

    @property
    def lowering(self) -> str:
        return self.plan.lowering

    def step(self, batch: Dict[str, Any]) -> PlanStepResult:
        raise NotImplementedError

    def save_checkpoint(self) -> Dict[str, Any]:
        raise NotImplementedError

    def load_checkpoint(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


def _wrap_chain_state(adam_state):
    """AdamW-shaped canonical opt state -> the chain(clip, adamw)
    layout ``make_train_step``'s default optimizer builds (the clip leg
    is stateless)."""
    import optax
    return (optax.EmptyState(), adam_state)


def _unwrap_chain_state(opt_state):
    """Inverse of :func:`_wrap_chain_state`."""
    return opt_state[1]


class _SPMDProgram(TrainProgram):
    """pp=1: ``make_train_step`` over a dp×fsdp mesh, state held
    in-program so the interface matches the pipeline lowerings."""

    def __init__(self, plan: ParallelPlan, config, *, learning_rate,
                 weight_decay, clip_norm, seed, devices,
                 telemetry_interval_s):
        import jax

        from ray_tpu.models.training import (
            default_optimizer, make_train_step)
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh
        from ray_tpu.parallel.quantization import DEFAULT_BLOCK_SIZE

        self.plan = plan
        self.config = config
        self.clip_norm = clip_norm
        n = plan.stage_world
        devices = list(devices) if devices is not None \
            else jax.devices()[:n]
        if len(devices) < n:
            raise ValueError(
                f"plan {plan.describe()} wants {n} devices, have "
                f"{len(devices)}")
        self.mesh = build_mesh(MeshSpec(dp=plan.dp, fsdp=plan.fsdp),
                               devices[:n])
        self.bundle = make_train_step(
            config, self.mesh,
            optimizer=default_optimizer(learning_rate, weight_decay,
                                        clip_norm),
            grad_transport=plan.grad_transport,
            shard_weight_update=plan.shard_weight_update,
            quant_block_size=plan.quant_block_size or DEFAULT_BLOCK_SIZE,
            quant_stochastic=plan.quant_stochastic,
            telemetry_interval_s=telemetry_interval_s)
        self.state = self.bundle.init(seed=seed)

    def step(self, batch: Dict[str, Any]) -> PlanStepResult:
        import numpy as np
        self.plan.validate_batch(
            int(np.asarray(batch["input_ids"]).shape[0]))
        t0 = time.perf_counter()
        self.state, metrics = self.bundle.step(self.state, batch)
        loss = float(metrics["loss"])
        wall = time.perf_counter() - t0
        return PlanStepResult(
            loss=loss, grad_norm=float(metrics["grad_norm"]),
            step=int(self.state["step"]), wall_s=wall,
            n_tokens=float(metrics["n_tokens"]), detail=metrics)

    # ------------------------------------------------------ checkpoint
    def save_checkpoint(self) -> Dict[str, Any]:
        import numpy as np

        import jax

        from ray_tpu.parallel.mpmd_pipeline import _map_param_subtrees
        from ray_tpu.parallel.sharding import unflatten_like

        host = lambda t: jax.tree.map(np.asarray, t)  # noqa: E731
        params = host(self.state["params"])
        opt = host(self.state["opt_state"])
        if self.plan.shard_weight_update:
            # flat 1/N update shards back to the param-shaped layout
            opt = _map_param_subtrees(
                opt, jax.tree.structure(params),
                lambda sub: unflatten_like(params, sub))
        if self.clip_norm is not None:
            opt = _unwrap_chain_state(opt)
        return {"params": params, "opt_state": opt,
                "step": int(self.state["step"])}

    def load_checkpoint(self, state: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp

        from ray_tpu.parallel.mpmd_pipeline import _map_param_subtrees
        from ray_tpu.parallel.quantization import DEFAULT_BLOCK_SIZE
        from ray_tpu.parallel.sharding import flatten_tree

        opt = state["opt_state"]
        if self.clip_norm is not None:
            opt = _wrap_chain_state(opt)
        if self.plan.shard_weight_update:
            n_shards = 1
            for a in ("dp", "fsdp"):
                if self.mesh.shape[a] > 1:
                    n_shards *= self.mesh.shape[a]
            block = self.plan.quant_block_size or DEFAULT_BLOCK_SIZE
            opt = _map_param_subtrees(
                opt, jax.tree.structure(state["params"]),
                lambda sub: flatten_tree(sub, n_shards, block))
        full = {"params": state["params"], "opt_state": opt,
                "step": jnp.asarray(state.get("step", 0), jnp.int32)}
        self.state = jax.device_put(full, self.bundle.state_shardings)


class _PipelineProgram(TrainProgram):
    """pp>=2: the MPMD pipeline, optionally with dp×fsdp stage meshes
    (nested 3D) and a slice-gang placement group."""

    def __init__(self, plan: ParallelPlan, config, *, learning_rate,
                 weight_decay, clip_norm, seed, actor_options,
                 step_timeout_s, placement_bundle, placement_timeout_s,
                 stage_mesh):
        from ray_tpu.parallel.mpmd_pipeline import MPMDPipeline

        self.plan = plan
        self.config = config
        self.pg = None
        if plan.slice_strategy is not None:
            self.pg = self._reserve_gang(placement_bundle,
                                         placement_timeout_s)
        self.pipeline = MPMDPipeline(
            config, n_stages=plan.pp,
            n_microbatches=plan.n_microbatches, seed=seed,
            n_virtual=plan.virtual, train=True,
            learning_rate=learning_rate, weight_decay=weight_decay,
            clip_norm=clip_norm, step_timeout_s=step_timeout_s,
            actor_options=actor_options,
            dp=plan.dp, fsdp=plan.fsdp,
            grad_transport=plan.grad_transport,
            shard_weight_update=plan.shard_weight_update,
            quant_block_size=plan.quant_block_size,
            quant_stochastic=plan.quant_stochastic,
            stage_mesh=stage_mesh,
            placement_group=self.pg)

    def _reserve_gang(self, placement_bundle, timeout_s):
        """One bundle per pipeline stage on a single slice's hosts —
        the gang → mesh hand-off. Falls back to local devices (None)
        when no runtime is up or no slice admits the gang in time, so
        the plan stays runnable anywhere."""
        try:
            import ray_tpu
            from ray_tpu.util.placement_group import (
                placement_group, remove_placement_group)
            if not ray_tpu.is_initialized():
                logger.warning(
                    "plan %s: no runtime for slice_strategy=%s — "
                    "falling back to local devices",
                    self.plan.describe(), self.plan.slice_strategy)
                return None
            bundle = dict(placement_bundle or {"CPU": 1})
            pg = placement_group([dict(bundle)
                                  for _ in range(self.plan.pp)],
                                 strategy=self.plan.slice_strategy)
            if pg.ready(timeout=timeout_s):
                logger.info("plan %s: gang placed on slice %s",
                            self.plan.describe(), pg.slice_id())
                return pg
            remove_placement_group(pg)
            logger.warning(
                "plan %s: no slice admitted the %d-bundle %s gang "
                "within %.0fs — falling back to local devices",
                self.plan.describe(), self.plan.pp,
                self.plan.slice_strategy, timeout_s)
            return None
        except Exception:
            logger.exception("plan %s: gang reservation failed — "
                             "falling back to local devices",
                             self.plan.describe())
            return None

    def step(self, batch: Dict[str, Any]) -> PlanStepResult:
        res = self.pipeline.step(batch)
        return PlanStepResult(
            loss=res.loss, grad_norm=res.grad_norm, step=res.step,
            wall_s=res.wall_s, n_tokens=res.n_tokens,
            bubble_fraction=res.bubble_fraction, detail=res)

    def save_checkpoint(self) -> Dict[str, Any]:
        return self.pipeline.save_checkpoint()

    def load_checkpoint(self, state: Dict[str, Any]) -> None:
        self.pipeline.load_checkpoint(state)

    def shutdown(self) -> None:
        self.pipeline.shutdown()
        if self.pg is not None:
            try:
                from ray_tpu.util.placement_group import (
                    remove_placement_group)
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None
