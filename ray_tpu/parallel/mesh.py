"""Device meshes and TPU topology.

The unit of accelerator scheduling in this framework is the TPU pod slice;
the unit of numerics is a jitted GSPMD program over a
``jax.sharding.Mesh``. This module builds meshes with the standard axis
vocabulary used across the libraries:

- ``dp``   — pure data parallel (params replicated)
- ``fsdp`` — data parallel with parameter/optimizer sharding (ZeRO-3-like)
- ``tp``   — tensor parallel (within ICI domain)
- ``sp``   — sequence/context parallel (ring attention axis)
- ``ep``   — expert parallel (MoE)
- ``pp``   — pipeline parallel (usually across DCN)

The reference has no in-tree TP/SP/PP (SURVEY.md §2.5); DP arrives via
torch DDP and FSDP via DeepSpeed integration. Here all strategies are mesh
axes of one GSPMD program.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Per-chip hardware numbers for MFU accounting."""
    name: str
    bf16_flops: float          # peak bf16 FLOP/s per chip
    hbm_bytes: int
    hbm_gbps: float            # HBM bandwidth GB/s
    ici_gbps: float            # per-link ICI bandwidth GB/s


# Public numbers (cloud.google.com/tpu/docs/system-architecture).
CHIP_SPECS: Dict[str, ChipSpec] = {
    "v4": ChipSpec("v4", 275e12, 32 << 30, 1228.0, 50.0),
    "v5e": ChipSpec("v5e", 197e12, 16 << 30, 819.0, 50.0),
    "v5p": ChipSpec("v5p", 459e12, 95 << 30, 2765.0, 100.0),
    "v6e": ChipSpec("v6e", 918e12, 32 << 30, 1640.0, 100.0),
    "cpu": ChipSpec("cpu", 1e11, 8 << 30, 50.0, 10.0),
}


def chip_spec(kind: Optional[str] = None) -> ChipSpec:
    """Resolve the chip spec for the current platform (or a named one)."""
    if kind is None:
        import jax
        d = jax.devices()[0]
        if d.platform != "tpu":
            return CHIP_SPECS["cpu"]
        k = getattr(d, "device_kind", "").lower()
        for name in ("v6e", "v5p", "v5e", "v4"):
            if name in k.replace(" ", "").replace("lite", "e"):
                return CHIP_SPECS[name]
        return CHIP_SPECS["v5e"]
    return CHIP_SPECS[kind]


@dataclasses.dataclass
class MeshSpec:
    """Named-axis mesh shape; -1 on at most one axis means "infer".

    Example: ``MeshSpec(fsdp=-1, tp=4)`` on a v5e-64 → mesh (fsdp=16, tp=4).
    """
    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.axis_sizes()
        infer = [a for a, s in sizes.items() if s == -1]
        if len(infer) > 1:
            raise ValueError("at most one axis may be -1")
        known = math.prod(s for s in sizes.values() if s != -1)
        if infer:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by {known}")
            sizes[infer[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {known} devices, have {n_devices}")
        return MeshSpec(**sizes)

    @property
    def nontrivial_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in AXIS_ORDER if getattr(self, a) > 1)


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    """Build a ``jax.sharding.Mesh`` over ``devices`` (default: all).

    Axis order puts ``pp`` outermost (slowest-varying → maps to DCN when
    devices span hosts/slices) and ``tp`` innermost (fastest-varying →
    nearest-neighbor ICI links), the standard layout from the scaling
    playbook.
    """
    import jax
    from jax.sharding import Mesh
    if devices is None:
        devices = jax.devices()
    spec = spec.resolve(len(devices))
    sizes = spec.axis_sizes()
    arr = np.asarray(devices).reshape(*[sizes[a] for a in AXIS_ORDER])
    return Mesh(arr, AXIS_ORDER)


def local_mesh(spec: Optional[MeshSpec] = None):
    """Mesh over this process's local devices only."""
    import jax
    devices = jax.local_devices()
    if spec is None:
        spec = MeshSpec(tp=len(devices))
    return build_mesh(spec, devices)


def mesh_shape_for_slice(pod_type: str, spec: MeshSpec) -> MeshSpec:
    """Resolve a MeshSpec against a named slice type, e.g. ``v5e-64``."""
    n = int(pod_type.rsplit("-", 1)[1])
    return spec.resolve(n)
