"""GSPMD sharding rules: logical axis names → mesh axes.

The idiomatic XLA equivalent of the reference's per-strategy integrations
(DDP process groups, DeepSpeed ZeRO-3): parameters are annotated with
*logical* axis names ("embed", "mlp", "heads", …); a rule table maps each
logical name to zero or more mesh axes; ``jit`` + ``NamedSharding`` then
compiles the collectives. Changing strategy = changing the rule table, not
the model.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AxisTarget = Union[None, str, Tuple[str, ...]]


class ShardingRules(dict):
    """logical axis name -> mesh axis (or tuple, or None=replicate)."""

    def spec_for(self, logical_axes: Sequence[Optional[str]]) -> P:
        return P(*[self.get(a) if a is not None else None
                   for a in logical_axes])


# Default rule tables for the standard strategies. "embed"/"mlp"/"heads"/
# "kv"/"vocab" are the model-side logical names used by ray_tpu.models.
FSDP_RULES = ShardingRules(
    batch=("dp", "fsdp"),
    sequence="sp",
    embed="fsdp",       # shard params along embed dim (ZeRO-3-like)
    mlp="tp",
    heads="tp",
    kv=None,
    vocab="tp",
    expert="ep",
    stage="pp",
)

DDP_RULES = ShardingRules(
    batch=("dp", "fsdp"),
    sequence="sp",
    embed=None,          # params fully replicated
    mlp="tp",
    heads="tp",
    kv=None,
    vocab="tp",
    expert="ep",
    stage="pp",
)


def logical_to_mesh_axes(logical_axes: Sequence[Optional[str]],
                         rules: ShardingRules) -> P:
    return rules.spec_for(logical_axes)


def shard_params(params, logical_axes_tree, rules: ShardingRules,
                 mesh: Mesh):
    """Build a NamedSharding pytree matching ``params`` from a pytree of
    logical-axis tuples (same treedef)."""
    def one(axes):
        if axes is None:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, rules.spec_for(axes))
    return jax.tree.map(one, logical_axes_tree,
                        is_leaf=lambda x: x is None or isinstance(x, tuple))


def batch_sharding(mesh: Mesh, rules: ShardingRules,
                   batch_axes: Sequence[Optional[str]] = ("batch",)):
    """Sharding for input batches (leading batch dim sharded over dp/fsdp)."""
    return NamedSharding(mesh, rules.spec_for(list(batch_axes)))


def constrain(x, mesh: Mesh, rules: ShardingRules,
              logical_axes: Sequence[Optional[str]]):
    """``with_sharding_constraint`` by logical names (inside jit)."""
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, rules.spec_for(logical_axes)))


def infer_param_logical_axes(params) -> object:
    """Fallback heuristic for unannotated params: shard the largest dim of
    big (≥2D, ≥2^16 elems) tensors on fsdp, replicate the rest."""
    def one(p):
        if p.ndim >= 2 and p.size >= (1 << 16):
            axes: list = [None] * p.ndim
            axes[int(max(range(p.ndim), key=lambda i: p.shape[i]))] = "embed"
            return tuple(axes)
        return None
    return jax.tree.map(one, params)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ----------------------------------------------- flat 1/N update shards
# Cross-replica sharded weight update (arXiv:2004.13336): gradients and
# master-param working copies flatten to 1-D, pad to n_shards * k quant
# blocks (so int8 transport and the flat layout share block boundaries),
# and shard over the data axes — each rank updates only its 1/N chunk of
# the flat optimizer state, then the fresh params all-gather back.
# Shared by ``models.training.make_train_step(shard_weight_update=True)``
# and the per-stage fused optimizer of ``parallel.mpmd_pipeline``.

def flat_pad_len(n: int, n_shards: int, block_size: int) -> int:
    """Padded flat length: the smallest multiple of ``n_shards`` whole
    quant blocks that holds ``n`` elements."""
    chunk = -(-n // n_shards)
    chunk = -(-chunk // block_size) * block_size
    return chunk * n_shards


def flatten_leaf(x, n_shards: int, block_size: int):
    """1-D zero-padded flat view of one leaf (see :func:`flat_pad_len`)."""
    import jax.numpy as jnp
    flat = x.reshape(-1)
    return jnp.pad(flat, (0, flat_pad_len(x.size, n_shards, block_size)
                          - x.size))


def flatten_tree(tree, n_shards: int, block_size: int,
                 constrain_to=None):
    """Flatten every leaf; an optional sharding constraint on each flat
    leaf compiles to the reduce-scatter (grads) / scatter (params)."""
    import jax

    def one(x):
        f = flatten_leaf(x, n_shards, block_size)
        if constrain_to is not None:
            f = jax.lax.with_sharding_constraint(f, constrain_to)
        return f
    return jax.tree.map(one, tree)


def unflatten_like(template, flat_tree):
    """Invert :func:`flatten_tree`: slice each padded flat leaf back to
    its template leaf's size and shape."""
    import jax
    return jax.tree.map(lambda p, f: f[:p.size].reshape(p.shape),
                        template, flat_tree)
