"""Int8 blockwise quantization for collective transport.

The wire format used by the quantized collectives (EQuARX-style,
arXiv:2506.17615): a tensor is flattened, padded to a whole number of
``block_size``-element blocks, and each block is symmetrically quantized
to int8 against its own f32 scale (``amax / 127``). On the wire a block
costs ``block_size`` bytes of payload plus 4 bytes of scale, so transport
shrinks ~4x vs f32 (``compression_ratio`` below gives the exact number).

Rounding is round-to-nearest by default; ``stochastic_rounding=True``
makes the quantizer unbiased (``E[dequant(quant(x))] = x``) at the cost
of higher per-element variance — the standard choice for gradient
transport, where bias compounds across steps but zero-mean noise averages
out across the reduction.

Everything here is jittable and shard_map-safe (pure ``jnp``); the
``*_np`` twins are the plain-NumPy reference used by the host-backend
collectives and the parity tests.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

DEFAULT_BLOCK_SIZE = 256
_QMAX = 127.0


def _padded_len(n: int, block_size: int) -> int:
    return -(-n // block_size) * block_size


def quantize_int8(x, block_size: int = DEFAULT_BLOCK_SIZE,
                  stochastic_rounding: bool = False,
                  key=None) -> Tuple:
    """Quantize ``x`` to ``(values int8 [nblocks, block_size],
    scales f32 [nblocks])``.

    Blocks are taken over the row-major flattening of ``x``; the final
    block is zero-padded (an all-zero block quantizes exactly, so padding
    never perturbs the scales). ``stochastic_rounding`` requires ``key``.
    """
    import jax
    import jax.numpy as jnp

    flat = jnp.asarray(x).astype(jnp.float32).reshape(-1)
    n = flat.shape[0]
    flat = jnp.pad(flat, (0, _padded_len(n, block_size) - n))
    blocks = flat.reshape(-1, block_size)
    amax = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.where(amax > 0, amax / _QMAX, 1.0)
    scaled = blocks / scales[:, None]
    if stochastic_rounding:
        if key is None:
            raise ValueError("stochastic_rounding requires a PRNG key")
        # floor(x + u), u ~ U[0,1): E[q] == x exactly.
        q = jnp.floor(scaled + jax.random.uniform(key, scaled.shape))
    else:
        q = jnp.round(scaled)
    return jnp.clip(q, -_QMAX, _QMAX).astype(jnp.int8), scales


def dequantize_int8(values, scales, shape=None, dtype=None):
    """Invert :func:`quantize_int8`. ``shape=None`` returns the padded
    1-D f32 payload; otherwise the result is sliced and reshaped (and
    cast to ``dtype`` if given)."""
    import jax.numpy as jnp

    flat = (values.astype(jnp.float32) * scales[..., None]).reshape(-1)
    if shape is not None:
        size = int(np.prod(shape)) if shape else 1
        flat = flat[:size].reshape(shape)
    return flat.astype(dtype) if dtype is not None else flat


def fake_quant(x, block_size: int = DEFAULT_BLOCK_SIZE,
               stochastic_rounding: bool = False, key=None):
    """``dequant(quant(x))`` with ``x``'s shape and dtype — the transport
    error a tensor picks up crossing one quantized wire leg. Used by the
    training step to model int8 gradient transport inside one SPMD
    program (where the reduction itself is compiled by XLA and the
    pre-reduction per-rank payloads aren't addressable)."""
    q, s = quantize_int8(x, block_size, stochastic_rounding, key)
    return dequantize_int8(q, s, x.shape, x.dtype)


def compression_ratio(numel: int,
                      block_size: int = DEFAULT_BLOCK_SIZE) -> float:
    """f32 bytes over int8-wire bytes for a ``numel`` tensor: payload is
    1 byte/elem (after padding) + 4 bytes of scale per block."""
    nblocks = -(-numel // block_size)
    return (4.0 * numel) / (nblocks * block_size + 4.0 * nblocks)


def wire_bytes(numel: int, block_size: int = DEFAULT_BLOCK_SIZE,
               transport: str = "int8") -> int:
    """Bytes one rank ships per reduction leg for a ``numel`` tensor:
    f32 transport moves ``4 * numel``; int8 moves 1 byte/elem (after
    block padding) plus a 4-byte f32 scale per block. The benches use
    this for the analytic comm column next to measured step excess."""
    if transport == "fp32":
        return 4 * numel
    nblocks = -(-numel // block_size)
    return nblocks * block_size + 4 * nblocks


def tree_wire_bytes(shapes, block_size: int = DEFAULT_BLOCK_SIZE,
                    transport: str = "int8") -> int:
    """Sum of :func:`wire_bytes` over an iterable of array shapes (or
    sizes) — the per-step gradient wire budget of one rank."""
    total = 0
    for s in shapes:
        numel = int(s) if np.isscalar(s) else int(np.prod(s)) if s else 1
        total += wire_bytes(numel, block_size, transport)
    return total


# ------------------------------------------------- NumPy reference twins
def quantize_int8_np(x: np.ndarray, block_size: int = DEFAULT_BLOCK_SIZE
                     ) -> Tuple[np.ndarray, np.ndarray]:
    flat = np.asarray(x, dtype=np.float32).reshape(-1)
    n = flat.shape[0]
    flat = np.pad(flat, (0, _padded_len(n, block_size) - n))
    blocks = flat.reshape(-1, block_size)
    amax = np.max(np.abs(blocks), axis=1)
    scales = np.where(amax > 0, amax / _QMAX, 1.0).astype(np.float32)
    q = np.clip(np.round(blocks / scales[:, None]), -_QMAX, _QMAX)
    return q.astype(np.int8), scales


def dequantize_int8_np(values: np.ndarray, scales: np.ndarray,
                       shape=None, dtype=None) -> np.ndarray:
    flat = (values.astype(np.float32) * scales[..., None]).reshape(-1)
    if shape is not None:
        size = int(np.prod(shape)) if shape else 1
        flat = flat[:size].reshape(shape)
    return flat.astype(dtype) if dtype is not None else flat
