"""MPMD pipeline parallelism: actor-hosted stages, streamed activations.

The SPMD pipeline in ``ops/pipeline.py`` compiles every stage into ONE
jitted GPipe program — one mesh, one compile, the full GPipe bubble.
This module is the MPMD alternative the task/actor runtime makes
possible (Scaling Deep Learning Training with MPMD Pipeline
Parallelism, arXiv:2412.14374; the decoupled-actor split mirrors
Podracer's sebulba, arXiv:2104.06272):

- each pipeline stage is a :class:`PipelineStage` **actor** pinned to
  its own device subset, holding its stage parameters
  (``models.transformer.stage_slice_params`` — a contiguous slice of
  the stacked layer leaves, bit-identical to the single-program
  weights) and TWO jitted programs:

  * stage-forward: ``jit(lambda p, x: jax.vjp(stage_fn, p, x))`` —
    returns the activation AND the vjp closure. ``jax.vjp``'s return
    is a pytree-registered ``Partial`` whose leaves are the saved
    residuals, so it crosses the jit boundary as plain arrays;
  * stage-backward: ``jit(lambda vjp, g: vjp(g))`` — applies a saved
    vjp to the downstream gradient, REUSING the forward's residuals
    (no recompute), and emits the upstream input-gradient.

  Per-stage compiles mean per-stage specialization: stages can differ
  in remat policy, precision, even layer count — the constraint the
  single shared compile imposed is gone.

- a driver-side **1F1B scheduler** (:class:`MPMDPipeline`) streams
  per-microbatch activations stage-to-stage: each stage's step is one
  ``num_returns="streaming"`` actor call whose yields are the per-
  microbatch outputs, the driver waits on whichever stage produces
  next (``streaming.wait_any``) and routes the item *ref* — never the
  bytes — into the downstream stage's mailbox, so stage *k*'s forward
  on microbatch *i+1* overlaps both the activation transport and
  stage *k+1*'s forward on microbatch *i*. Transport rides the PR-2/
  PR-3 reliable+credit layer; activations ship via the device-array
  out-of-band serialization fast path (``core/serialization.py``).

Every forward/backward/idle interval is recorded as a ``STAGE_TICK``
flight-recorder event, so the Perfetto ``/timeline`` export doubles as
the bubble visualization, and :meth:`PipelineStage.step_stats` returns
the measured busy/idle split the bench turns into a bubble fraction.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "one_f_one_b_order",
    "analytic_gpipe_bubble",
    "PipelineStage",
    "MPMDPipeline",
    "PipelineStepResult",
]


def one_f_one_b_order(stage: int, n_stages: int, n_microbatches: int
                      ) -> List[Tuple[str, int]]:
    """The 1F1B schedule as seen by one stage: ``[("F", mb), ...]``.

    Warmup forwards fill the pipe (``n_stages - 1 - stage`` of them —
    the last stage has none), then the steady state alternates one
    forward with one backward, then the cooldown drains the remaining
    backwards. Deterministic per (stage, n_stages, M): the driver and
    the stage actor both derive it, so stream item *j* of stage *s*
    IS operation ``order[j]`` — no tags ride the wire.
    """
    m = n_microbatches
    warmup = min(n_stages - 1 - stage, m)
    order = [("F", i) for i in range(warmup)]
    b = 0
    for f in range(warmup, m):
        order.append(("F", f))
        order.append(("B", b))
        b += 1
    order.extend(("B", i) for i in range(b, m))
    return order


def analytic_gpipe_bubble(n_stages: int, n_microbatches: int) -> float:
    """The GPipe pipeline-bubble fraction ``(S-1)/(M+S-1)``: the share
    of each device's timeline spent idle when M microbatches flow
    through S stages with a full flush between steps. 1F1B has the
    same bubble in steady state; its win is activation memory."""
    s, m = n_stages, n_microbatches
    return (s - 1) / (m + s - 1)


def _recorder():
    """This process's flight recorder (None outside a runtime)."""
    try:
        from ray_tpu.core.global_state import try_global_worker
        w = try_global_worker()
        return w.recorder if w is not None else None
    except Exception:
        return None


class PipelineStage:
    """One pipeline stage, hosted in its own actor process.

    Holds the stage's parameter slice on its pinned device and the two
    jitted programs (forward-with-vjp, backward-from-saved-residuals).
    Activations and gradients arrive through mailboxes
    (:meth:`put_activation` / :meth:`put_grad` / :meth:`put_targets` —
    tiny actor calls whose object args are pulled worker-to-worker),
    and one streaming :meth:`run` call per step yields the stage's
    per-microbatch outputs in its 1F1B order.

    Run with ``max_concurrency >= 2``: ``run`` blocks on mailboxes
    while the feed calls execute on sibling threads.
    """

    #: seconds a mailbox take may starve before the stage fails typed
    #: (a dead neighbor must surface as an error, never a hang)
    TAKE_TIMEOUT_S = 120.0

    def __init__(self, config, stage: int, n_stages: int, seed: int = 0,
                 device_index: Optional[int] = None,
                 remat_policy: Optional[str] = None):
        import threading

        import jax

        from ray_tpu.models.transformer import (
            init_params, stage_slice_params)

        if remat_policy is not None:
            config = dataclasses.replace(config, remat=None,
                                         remat_policy=remat_policy)
        self.config = config
        self.stage = stage
        self.n_stages = n_stages
        devices = jax.devices()
        self.device = devices[(stage if device_index is None
                               else device_index) % len(devices)]
        # full init from the shared seed, then slice: the stage weights
        # are bit-identical to the single-program model's (parity is a
        # slicing invariant, not a tolerance)
        params = init_params(config, jax.random.PRNGKey(seed))
        self.params = jax.device_put(
            stage_slice_params(config, params, stage, n_stages),
            self.device)
        del params
        self._fwd, self._bwd, self._acc = self._build_programs()
        self._cond = threading.Condition()
        self._acts: Dict[int, Any] = {}
        self._grads_in: Dict[int, Any] = {}
        self._targets: Dict[int, Any] = {}
        self._abort = False
        self._vjps: Dict[int, Any] = {}
        self.grads = None
        self._stats = {"busy_s": 0.0, "idle_s": 0.0, "fwd_s": 0.0,
                       "bwd_s": 0.0, "ops": 0, "span_s": 0.0}

    # ------------------------------------------------------- programs
    def _build_programs(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import stage_forward, stage_loss

        c, s, n = self.config, self.stage, self.n_stages
        last = s == n - 1

        if s == 0:
            # token ids are int32: differentiate wrt params only
            def fwd(p, x):
                return jax.vjp(lambda q: stage_forward(c, s, n, q, x), p)
        elif last:
            def fwd(p, x, ids, mask):
                def f(q, xx):
                    h = stage_forward(c, s, n, q, xx)
                    return stage_loss(c, q, h, ids, mask)[0]
                return jax.vjp(f, p, x)
        else:
            def fwd(p, x):
                return jax.vjp(
                    lambda q, xx: stage_forward(c, s, n, q, xx), p, x)

        # device pinning rides the params: they are committed to
        # self.device, so jit places every stage program there
        return (jax.jit(fwd),
                jax.jit(lambda vjp, g: vjp(g)),
                jax.jit(lambda a, b: jax.tree.map(jnp.add, a, b)))

    # ------------------------------------------------------- mailboxes
    def put_activation(self, i: int, x) -> None:
        with self._cond:
            self._acts[i] = x
            self._cond.notify_all()

    def put_grad(self, i: int, g) -> None:
        with self._cond:
            self._grads_in[i] = g
            self._cond.notify_all()

    def put_targets(self, i: int, input_ids, loss_mask=None) -> None:
        """Last stage only: the labels (and mask) microbatch the loss
        needs — fed by the driver alongside stage 0's token feed."""
        with self._cond:
            self._targets[i] = (input_ids, loss_mask)
            self._cond.notify_all()

    def abort(self) -> None:
        """Unblock any pending mailbox take with a typed error (driver
        cleanup after a neighbor stage died)."""
        with self._cond:
            self._abort = True
            self._cond.notify_all()

    def _take(self, box: Dict[int, Any], i: int):
        deadline = time.monotonic() + self.TAKE_TIMEOUT_S
        with self._cond:
            while i not in box:
                if self._abort:
                    raise RuntimeError(
                        f"stage {self.stage} aborted waiting for "
                        f"microbatch {i}")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"stage {self.stage} starved waiting for "
                        f"microbatch {i} (neighbor stage dead?)")
                self._cond.wait(0.1)
            return box.pop(i)

    # ------------------------------------------------------------ step
    def run(self, n_microbatches: int):
        """One pipeline step as a streaming generator: walks this
        stage's 1F1B order, blocking on the mailbox each op needs,
        and yields the op's output as its own stream item — the
        activation (F, non-last), the (loss, n_tokens) pair (F, last),
        the upstream input-gradient (B, stage > 0) or the op duration
        (B, stage 0). Records a ``STAGE_TICK`` span per compute AND
        per idle interval: the timeline shows the bubbles."""
        import jax

        rec = _recorder()
        last = self.stage == self.n_stages - 1
        self._stats = {"busy_s": 0.0, "idle_s": 0.0, "fwd_s": 0.0,
                       "bwd_s": 0.0, "ops": 0, "span_s": 0.0}
        with self._cond:
            self._abort = False
        self._vjps.clear()
        self.grads = None
        t_start = time.perf_counter()
        for op, i in one_f_one_b_order(self.stage, self.n_stages,
                                       n_microbatches):
            t_wait = time.perf_counter()
            if op == "F":
                x = self._take(self._acts, i)
                tgt = self._take(self._targets, i) if last else None
            else:
                g = self._take(self._grads_in, i)
            idle = time.perf_counter() - t_wait
            if rec is not None and idle > 1e-4:
                rec.record("STAGE_TICK", stage=self.stage, mb=i,
                           phase="idle", dur_s=round(idle, 6))
            t0 = time.perf_counter()
            if op == "F":
                if self.stage == 0:
                    out, vjp = self._fwd(self.params, x)
                elif last:
                    import jax.numpy as jnp
                    ids, mask = tgt
                    if mask is None:
                        mask = jnp.ones_like(ids, dtype=jnp.float32)
                    loss, vjp = self._fwd(self.params, x, ids, mask)
                    n = float(jnp.sum(mask[:, 1:]))
                    out = {"loss": float(loss), "n_tokens": n}
                else:
                    out, vjp = self._fwd(self.params, x)
                if not isinstance(out, dict):
                    jax.block_until_ready(out)
                self._vjps[i] = vjp
            else:
                parts = self._bwd(self._vjps.pop(i), g)
                gp = parts[0]
                out = parts[1] if self.stage > 0 else None
                self.grads = gp if self.grads is None \
                    else self._acc(self.grads, gp)
                if out is not None:
                    jax.block_until_ready(out)
                else:
                    jax.block_until_ready(self.grads)
            dur = time.perf_counter() - t0
            st = self._stats
            st["busy_s"] += dur
            st["idle_s"] += idle
            st["fwd_s" if op == "F" else "bwd_s"] += dur
            st["ops"] += 1
            if rec is not None:
                rec.record("STAGE_TICK", stage=self.stage, mb=i,
                           phase="forward" if op == "F" else "backward",
                           dur_s=round(dur, 6))
                rec.maybe_flush()
            yield out if out is not None else {"dur_s": dur}
        self._stats["span_s"] = time.perf_counter() - t_start

    # ------------------------------------- serial (unpipelined) path
    def forward_one(self, i: int, x, input_ids=None, loss_mask=None):
        """Unary forward for the serial stage-by-stage baseline: same
        jitted program, no mailbox, one microbatch per call."""
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        if self.stage == self.n_stages - 1 and self.stage > 0:
            if loss_mask is None:
                loss_mask = jnp.ones_like(input_ids, dtype=jnp.float32)
            out, vjp = self._fwd(self.params, x, input_ids, loss_mask)
            n = float(jnp.sum(loss_mask[:, 1:]))
            res: Any = {"loss": float(out), "n_tokens": n}
        else:
            out, vjp = self._fwd(self.params, x)
            jax.block_until_ready(out)
            res = out
        self._vjps[i] = vjp
        self._tick("forward", i, time.perf_counter() - t0)
        return res

    def backward_one(self, i: int, g):
        t0 = time.perf_counter()
        parts = self._bwd(self._vjps.pop(i), g)
        gp = parts[0]
        out = parts[1] if self.stage > 0 else None
        self.grads = gp if self.grads is None else self._acc(self.grads,
                                                             gp)
        import jax
        jax.block_until_ready(out if out is not None else self.grads)
        self._tick("backward", i, time.perf_counter() - t0)
        return out

    def _tick(self, phase: str, i: int, dur: float) -> None:
        st = self._stats
        st["busy_s"] += dur
        st[("fwd_s" if phase == "forward" else "bwd_s")] += dur
        st["ops"] += 1
        rec = _recorder()
        if rec is not None:
            rec.record("STAGE_TICK", stage=self.stage, mb=i, phase=phase,
                       dur_s=round(dur, 6))
            rec.maybe_flush()

    def reset_step(self) -> None:
        """Serial-path step reset (the streaming ``run`` resets
        itself)."""
        self._vjps.clear()
        self.grads = None
        self._stats = {"busy_s": 0.0, "idle_s": 0.0, "fwd_s": 0.0,
                       "bwd_s": 0.0, "ops": 0, "span_s": 0.0}
        self._t_reset = time.perf_counter()

    # ------------------------------------------------------- queries
    def step_stats(self) -> Dict[str, float]:
        st = dict(self._stats)
        if not st["span_s"] and getattr(self, "_t_reset", None):
            st["span_s"] = time.perf_counter() - self._t_reset
        st["device"] = str(self.device)
        st["stage"] = self.stage
        return st

    def get_grads(self):
        """Host copy of the accumulated stage-parameter gradients."""
        import numpy as np

        import jax
        return jax.tree.map(np.asarray, self.grads)

    def ping(self) -> int:
        return self.stage


@dataclasses.dataclass
class PipelineStepResult:
    loss: float
    n_tokens: float
    #: per-microbatch (loss, n) pairs in microbatch order
    microbatch_losses: List[Tuple[float, float]]
    #: per-stage step_stats dicts
    stage_stats: List[Dict[str, float]]
    wall_s: float

    @property
    def bubble_fraction(self) -> float:
        """Measured bubble: the mean over stages of the fraction of
        the step's wall clock each stage spent NOT computing."""
        if not self.wall_s:
            return 0.0
        fr = [1.0 - min(s["busy_s"] / self.wall_s, 1.0)
              for s in self.stage_stats]
        return sum(fr) / len(fr)


class MPMDPipeline:
    """Driver-side 1F1B scheduler over :class:`PipelineStage` actors.

    ``step(batch)`` splits the batch into ``n_microbatches`` along the
    batch axis, feeds stage 0's token microbatches / the last stage's
    targets and loss seeds, launches one streaming ``run`` per stage,
    and routes items (by ref) between neighbors as
    ``streaming.wait_any`` reports them ready. The combined loss is
    the token-weighted mean of the per-microbatch losses — exactly the
    single-program ``lm_loss`` of the full batch.

    ``serial=True`` drives the same actors microbatch-by-microbatch
    with unary calls and full barriers — the no-overlap baseline the
    measured bubble fraction is compared against.
    """

    def __init__(self, config, n_stages: int = 2,
                 n_microbatches: int = 4, seed: int = 0,
                 serial: bool = False,
                 step_timeout_s: float = 300.0,
                 actor_options: Optional[Dict[str, Any]] = None,
                 remat_policies: Optional[Sequence[Optional[str]]] = None):
        import ray_tpu

        if n_stages < 2:
            raise ValueError("MPMDPipeline needs n_stages >= 2 "
                             "(use the plain train step otherwise)")
        self.config = config
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.serial = serial
        self.step_timeout_s = step_timeout_s
        opts = {"max_concurrency": 4, "max_restarts": 0}
        opts.update(actor_options or {})
        cls = ray_tpu.remote(**opts)(PipelineStage)
        policies = remat_policies or [None] * n_stages
        self.stages = [
            cls.remote(config, s, n_stages, seed=seed, device_index=s,
                       remat_policy=policies[s])
            for s in range(n_stages)]
        ray_tpu.get([a.ping.remote() for a in self.stages], timeout=300)

    # ---------------------------------------------------------- steps
    def _split(self, batch: Dict[str, Any]):
        import numpy as np

        ids = np.asarray(batch["input_ids"])
        mask = batch.get("loss_mask")
        mask = np.asarray(mask) if mask is not None else None
        m = self.n_microbatches
        if ids.shape[0] % m:
            raise ValueError(f"batch {ids.shape[0]} not divisible by "
                             f"{m} microbatches")
        ids_mb = np.split(ids, m)
        mask_mb = np.split(mask, m) if mask is not None else [None] * m
        # per-microbatch label-token counts — known to the driver
        # without running the model, so the last stage's backward seeds
        # (d total / d loss_i = n_i / N) can be fed up front
        ns = [float(mk[:, 1:].sum()) if mk is not None
              else float(i.shape[0] * (i.shape[1] - 1))
              for i, mk in zip(ids_mb, mask_mb)]
        return ids_mb, mask_mb, ns

    def step(self, batch: Dict[str, Any]) -> PipelineStepResult:
        return (self._step_serial if self.serial
                else self._step_1f1b)(batch)

    def _step_1f1b(self, batch: Dict[str, Any]) -> PipelineStepResult:
        import numpy as np

        import ray_tpu
        from ray_tpu.core import streaming

        S, M = self.n_stages, self.n_microbatches
        ids_mb, mask_mb, ns = self._split(batch)
        total_n = sum(ns)
        t0 = time.perf_counter()
        hold = []  # keep routed refs alive until the step completes
        for i in range(M):
            hold.append(self.stages[0].put_activation.remote(
                i, ids_mb[i]))
            last = self.stages[-1]
            if S > 1:
                hold.append(last.put_targets.remote(
                    i, ids_mb[i], mask_mb[i]))
            # the loss cotangent: scalar n_i / N, feedable up front
            hold.append(last.put_grad.remote(
                i, np.float32(ns[i] / total_n)))
        gens = [a.run.options(num_returns="streaming").remote(M)
                for a in self.stages]
        orders = [one_f_one_b_order(s, S, M) for s in range(S)]
        cursors = [0] * S
        losses: Dict[int, Tuple[float, float]] = {}
        by_gen = {id(g): s for s, g in enumerate(gens)}
        active = list(gens)
        deadline = time.monotonic() + self.step_timeout_s
        try:
            while active:
                ready, _ = streaming.wait_any(
                    active, timeout=max(deadline - time.monotonic(), 0.0))
                if not ready:
                    raise TimeoutError(
                        f"pipeline step stalled: no stage produced an "
                        f"item within {self.step_timeout_s}s")
                for g in ready:
                    s = by_gen[id(g)]
                    try:
                        ref = g.next_ref(timeout=1.0)
                    except StopIteration:
                        active.remove(g)
                        continue
                    op, i = orders[s][cursors[s]]
                    cursors[s] += 1
                    if op == "F" and s < S - 1:
                        hold.append(
                            self.stages[s + 1].put_activation.remote(
                                i, ref))
                    elif op == "F":
                        item = ray_tpu.get(ref, timeout=60)
                        losses[i] = (item["loss"], item["n_tokens"])
                    elif op == "B" and s > 0:
                        hold.append(self.stages[s - 1].put_grad.remote(
                            i, ref))
                    hold.append(ref)
        except BaseException:
            self._cleanup(gens)
            raise
        wall = time.perf_counter() - t0
        stats = ray_tpu.get(
            [a.step_stats.remote() for a in self.stages], timeout=60)
        mb = [losses[i] for i in range(M)]
        loss = sum(l * n for l, n in mb) / total_n
        return PipelineStepResult(
            loss=loss, n_tokens=total_n, microbatch_losses=mb,
            stage_stats=stats, wall_s=wall)

    def _step_serial(self, batch: Dict[str, Any]) -> PipelineStepResult:
        """No-overlap baseline: each microbatch walks every stage's
        forward, then every stage's backward, with a full barrier per
        call — what pipelining exists to beat."""
        import numpy as np

        import ray_tpu

        S, M = self.n_stages, self.n_microbatches
        ids_mb, mask_mb, ns = self._split(batch)
        total_n = sum(ns)
        t0 = time.perf_counter()
        ray_tpu.get([a.reset_step.remote() for a in self.stages],
                    timeout=60)
        losses = []
        for i in range(M):
            act = ray_tpu.get(
                self.stages[0].forward_one.remote(i, ids_mb[i]),
                timeout=self.step_timeout_s)
            for s in range(1, S):
                out = self.stages[s].forward_one.remote(
                    i, act, ids_mb[i], mask_mb[i]) if s == S - 1 else \
                    self.stages[s].forward_one.remote(i, act)
                act = ray_tpu.get(out, timeout=self.step_timeout_s)
            losses.append((act["loss"], act["n_tokens"]))
            g: Any = np.float32(ns[i] / total_n)
            for s in range(S - 1, -1, -1):
                g = ray_tpu.get(self.stages[s].backward_one.remote(i, g),
                                timeout=self.step_timeout_s)
        wall = time.perf_counter() - t0
        stats = ray_tpu.get(
            [a.step_stats.remote() for a in self.stages], timeout=60)
        loss = sum(l * n for l, n in losses) / total_n
        return PipelineStepResult(
            loss=loss, n_tokens=total_n, microbatch_losses=losses,
            stage_stats=stats, wall_s=wall)

    # -------------------------------------------------------- cleanup
    def _cleanup(self, gens) -> None:
        """Failure path: unblock every stage, then drop all stream
        state — typed error out, no hang, no leaked stream refs."""
        for a in self.stages:
            try:
                a.abort.remote()
            except Exception:
                pass
        for g in gens:
            try:
                g.close()
            except Exception:
                pass

    def grads(self, timeout: float = 120.0):
        """Per-stage accumulated parameter-gradient trees (host)."""
        import ray_tpu
        return ray_tpu.get([a.get_grads.remote() for a in self.stages],
                           timeout=timeout)

    def shutdown(self) -> None:
        import ray_tpu
        for a in self.stages:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
