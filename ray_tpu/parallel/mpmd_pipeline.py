"""MPMD pipeline parallelism: actor-hosted stages, streamed activations,
interleaved virtual stages, per-stage fused optimizer step.

The SPMD pipeline in ``ops/pipeline.py`` compiles every stage into ONE
jitted GPipe program — one mesh, one compile, the full GPipe bubble.
This module is the MPMD alternative the task/actor runtime makes
possible (Scaling Deep Learning Training with MPMD Pipeline
Parallelism, arXiv:2412.14374; the decoupled-actor split mirrors
Podracer's sebulba, arXiv:2104.06272):

- each pipeline stage is a :class:`PipelineStage` **actor** pinned to
  its own device subset, holding ``n_virtual`` *virtual stage* slices
  of the model (``models.transformer.stage_slice_params`` over
  round-robin chunk ids — actor ``s`` hosts global chunks
  ``s, s+S, s+2S, ...`` of the ``K = S*v`` total, each a contiguous
  slab of the stacked layer leaves, bit-identical to the
  single-program weights) and THREE jitted program families:

  * stage-forward (per chunk role): ``jit(lambda p, x:
    jax.vjp(stage_fn, p, x))`` — returns the activation AND the vjp
    closure. ``jax.vjp``'s return is a pytree-registered ``Partial``
    whose leaves are the saved residuals, so it crosses the jit
    boundary as plain arrays;
  * stage-backward: ``jit(lambda vjp, g: vjp(g))`` — applies a saved
    vjp to the downstream gradient, REUSING the forward's residuals
    (no recompute), and emits the upstream input-gradient. Per-chunk
    parameter gradients accumulate in-actor across microbatches
    (donated accumulator buffers);
  * stage-optimizer (``train=True``): one fused jitted program that
    scales the accumulated grads by the global clip factor, runs the
    optax update on the stage's param slice, and applies it — params,
    optimizer state AND grads donated. Optimizer state never leaves
    the stage; after warmup the only per-step driver traffic is the
    scalar grad-norm reduction and the loss scalars.

- a driver-side **interleaved 1F1B scheduler** (:class:`MPMDPipeline`)
  streams per-microbatch activations chunk-to-chunk: each stage's step
  is one ``num_returns="streaming"`` actor call whose yields are the
  per-op outputs in the stage's deterministic
  :func:`one_f_one_b_order`, the driver waits on whichever stage
  produces next (``streaming.wait_any``) and routes the item *ref* —
  never the bytes — into the next chunk's mailbox. With ``n_virtual >
  1`` the warmup/cooldown bubble shrinks by the virtual-stage factor:
  analytic ``(S-1)/(v*M+S-1)`` vs GPipe's ``(S-1)/(M+S-1)``.

Every forward/backward/opt/idle interval is recorded as a
``STAGE_TICK`` flight-recorder event labelled with its phase and
virtual-stage (chunk) index, so the Perfetto ``/timeline`` export
doubles as the bubble visualization, and
:meth:`PipelineStage.step_stats` returns the measured busy/idle split
the bench turns into a bubble fraction.

Checkpointing: :meth:`PipelineStage.stage_checkpoint` returns the
stage's param/opt-state slices keyed by global chunk id;
:func:`merge_stage_checkpoints` reassembles the canonical
single-program ``{"params", "opt_state", "step"}`` layout (the same
treedef ``models.training.make_train_step`` produces for the same
optimizer), and :func:`split_train_state` re-slices it for any other
``(n_stages, n_virtual)`` — a checkpoint saved at v=2 reloads into a
v=1 pipeline and vice versa.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "one_f_one_b_order",
    "interleaved_orders",
    "stage_virtual_chunks",
    "analytic_gpipe_bubble",
    "analytic_bubble",
    "PipelineStage",
    "MPMDPipeline",
    "PipelineStepResult",
    "merge_stage_checkpoints",
    "split_train_state",
]


def stage_virtual_chunks(stage: int, n_stages: int,
                         n_virtual: int = 1) -> Tuple[int, ...]:
    """Global chunk ids hosted by one stage actor: round-robin slabs
    ``stage, stage+S, stage+2S, ...`` of the ``K = S*v`` virtual
    stages (Megatron-style interleaving: chunk ``c`` lives on actor
    ``c % S``, so chunk ``c``'s output always feeds the NEXT actor)."""
    return tuple(range(stage, n_stages * n_virtual, n_stages))


def _classic_1f1b(stage: int, n_stages: int, n_microbatches: int
                  ) -> List[Tuple[str, int, int]]:
    """The v=1 1F1B schedule as seen by one stage (chunk == stage):
    warmup forwards fill the pipe (``n_stages - 1 - stage`` of them —
    the last stage has none), then the steady state alternates one
    forward with one backward, then the cooldown drains the remaining
    backwards."""
    m = n_microbatches
    warmup = min(n_stages - 1 - stage, m)
    order = [("F", i, stage) for i in range(warmup)]
    b = 0
    for f in range(warmup, m):
        order.append(("F", f, stage))
        order.append(("B", b, stage))
        b += 1
    order.extend(("B", i, stage) for i in range(b, m))
    return order


@functools.lru_cache(maxsize=256)
def interleaved_orders(n_stages: int, n_microbatches: int,
                       n_virtual: int
                       ) -> Tuple[Tuple[Tuple[str, int, int], ...], ...]:
    """Per-stage interleaved-1F1B op orders for a ``S x M x v`` grid,
    as a tuple (stage-indexed) of op tuples ``(op, microbatch, chunk)``.

    Built by a deterministic greedy tick simulation: at each tick every
    stage runs at most one *runnable* op (an op whose producers
    finished at a strictly earlier tick — one tick of transport
    latency), preferring backwards over forwards (1F1B steady state)
    and breaking ties with the Megatron-style group key ``(mb // S,
    chunk, mb % S)`` so forwards sweep chunk groups of S microbatches.
    The result is valid for ANY grid (no ``M % S`` constraint): the
    simulation only ever schedules dependency-satisfied ops, and a
    stage executing its list in order while blocking on mailboxes can
    never deadlock (every op's producers appear at earlier ticks).
    Deterministic in (S, M, v): the driver and every stage actor derive
    the same lists, so stream item *j* of stage *s* IS operation
    ``orders[s][j]`` — no tags ride the wire."""
    S, M, v = n_stages, n_microbatches, n_virtual
    K = S * v
    done_f: Dict[Tuple[int, int], int] = {}
    done_b: Dict[Tuple[int, int], int] = {}
    orders: List[List[Tuple[str, int, int]]] = [[] for _ in range(S)]
    total = 2 * M * K
    scheduled, t = 0, 0
    while scheduled < total:
        picks = []
        for s in range(S):
            chunks = stage_virtual_chunks(s, S, v)
            best = None
            # backwards first: B(c, i) needs F(c, i) and B(c+1, i)
            for c in chunks:
                for i in range(M):
                    if (c, i) in done_b:
                        continue
                    if done_f.get((c, i), t) >= t:
                        continue
                    if c < K - 1 and done_b.get((c + 1, i), t) >= t:
                        continue
                    key = ("B", i // S, K - 1 - c, i % S)
                    if best is None or key < best[0]:
                        best = (key, ("B", i, c))
            if best is None:
                # forwards: F(c, i) needs F(c-1, i)
                for c in chunks:
                    for i in range(M):
                        if (c, i) in done_f:
                            continue
                        if c > 0 and done_f.get((c - 1, i), t) >= t:
                            continue
                        key = ("F", i // S, c, i % S)
                        if best is None or key < best[0]:
                            best = (key, ("F", i, c))
            if best is not None:
                picks.append((s, best[1]))
        for s, (op, i, c) in picks:
            orders[s].append((op, i, c))
            (done_f if op == "F" else done_b)[(c, i)] = t
            scheduled += 1
        t += 1
    return tuple(tuple(o) for o in orders)


def one_f_one_b_order(stage: int, n_stages: int, n_microbatches: int,
                      n_virtual: int = 1) -> List[Tuple[str, int, int]]:
    """One stage's pipeline-step op order: ``[(op, microbatch, chunk),
    ...]`` with op "F"/"B" and ``chunk`` the global virtual-stage id.

    ``n_virtual == 1`` is the classic 1F1B schedule (chunk == stage);
    ``n_virtual > 1`` interleaves the stage's round-robin chunks via
    the deterministic greedy simulation in :func:`interleaved_orders`,
    cutting warmup/cooldown idle by the virtual-stage factor."""
    if n_virtual <= 1:
        return _classic_1f1b(stage, n_stages, n_microbatches)
    return list(interleaved_orders(n_stages, n_microbatches,
                                   n_virtual)[stage])


def analytic_bubble(n_stages: int, n_microbatches: int,
                    n_virtual: int = 1) -> float:
    """The analytic pipeline-bubble fraction with interleaved virtual
    stages, ``(S-1)/(v*M+S-1)``: warmup and cooldown are paid in
    CHUNK-sized quanta (1/v of a full stage visit), so the idle share
    of each device's timeline shrinks by the virtual-stage factor
    (arXiv:2412.14374; Megatron interleaved 1F1B)."""
    s, m, v = n_stages, n_microbatches, n_virtual
    return (s - 1) / (v * m + s - 1)


def analytic_gpipe_bubble(n_stages: int, n_microbatches: int) -> float:
    """The GPipe pipeline-bubble fraction ``(S-1)/(M+S-1)``: the share
    of each device's timeline spent idle when M microbatches flow
    through S stages with a full flush between steps. 1F1B has the
    same bubble in steady state; its win is activation memory."""
    return analytic_bubble(n_stages, n_microbatches, 1)


def _recorder():
    """This process's flight recorder (None outside a runtime)."""
    try:
        from ray_tpu.core.global_state import try_global_worker
        w = try_global_worker()
        return w.recorder if w is not None else None
    except Exception:
        return None


def _default_stage_optimizer(learning_rate: float, weight_decay: float):
    """The per-stage optimizer matching ``models.training``'s default
    MINUS the global-norm clip — clipping needs the cross-stage norm,
    so the driver reduces per-stage squared norms and every stage
    applies the same scale inside its fused opt program."""
    import optax
    return optax.adamw(learning_rate, b1=0.9, b2=0.95, eps=1e-8,
                       weight_decay=weight_decay)


# --------------------------------------------------------- checkpoints
def _map_param_subtrees(tree, params_treedef, fn):
    """Apply ``fn`` to every subtree of ``tree`` whose structure equals
    ``params_treedef`` (the stage's ``{chunk: param_tree}`` layout),
    passing other leaves through — the trick ``models.training`` uses
    to find param-shaped subtrees (Adam moments) inside an arbitrary
    optax state."""
    import jax

    def is_p(x):
        try:
            return jax.tree.structure(x) == params_treedef
        except Exception:
            return False

    return jax.tree.map(lambda sub: fn(sub) if is_p(sub) else sub,
                        tree, is_leaf=is_p)


def _collect_param_subtrees(tree, params_treedef) -> List[Any]:
    out: List[Any] = []
    _map_param_subtrees(tree, params_treedef,
                        lambda sub: (out.append(sub), sub)[1])
    return out


def merge_stage_checkpoints(config, parts: Sequence[Dict]) -> Dict:
    """Reassemble per-stage checkpoints (from
    :meth:`PipelineStage.stage_checkpoint`) into the canonical
    single-program train state ``{"params", "opt_state", "step"}`` —
    the exact pytree layout ``make_train_step(optimizer=<same
    optimizer>)`` builds, so the pipeline checkpoint round-trips
    against the single-program one. Param-shaped subtrees inside the
    optax state (Adam mu/nu) are found by treedef match and merged
    chunk-wise; counters are taken from stage 0 (identical across
    stages by construction)."""
    import jax

    from ray_tpu.models.transformer import merge_stage_params

    parts = sorted(parts, key=lambda p: p["stage"])
    chunks: Dict[int, Any] = {}
    for p in parts:
        chunks.update(p["chunks"])
    out: Dict[str, Any] = {
        "params": merge_stage_params(config, chunks),
        "step": parts[0].get("step", 0),
    }
    if parts[0].get("opt_state") is not None:
        per_stage = [
            _collect_param_subtrees(p["opt_state"],
                                    jax.tree.structure(p["chunks"]))
            for p in parts]
        counts = {len(s) for s in per_stage}
        if len(counts) != 1:
            raise ValueError(
                f"stage opt states disagree on param-subtree count: "
                f"{sorted(counts)}")
        merged = []
        for j in range(counts.pop()):
            union: Dict[int, Any] = {}
            for s in per_stage:
                union.update(s[j])
            merged.append(merge_stage_params(config, union))
        it = iter(merged)
        out["opt_state"] = _map_param_subtrees(
            parts[0]["opt_state"],
            jax.tree.structure(parts[0]["chunks"]), lambda _: next(it))
    return out


def split_train_state(config, state: Dict, n_stages: int,
                      n_virtual: int = 1) -> List[Dict]:
    """Slice a canonical train state into per-stage load parts for any
    ``(n_stages, n_virtual)`` — the reload target need not match the
    layout the checkpoint was saved under. Inverse of
    :func:`merge_stage_checkpoints` (chunk slices of the stacked layer
    leaves are views of the same weights)."""
    import jax

    from ray_tpu.models.transformer import stage_slice_params

    K = n_stages * n_virtual
    full_td = jax.tree.structure(state["params"])

    def slice_for(s):
        chs = stage_virtual_chunks(s, n_stages, n_virtual)
        part: Dict[str, Any] = {
            "params": {c: stage_slice_params(config, state["params"],
                                             c, K) for c in chs},
            "step": state.get("step", 0),
        }
        if state.get("opt_state") is not None:
            part["opt_state"] = _map_param_subtrees(
                state["opt_state"], full_td,
                lambda sub: {c: stage_slice_params(config, sub, c, K)
                             for c in chs})
        return part

    return [slice_for(s) for s in range(n_stages)]


class PipelineStage:
    """One pipeline stage, hosted in its own actor process.

    Holds the stage's ``n_virtual`` parameter chunks on its pinned
    device, the per-chunk-role jitted forward programs, the shared
    backward program (backward-from-saved-residuals), and — with
    ``train=True`` — the fused optimizer program plus resident optax
    state. Activations and gradients arrive through mailboxes keyed by
    ``(chunk, microbatch)`` (:meth:`put_activation` / :meth:`put_grad`
    / :meth:`put_targets` — tiny actor calls whose object args are
    pulled worker-to-worker), and one streaming :meth:`run` call per
    step yields the stage's per-op outputs in its deterministic
    interleaved-1F1B order.

    Run with ``max_concurrency >= 2``: ``run`` blocks on mailboxes
    while the feed calls execute on sibling threads.
    """

    def __init__(self, config, stage: int, n_stages: int, seed: int = 0,
                 device_index: Optional[int] = None,
                 remat_policy: Optional[str] = None,
                 n_virtual: int = 1,
                 train: bool = False,
                 learning_rate: float = 1e-5,
                 weight_decay: float = 0.0,
                 clip_norm: Optional[float] = 1.0,
                 optimizer_factory=None,
                 mailbox_deadline_s: Optional[float] = None,
                 dp: int = 1,
                 fsdp: int = 1,
                 grad_transport: str = "fp32",
                 shard_weight_update: bool = False,
                 quant_block_size: Optional[int] = None,
                 quant_stochastic: bool = False,
                 stage_mesh: Optional[bool] = None,
                 device_indices: Optional[Sequence[int]] = None):
        import threading

        import jax

        from ray_tpu.core.config import get_config
        from ray_tpu.models.transformer import (
            init_params, stage_slice_params)
        from ray_tpu.parallel.quantization import DEFAULT_BLOCK_SIZE

        if remat_policy is not None:
            config = dataclasses.replace(config, remat=None,
                                         remat_policy=remat_policy)
        if grad_transport not in ("fp32", "int8"):
            raise ValueError(f"grad_transport must be 'fp32' or 'int8', "
                             f"got {grad_transport!r}")
        self.config = config
        self.stage = stage
        self.n_stages = n_stages
        self.n_virtual = n_virtual
        self.n_chunks = n_stages * n_virtual
        self.chunks = stage_virtual_chunks(stage, n_stages, n_virtual)
        #: the stage's own data-parallel grid: every mailbox microbatch
        #: is sharded batch-wise over a dp×fsdp mesh of this actor's
        #: devices, and the fused optimizer runs the cross-replica
        #: sharded-update path over the same axes (3D = pp × dp × fsdp)
        self.dp = int(dp)
        self.fsdp = int(fsdp)
        self.n_model = self.dp * self.fsdp
        self.grad_transport = grad_transport
        self.shard_weight_update = bool(shard_weight_update)
        self.quant_block_size = int(quant_block_size
                                    or DEFAULT_BLOCK_SIZE)
        self.quant_stochastic = bool(quant_stochastic)
        #: shard_map'd stage programs: automatic when the stage grid is
        #: nontrivial; ``stage_mesh=True`` forces the path onto a
        #: 1-device mesh (the bench's comm/compute reference and the
        #: clusterless tests use this to exercise the 3D programs
        #: without multiple devices)
        self.use_mesh = (self.n_model > 1 if stage_mesh is None
                         else bool(stage_mesh))
        #: seconds a mailbox take may starve before the stage fails
        #: typed (a dead neighbor must surface as an error, never a
        #: hang) — config.pipeline_mailbox_deadline_s unless overridden
        self.mailbox_deadline_s = float(
            mailbox_deadline_s if mailbox_deadline_s is not None
            else get_config().pipeline_mailbox_deadline_s)
        devices = jax.devices()
        self.mesh = None
        if self.use_mesh:
            from ray_tpu.parallel.mesh import MeshSpec, build_mesh
            if device_indices is None:
                base = (stage if device_index is None
                        else device_index) * self.n_model
                device_indices = [(base + j) % len(devices)
                                  for j in range(self.n_model)]
            mine = [devices[i % len(devices)] for i in device_indices]
            if len({d.id for d in mine}) < self.n_model:
                raise ValueError(
                    f"stage {stage} needs {self.n_model} distinct "
                    f"devices for its dp={self.dp} x fsdp={self.fsdp} "
                    f"mesh, process has {len(devices)}")
            self.mesh = build_mesh(
                MeshSpec(dp=self.dp, fsdp=self.fsdp), mine)
            self.device = mine[0]
        else:
            self.device = devices[(stage if device_index is None
                                   else device_index) % len(devices)]
        # full init from the shared seed, then slice: the stage weights
        # are bit-identical to the single-program model's (parity is a
        # slicing invariant, not a tolerance)
        params = init_params(config, jax.random.PRNGKey(seed))
        self.params = {
            c: self._place_params(
                stage_slice_params(config, params, c, self.n_chunks))
            for c in self.chunks}
        del params
        self._build_programs()
        self.optimizer = None
        self.opt_state = None
        self.clip_norm = clip_norm
        #: flat 1/N optimizer shards only make sense on a stage mesh
        self._opt_flat = self.use_mesh and self.shard_weight_update
        if train:
            factory = optimizer_factory or _default_stage_optimizer
            self.optimizer = factory(learning_rate, weight_decay)
            if self._opt_flat:
                # optimizer state lives flat-sharded over the stage
                # mesh (1/N resident per device): init inside jit so
                # the flat constraint shards the moments at creation
                from ray_tpu.parallel.sharding import flatten_tree
                world, block = self.n_model, self.quant_block_size
                flat_sh = self._flat_sharding()
                init_prog = jax.jit(lambda p: self.optimizer.init(
                    flatten_tree(p, world, block,
                                 constrain_to=flat_sh)))
                self.opt_state = init_prog(self.params)
            elif self.use_mesh:
                self.opt_state = self._place_params(
                    self.optimizer.init(self.params))
            else:
                self.opt_state = jax.device_put(
                    self.optimizer.init(self.params), self.device)
            self._build_opt_program()
        self._step_count = 0
        self._cond = threading.Condition()
        self._acts: Dict[Tuple[int, int], Any] = {}
        self._grads_in: Dict[Tuple[int, int], Any] = {}
        self._targets: Dict[int, Any] = {}
        self._abort = False
        self._vjps: Dict[Tuple[int, int], Any] = {}
        self._inputs: Dict[Tuple[int, int], Any] = {}
        self._grads: Dict[int, Any] = {}
        self._red_cache = None
        self._sqn = None
        self._stats = self._fresh_stats()
        # live mailbox-depth gauge (fleet metrics plane): how many
        # microbatches are parked waiting for this stage — the queue
        # signal behind the bubbles the timeline shows
        self._mbx_gauge = None
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            self._mbx_gauge = runtime_metrics().pipeline_mailbox_depth
        except Exception:
            pass
        self._mbx_tags = {"stage": str(stage)}

    def _mbx_report_locked(self) -> None:
        """Refresh the mailbox-depth gauge (``self._cond`` held)."""
        if self._mbx_gauge is None:
            return
        try:
            self._mbx_gauge.set(
                len(self._acts) + len(self._grads_in) +
                len(self._targets), tags=self._mbx_tags)
        except Exception:
            pass

    @staticmethod
    def _fresh_stats() -> Dict[str, float]:
        return {"busy_s": 0.0, "idle_s": 0.0, "fwd_s": 0.0,
                "bwd_s": 0.0, "opt_s": 0.0, "ops": 0, "span_s": 0.0}

    # ---------------------------------------------- mesh placement
    def _batch_spec(self):
        from jax.sharding import PartitionSpec as P
        return P(("dp", "fsdp"))

    def _flat_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(("dp", "fsdp")))

    def _place_params(self, tree):
        """Stage params (and param-shaped state) live replicated over
        the stage mesh — the dp×fsdp axes shard the BATCH; the fsdp
        distinction shows up in the flat 1/N optimizer shards of the
        cross-replica update, not the compute layout."""
        import jax
        if self.mesh is None:
            return jax.device_put(tree, self.device)
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(tree, NamedSharding(self.mesh, P()))

    def _place_batch(self, x):
        """Ship one mailbox payload to the stage's devices: batch dim 0
        sharded over (dp, fsdp) on a mesh stage, plain device_put on a
        single-device stage."""
        import jax
        if self.mesh is None:
            return x
        from jax.sharding import NamedSharding
        return jax.device_put(x, NamedSharding(self.mesh,
                                               self._batch_spec()))

    def _place_scalar(self, x):
        import jax
        if self.mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(x, NamedSharding(self.mesh, P()))

    # ------------------------------------------------------- programs
    def _build_programs(self):
        if self.mesh is not None:
            return self._build_mesh_programs()
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import stage_forward, stage_loss

        c, K = self.config, self.n_chunks
        progs: Dict[str, Any] = {}
        if 0 in self.chunks:
            # token ids are int32: differentiate wrt params only
            def fwd_first(p, x):
                return jax.vjp(lambda q: stage_forward(c, 0, K, q, x), p)
            progs["first"] = jax.jit(fwd_first)
        if K - 1 in self.chunks:
            def fwd_loss(p, x, ids, mask):
                def f(q, xx):
                    h = stage_forward(c, K - 1, K, q, xx)
                    return stage_loss(c, q, h, ids, mask)[0]
                return jax.vjp(f, p, x)
            progs["loss"] = jax.jit(fwd_loss)
        if any(0 < ch < K - 1 for ch in self.chunks):
            # any middle chunk: same program, retraced per param shape
            def fwd_mid(p, x):
                return jax.vjp(
                    lambda q, xx: stage_forward(c, 1, K, q, xx), p, x)
            progs["mid"] = jax.jit(fwd_mid)
        # device pinning rides the params: they are committed to
        # self.device, so jit places every stage program there. The
        # grad accumulator donates the OLD accumulator buffer (CPU
        # doesn't support donation — skip it there to avoid a
        # per-compile warning; the arithmetic is identical).
        self._donate = jax.default_backend() != "cpu"
        self._fwd_progs = progs
        self._bwd = jax.jit(lambda vjp, g: vjp(g))
        self._acc = jax.jit(lambda a, b: jax.tree.map(jnp.add, a, b),
                            donate_argnums=(0,) if self._donate else ())

    def _build_mesh_programs(self):
        """The dp×fsdp stage programs: every forward/backward is a
        ``shard_map`` over the stage's own mesh — params replicated in,
        the microbatch sharded batch-wise over ``("dp", "fsdp")``.

        Backwards RECOMPUTE the stage forward from the saved input
        (stage-level remat): residuals never cross the shard_map
        boundary, so the sharded path needs no per-residual specs. Each
        rank's parameter gradients come back STACKED on a leading
        world axis (per-rank partial sums, no reduction in the
        backward); one :func:`collective.psum_tree` pass at optimizer
        time puts the whole step's gradient bytes on the wire at once —
        f32 ``psum`` for ``grad_transport="fp32"``, the two-leg
        int8-quantized reduction (REAL int8 values + f32 scales in the
        all-gather) for ``"int8"``."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ray_tpu.models.transformer import stage_forward, stage_loss
        from ray_tpu.parallel.collective import psum_tree
        from ray_tpu.util.jax_compat import shard_map

        c, K = self.config, self.n_chunks
        mesh, world = self.mesh, self.n_model
        axes = ("dp", "fsdp")
        bspec = self._batch_spec()
        rep = P()

        def smap(f, in_specs, out_specs):
            return jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                                     out_specs=out_specs,
                                     check_vma=False))

        def stack(tree):
            return jax.tree.map(lambda a: a[None], tree)

        fwd: Dict[str, Any] = {}
        bwd: Dict[str, Any] = {}
        if 0 in self.chunks:
            fwd["first"] = smap(
                lambda p, x: stage_forward(c, 0, K, p, x),
                (rep, bspec), bspec)

            def bwd_first(p, x, g):
                _, vjp = jax.vjp(
                    lambda q: stage_forward(c, 0, K, q, x), p)
                (gp,) = vjp(g)
                return stack(gp)
            bwd["first"] = smap(bwd_first, (rep, bspec, bspec), bspec)
        if K - 1 in self.chunks:
            def fwd_loss(p, x, ids, mask):
                h = stage_forward(c, K - 1, K, p, x)
                loss, n = stage_loss(c, p, h, ids, mask)
                n_tot = jax.lax.psum(n, axes)
                loss_w = jax.lax.psum(loss * n, axes) \
                    / jnp.maximum(n_tot, 1.0)
                return loss_w, n_tot
            fwd["loss"] = smap(fwd_loss, (rep, bspec, bspec, bspec),
                               (rep, rep))

            def bwd_loss(p, x, ids, mask, seed):
                # local loss is the mean over the LOCAL shard's tokens;
                # the cotangent rescales it so summed-over-ranks grads
                # equal the global-mean gradient: seed is the driver's
                # n_mb/N, local seed = seed * n_loc/n_mb = n_loc/N
                def f(q, xx):
                    h = stage_forward(c, K - 1, K, q, xx)
                    return stage_loss(c, q, h, ids, mask)[0]
                _, vjp = jax.vjp(f, p, x)
                n_loc = jnp.sum(mask[:, 1:])
                n_mb = jax.lax.psum(n_loc, axes)
                gp, gx = vjp(seed * n_loc / jnp.maximum(n_mb, 1.0))
                return stack(gp), gx
            bwd["loss"] = smap(bwd_loss,
                               (rep, bspec, bspec, bspec, rep),
                               (bspec, bspec))
        if any(0 < ch < K - 1 for ch in self.chunks):
            fwd["mid"] = smap(
                lambda p, x: stage_forward(c, 1, K, p, x),
                (rep, bspec), bspec)

            def bwd_mid(p, x, g):
                _, vjp = jax.vjp(
                    lambda q, xx: stage_forward(c, 1, K, q, xx), p, x)
                gp, gx = vjp(g)
                return stack(gp), gx
            bwd["mid"] = smap(bwd_mid, (rep, bspec, bspec),
                              (bspec, bspec))

        # the once-per-step gradient reduction: stacked per-rank
        # accumulators in, reduced (replicated) gradients out — the
        # stage's REAL bytes on the wire
        tr, block = self.grad_transport, self.quant_block_size
        sr = self.quant_stochastic

        def reduce_body(stacked, seed):
            local = jax.tree.map(lambda a: a[0], stacked)
            key = None
            if sr:
                idx = 0
                for a in axes:
                    idx = idx * mesh.shape[a] + jax.lax.axis_index(a)
                key = jax.random.fold_in(jax.random.PRNGKey(0xE8), seed)
                key = jax.random.fold_in(key, idx)
            return psum_tree(local, axes, world, transport=tr,
                             block_size=block, stochastic_rounding=sr,
                             key=key)
        self._reduce_prog = smap(reduce_body, (bspec, rep), rep)

        self._donate = jax.default_backend() != "cpu"
        self._m_fwd = fwd
        self._m_bwd = bwd
        self._acc = jax.jit(lambda a, b: jax.tree.map(jnp.add, a, b),
                            donate_argnums=(0,) if self._donate else ())

    def _role_for(self, chunk: int) -> str:
        if chunk == 0:
            return "first"
        if chunk == self.n_chunks - 1:
            return "loss"
        return "mid"

    def _fwd_for(self, chunk: int):
        if chunk == 0:
            return self._fwd_progs["first"]
        if chunk == self.n_chunks - 1:
            return self._fwd_progs["loss"]
        return self._fwd_progs["mid"]

    def _build_opt_program(self):
        """The fused per-stage optimizer step: clip-scale the
        accumulated grads by the DRIVER-reduced global norm, run the
        optax update on this stage's param slice, apply it — params,
        opt state and grads all donated, so the update is in-place on
        the stage and nothing heavier than a scalar ever crosses the
        driver."""
        import jax
        import jax.numpy as jnp
        import optax

        clip = self.clip_norm
        optimizer = self.optimizer
        opt_flat = self._opt_flat
        if opt_flat:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ray_tpu.parallel.sharding import flatten_tree
            world, block = self.n_model, self.quant_block_size
            flat_sh = self._flat_sharding()
            rep_sh = NamedSharding(self.mesh, P())

        def opt_step(params, opt_state, grads, global_sq_norm):
            if clip is not None:
                gn = jnp.sqrt(global_sq_norm.astype(jnp.float32))
                # exactly optax.clip_by_global_norm's select, with the
                # cross-stage norm in place of the local one
                scale = jnp.where(gn < clip, 1.0, clip / gn)
                grads = jax.tree.map(lambda g: g * scale, grads)
            if opt_flat:
                # cross-replica sharded update over the stage mesh
                # (arXiv:2004.13336): scatter grads + master params to
                # flat 1/N shards, update only the local optimizer
                # shard, gather fresh params via the constraint back
                gflat = flatten_tree(grads, world, block,
                                     constrain_to=flat_sh)
                pflat = flatten_tree(params, world, block,
                                     constrain_to=flat_sh)
                updates, new_opt = optimizer.update(
                    gflat, opt_state, pflat)
                new_pflat = optax.apply_updates(pflat, updates)
                new_params = jax.tree.map(
                    lambda p, f: jax.lax.with_sharding_constraint(
                        f[:p.size].reshape(p.shape), rep_sh),
                    params, new_pflat)
                return new_params, new_opt
            updates, new_opt = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_opt

        self._opt_prog = jax.jit(
            opt_step,
            donate_argnums=(0, 1, 2) if self._donate else ())

    # ------------------------------------------------------- mailboxes
    def feed(self, acts=None, grads=None, targets=None) -> None:
        """Batched mailbox fill: the driver front-loads a whole step's
        token microbatches, targets and loss seeds in ONE actor call
        per stage (``acts``/``grads`` keyed ``(chunk, mb)``,
        ``targets`` keyed ``mb``) instead of 3M unary puts — on a
        busy box the per-call overhead is the pipeline's fixed tax."""
        with self._cond:
            if acts:
                self._acts.update(acts)
            if grads:
                self._grads_in.update(grads)
            if targets:
                self._targets.update(targets)
            self._mbx_report_locked()
            self._cond.notify_all()

    def put_activation(self, chunk: int, i: int, x) -> None:
        with self._cond:
            self._acts[(chunk, i)] = x
            self._mbx_report_locked()
            self._cond.notify_all()

    def put_grad(self, chunk: int, i: int, g) -> None:
        with self._cond:
            self._grads_in[(chunk, i)] = g
            self._mbx_report_locked()
            self._cond.notify_all()

    def put_targets(self, i: int, input_ids, loss_mask=None) -> None:
        """Last stage only: the labels (and mask) microbatch the loss
        needs — fed by the driver alongside stage 0's token feed."""
        with self._cond:
            self._targets[i] = (input_ids, loss_mask)
            self._cond.notify_all()

    def abort(self) -> None:
        """Unblock any pending mailbox take with a typed error (driver
        cleanup after a neighbor stage died) AND drain every queued
        mailbox item. Mailbox keys are ``(chunk, microbatch)`` and
        repeat every step, so an item stranded by an aborted step would
        otherwise be silently consumed by the NEXT step's matching op —
        stale activations in, and the op that should have produced them
        starving into the mailbox deadline. Draining here makes an
        aborted stage immediately reusable."""
        with self._cond:
            self._abort = True
            self._acts.clear()
            self._grads_in.clear()
            self._targets.clear()
            self._vjps.clear()
            self._inputs.clear()
            self._grads = {}
            self._red_cache = None
            self._mbx_report_locked()
            self._cond.notify_all()

    def _take(self, box: Dict, key):
        deadline = time.monotonic() + self.mailbox_deadline_s
        with self._cond:
            while key not in box:
                if self._abort:
                    raise RuntimeError(
                        f"stage {self.stage} aborted waiting for "
                        f"{key}")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"stage {self.stage} starved waiting for "
                        f"{key} beyond pipeline_mailbox_deadline_s="
                        f"{self.mailbox_deadline_s} (neighbor stage "
                        f"dead?)")
                self._cond.wait(0.1)
            out = box.pop(key)
            self._mbx_report_locked()
            return out

    # ----------------------------------------------------- op helpers
    def _fwd_op(self, ch: int, i: int, x, tgt):
        """One forward op: returns the yieldable output — the
        activation, or the ``{"loss", "n_tokens"}`` dict on the last
        chunk — and saves what the matching backward needs: the vjp
        residuals on a single-device stage, the raw (placed) inputs on
        a mesh stage (whose backward recomputes)."""
        import jax
        import jax.numpy as jnp

        K = self.n_chunks
        if self.mesh is not None:
            x = self._place_batch(x)
            if ch == K - 1:
                ids, mask = tgt
                if mask is None:
                    import numpy as np
                    mask = np.ones(np.asarray(ids).shape, np.float32)
                ids = self._place_batch(ids)
                mask = self._place_batch(mask)
                loss, n = self._m_fwd["loss"](self.params[ch], x,
                                              ids, mask)
                self._inputs[(ch, i)] = (x, ids, mask)
                return {"loss": float(loss), "n_tokens": float(n)}
            out = self._m_fwd[self._role_for(ch)](self.params[ch], x)
            self._inputs[(ch, i)] = (x,)
            jax.block_until_ready(out)
            return out
        if ch == K - 1:
            ids, mask = tgt
            if mask is None:
                mask = jnp.ones_like(ids, dtype=jnp.float32)
            loss, vjp = self._fwd_for(ch)(self.params[ch], x, ids, mask)
            n = float(jnp.sum(mask[:, 1:]))
            out: Any = {"loss": float(loss), "n_tokens": n}
        else:
            out, vjp = self._fwd_for(ch)(self.params[ch], x)
            jax.block_until_ready(out)
        self._vjps[(ch, i)] = vjp
        return out

    def _bwd_op(self, ch: int, i: int, g):
        """One backward op: accumulates this chunk's parameter
        gradients in-actor and returns the upstream input-gradient
        (None on chunk 0). Mesh stages recompute the forward from the
        saved input and accumulate per-rank STACKED partials — the
        cross-rank reduction waits for :meth:`_reduced_grads`."""
        import jax

        if self.mesh is not None:
            saved = self._inputs.pop((ch, i))
            role = self._role_for(ch)
            if role == "loss":
                x, ids, mask = saved
                gp, gx = self._m_bwd["loss"](
                    self.params[ch], x, ids, mask,
                    self._place_scalar(g))
                out = gx if ch > 0 else None
            elif role == "first":
                gp = self._m_bwd["first"](self.params[ch], saved[0],
                                          self._place_batch(g))
                out = None
            else:
                gp, out = self._m_bwd["mid"](self.params[ch], saved[0],
                                             self._place_batch(g))
            self._red_cache = None
        else:
            parts = self._bwd(self._vjps.pop((ch, i)), g)
            gp = parts[0]
            out = parts[1] if ch > 0 else None
        self._grads[ch] = gp if self._grads.get(ch) is None \
            else self._acc(self._grads[ch], gp)
        jax.block_until_ready(out if out is not None
                              else self._grads[ch])
        return out

    # ------------------------------------------------------------ step
    def run(self, n_microbatches: int):
        """One pipeline step as a streaming generator: walks this
        stage's (interleaved) 1F1B order, blocking on the mailbox each
        op needs, and yields the op's output as its own stream item —
        the activation (F, non-last chunk), the (loss, n_tokens) pair
        (F, last chunk), the upstream input-gradient (B, chunk > 0) or
        the op duration (B, chunk 0). Records a ``STAGE_TICK`` span
        per compute AND per idle interval, labelled with phase and
        virtual-stage index: the timeline shows the bubbles."""
        import jax

        rec = _recorder()
        K = self.n_chunks
        self._stats = self._fresh_stats()
        with self._cond:
            self._abort = False
        self._vjps.clear()
        self._inputs.clear()
        self._grads = {}
        self._red_cache = None
        t_start = time.perf_counter()
        for op, i, ch in one_f_one_b_order(
                self.stage, self.n_stages, n_microbatches,
                self.n_virtual):
            t_wait = time.perf_counter()
            if op == "F":
                x = self._take(self._acts, (ch, i))
                tgt = self._take(self._targets, i) if ch == K - 1 \
                    else None
            else:
                g = self._take(self._grads_in, (ch, i))
            idle = time.perf_counter() - t_wait
            if rec is not None and idle > 1e-4:
                rec.record("STAGE_TICK", stage=self.stage, mb=i, vs=ch,
                           phase="idle", dur_s=round(idle, 6))
            t0 = time.perf_counter()
            if op == "F":
                out = self._fwd_op(ch, i, x, tgt)
            else:
                out = self._bwd_op(ch, i, g)
            dur = time.perf_counter() - t0
            st = self._stats
            st["busy_s"] += dur
            st["idle_s"] += idle
            st["fwd_s" if op == "F" else "bwd_s"] += dur
            st["ops"] += 1
            if rec is not None:
                rec.record("STAGE_TICK", stage=self.stage, mb=i, vs=ch,
                           phase="forward" if op == "F" else "backward",
                           dur_s=round(dur, 6))
                rec.maybe_flush()
            yield out if out is not None else {"dur_s": dur}
        self._stats["span_s"] = time.perf_counter() - t_start

    # ------------------------------------------- fused optimizer step
    def _require_grads(self) -> None:
        missing = [c for c in self.chunks if self._grads.get(c) is None]
        if missing:
            raise RuntimeError(
                f"stage {self.stage}: no accumulated grads for chunks "
                f"{missing} (run a step first)")

    def _reduced_grads(self):
        """The step's accumulated gradients, reduced across the stage
        mesh (identity on single-device stages). On mesh stages this is
        THE stage communication op — one ``psum_tree`` pass over the
        whole accumulated gradient per step: plain f32 ``psum`` for
        fp32 transport, the two-leg int8 reduction (real int8 bytes in
        the gather) for int8. Cached until the next backward/step."""
        if self.mesh is None:
            return {c: self._grads[c] for c in self.chunks}
        if self._red_cache is None:
            import numpy as np
            stacked = {c: self._grads[c] for c in self.chunks}
            self._red_cache = self._reduce_prog(
                stacked, np.uint32(self._step_count))
        return self._red_cache

    def grad_sq_norm(self) -> float:
        """Squared L2 norm of this stage's accumulated grads — the
        stage's contribution to the global clip norm (a single f32
        scalar; the only gradient-derived value that ever reaches the
        driver in train mode)."""
        import jax
        import jax.numpy as jnp

        self._require_grads()
        if self._sqn is None:
            self._sqn = jax.jit(lambda g: sum(
                jnp.sum(jnp.square(x.astype(jnp.float32)))
                for x in jax.tree.leaves(g)))
        return float(self._sqn(self._reduced_grads()))

    def apply_opt(self, global_sq_norm: float) -> Dict[str, float]:
        """The per-stage fused optimizer step: one jitted program
        (clip-scale + optax update + apply, donated buffers) over the
        stage's accumulated grads. Grads/params/opt-state never leave
        the actor; returns only scalar metrics."""
        import jax
        import jax.numpy as jnp

        if self.optimizer is None:
            raise RuntimeError("stage built with train=False has no "
                               "optimizer (pass train=True)")
        self._require_grads()
        t0 = time.perf_counter()
        grads = self._reduced_grads()
        self.params, self.opt_state = self._opt_prog(
            self.params, self.opt_state, grads,
            jnp.float32(global_sq_norm))
        jax.block_until_ready(self.params)
        self._grads = {}
        self._red_cache = None
        self._step_count += 1
        dur = time.perf_counter() - t0
        st = self._stats
        st["busy_s"] += dur
        st["opt_s"] += dur
        rec = _recorder()
        if rec is not None:
            rec.record("STAGE_TICK", stage=self.stage, phase="opt",
                       dur_s=round(dur, 6))
            rec.maybe_flush()
        return {"grad_norm": float(global_sq_norm) ** 0.5,
                "opt_s": dur, "step": self._step_count}

    # ----------------------------------------------------- checkpoint
    def stage_checkpoint(self) -> Dict[str, Any]:
        """Host copy of the stage's train state, keyed by global chunk
        id — :func:`merge_stage_checkpoints` reassembles the canonical
        single-program layout from all stages' parts."""
        import numpy as np

        import jax

        host = lambda t: jax.tree.map(np.asarray, t)  # noqa: E731
        chunks = {c: host(p) for c, p in self.params.items()}
        opt = None
        if self.opt_state is not None:
            opt = host(self.opt_state)
            if self._opt_flat:
                # flat 1/N shards back to the canonical param-shaped
                # layout, so a 3D checkpoint merges/reloads like any
                # other (the flat layout is a residency optimization,
                # not a checkpoint format)
                from ray_tpu.parallel.sharding import unflatten_like
                opt = _map_param_subtrees(
                    opt, jax.tree.structure(chunks),
                    lambda sub: unflatten_like(chunks, sub))
        part: Dict[str, Any] = {
            "stage": self.stage,
            "n_stages": self.n_stages,
            "n_virtual": self.n_virtual,
            "chunks": chunks,
            "opt_state": opt,
            "step": self._step_count,
        }
        return part

    def load_state(self, part: Dict[str, Any]) -> None:
        """Load one part from :func:`split_train_state` (params keyed
        by this stage's chunk ids, opt state in the stage layout)."""
        import jax

        want = set(self.chunks)
        got = set(part["params"])
        if want != got:
            raise ValueError(
                f"stage {self.stage} hosts chunks {sorted(want)}, "
                f"checkpoint part carries {sorted(got)}")
        self.params = self._place_params(
            {int(c): p for c, p in part["params"].items()})
        if part.get("opt_state") is not None:
            if self.optimizer is None:
                raise RuntimeError("cannot load optimizer state into a "
                                   "train=False stage")
            opt = part["opt_state"]
            if self._opt_flat:
                # canonical param-shaped state back into flat 1/N
                # shards over the stage mesh
                from ray_tpu.parallel.sharding import flatten_tree
                world, block = self.n_model, self.quant_block_size
                flat_sh = self._flat_sharding()
                td = jax.tree.structure(self.params)
                place = jax.jit(lambda o: _map_param_subtrees(
                    o, td, lambda sub: flatten_tree(
                        sub, world, block, constrain_to=flat_sh)))
                self.opt_state = place(opt)
            else:
                self.opt_state = self._place_params(opt)
        self._step_count = int(part.get("step", 0))

    def stream_checkpoint(self):
        """:meth:`stage_checkpoint` as a stream: one block per param
        chunk, then one meta block carrying the (canonicalized) opt
        state and step count. Each block is its own stream item —
        exactly-once over the reliable layer — so the driver can
        forward a chunk's ref to its new owner while later chunks are
        still being host-copied, and the bytes move worker-to-worker
        (:meth:`load_state_blocks`) instead of round-tripping through
        the driver."""
        import numpy as np

        import jax

        host = lambda t: jax.tree.map(np.asarray, t)  # noqa: E731
        chunks: Dict[int, Any] = {}
        for c in self.chunks:
            chunks[c] = host(self.params[c])
            yield {"block": "params", "stage": self.stage, "chunk": c,
                   "params": chunks[c]}
        opt = None
        if self.opt_state is not None:
            opt = host(self.opt_state)
            if self._opt_flat:
                from ray_tpu.parallel.sharding import unflatten_like
                opt = _map_param_subtrees(
                    opt, jax.tree.structure(chunks),
                    lambda sub: unflatten_like(chunks, sub))
        yield {"block": "meta", "stage": self.stage,
               "n_stages": self.n_stages, "n_virtual": self.n_virtual,
               "opt_state": opt, "step": self._step_count}

    def load_state_blocks(self, *blocks) -> None:
        """Assemble a stage part from :meth:`stream_checkpoint` blocks
        and load it. The blocks arrive as actor-call object args, so
        when the driver passes the REFS a peer stage streamed, the
        payload is pulled worker-to-worker — the driver never
        materializes the bytes (the elastic same-grid reload path)."""
        part: Dict[str, Any] = {"params": {}}
        for b in blocks:
            if b.get("block") == "params":
                part["params"][int(b["chunk"])] = b["params"]
            else:
                part["opt_state"] = b.get("opt_state")
                part["step"] = b.get("step", 0)
        self.load_state(part)

    # ------------------------------------- serial (unpipelined) path
    def forward_one(self, chunk: int, i: int, x, input_ids=None,
                    loss_mask=None):
        """Unary forward for the serial chunk-by-chunk baseline: same
        jitted programs, no mailbox, one (chunk, microbatch) per
        call."""
        t0 = time.perf_counter()
        tgt = (input_ids, loss_mask) \
            if chunk == self.n_chunks - 1 and chunk > 0 else None
        res = self._fwd_op(chunk, i, x, tgt)
        self._tick("forward", i, chunk, time.perf_counter() - t0)
        return res

    def backward_one(self, chunk: int, i: int, g):
        t0 = time.perf_counter()
        out = self._bwd_op(chunk, i, g)
        self._tick("backward", i, chunk, time.perf_counter() - t0)
        return out

    def _tick(self, phase: str, i: int, chunk: int, dur: float) -> None:
        st = self._stats
        st["busy_s"] += dur
        st[("fwd_s" if phase == "forward" else "bwd_s")] += dur
        st["ops"] += 1
        rec = _recorder()
        if rec is not None:
            rec.record("STAGE_TICK", stage=self.stage, mb=i, vs=chunk,
                       phase=phase, dur_s=round(dur, 6))
            rec.maybe_flush()

    def reset_step(self) -> None:
        """Serial-path step reset (the streaming ``run`` resets
        itself)."""
        with self._cond:
            self._abort = False
        self._vjps.clear()
        self._inputs.clear()
        self._grads = {}
        self._red_cache = None
        self._stats = self._fresh_stats()
        self._t_reset = time.perf_counter()

    # ------------------------------------------------------- queries
    def step_stats(self) -> Dict[str, float]:
        st = dict(self._stats)
        if not st["span_s"] and getattr(self, "_t_reset", None):
            st["span_s"] = time.perf_counter() - self._t_reset
        st["device"] = str(self.device)
        st["stage"] = self.stage
        st["chunks"] = list(self.chunks)
        return st

    def get_grads(self):
        """Host copy of the accumulated parameter gradients, keyed by
        global chunk id (legacy fwd+bwd mode — in train mode grads are
        consumed in-actor by :meth:`apply_opt`). Mesh stages return the
        cross-rank REDUCED gradients (one reduction, cached)."""
        import numpy as np

        import jax
        if self.mesh is not None:
            if any(self._grads.get(c) is None for c in self.chunks):
                return {}
            return {c: jax.tree.map(np.asarray, g)
                    for c, g in self._reduced_grads().items()}
        return {c: jax.tree.map(np.asarray, g)
                for c, g in self._grads.items()}

    def ping(self) -> int:
        return self.stage


@dataclasses.dataclass
class PipelineStepResult:
    loss: float
    n_tokens: float
    #: per-microbatch (loss, n) pairs in microbatch order
    microbatch_losses: List[Tuple[float, float]]
    #: per-stage step_stats dicts
    stage_stats: List[Dict[str, float]]
    wall_s: float
    #: global gradient norm (train mode; None for fwd+bwd steps)
    grad_norm: Optional[float] = None
    #: optimizer step count after this step (train mode)
    step: Optional[int] = None

    @property
    def bubble_fraction(self) -> float:
        """Measured bubble: the mean over stages of the fraction of
        the step's wall clock each stage spent NOT computing."""
        if not self.wall_s:
            return 0.0
        fr = [1.0 - min(s["busy_s"] / self.wall_s, 1.0)
              for s in self.stage_stats]
        return sum(fr) / len(fr)


class MPMDPipeline:
    """Driver-side interleaved-1F1B scheduler over
    :class:`PipelineStage` actors.

    ``step(batch)`` splits the batch into ``n_microbatches`` along the
    batch axis, feeds chunk 0's token microbatches / the last chunk's
    targets and loss seeds, launches one streaming ``run`` per stage,
    and routes items (by ref) between neighbor chunks as
    ``streaming.wait_any`` reports them ready. The combined loss is
    the token-weighted mean of the per-microbatch losses — exactly the
    single-program ``lm_loss`` of the full batch.

    ``n_virtual > 1`` hosts that many round-robin virtual stage chunks
    per actor and drives the interleaved schedule — analytic bubble
    ``(S-1)/(v*M+S-1)``.

    ``train=True`` makes ``step`` a full train step: after the streams
    drain, the driver reduces the per-stage squared grad norms (one
    scalar per stage), then every stage runs its fused optimizer
    program concurrently — gradients, parameters and optimizer state
    never transit the driver. ``save_checkpoint()`` /
    ``load_checkpoint()`` move the canonical single-program state
    layout in and out (any ``n_virtual``).

    ``serial=True`` drives the same actors chunk-by-chunk with unary
    calls and full barriers — the no-overlap baseline the measured
    bubble fraction is compared against.
    """

    def __init__(self, config, n_stages: int = 2,
                 n_microbatches: int = 4, seed: int = 0,
                 serial: bool = False,
                 step_timeout_s: float = 300.0,
                 actor_options: Optional[Dict[str, Any]] = None,
                 remat_policies: Optional[Sequence[Optional[str]]] = None,
                 n_virtual: int = 1,
                 train: bool = False,
                 learning_rate: float = 1e-5,
                 weight_decay: float = 0.0,
                 clip_norm: Optional[float] = 1.0,
                 optimizer_factory=None,
                 mailbox_deadline_s: Optional[float] = None,
                 dp: int = 1,
                 fsdp: int = 1,
                 grad_transport: str = "fp32",
                 shard_weight_update: bool = False,
                 quant_block_size: Optional[int] = None,
                 quant_stochastic: bool = False,
                 stage_mesh: Optional[bool] = None,
                 placement_group=None):
        import ray_tpu
        from ray_tpu.core.config import get_config

        if n_stages < 2:
            raise ValueError("MPMDPipeline needs n_stages >= 2 "
                             "(use the plain train step otherwise)")
        if n_virtual < 1:
            raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
        if n_stages * n_virtual > config.n_layers:
            raise ValueError(
                f"n_stages*n_virtual = {n_stages * n_virtual} virtual "
                f"stages need at least that many layers, model has "
                f"{config.n_layers}")
        if dp < 1 or fsdp < 1:
            raise ValueError(f"dp/fsdp must be >= 1, got {dp}/{fsdp}")
        self.config = config
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.n_virtual = n_virtual
        self.n_chunks = n_stages * n_virtual
        self.serial = serial
        self.train = train
        self.dp = dp
        self.fsdp = fsdp
        self.n_model = dp * fsdp
        self._stage_mesh = (self.n_model > 1 if stage_mesh is None
                            else bool(stage_mesh))
        self.placement_group = placement_group
        self.step_timeout_s = step_timeout_s
        # resolve the mailbox deadline on the DRIVER (its config sees
        # _system_config overrides) and ship the value to every stage
        deadline = (mailbox_deadline_s if mailbox_deadline_s is not None
                    else get_config().pipeline_mailbox_deadline_s)
        opts = {"max_concurrency": 4, "max_restarts": 0}
        opts.update(actor_options or {})
        policies = remat_policies or [None] * n_stages
        self.stages = []
        for s in range(n_stages):
            stage_opts = dict(opts)
            if placement_group is not None:
                # gang → mesh hand-off: one stage actor per bundle of a
                # (typically SLICE_SPREAD) placement group — each stage
                # builds its dp×fsdp mesh from the devices of the host
                # its bundle reserved
                from ray_tpu.util.scheduling_strategies import (
                    PlacementGroupSchedulingStrategy)
                stage_opts["scheduling_strategy"] = \
                    PlacementGroupSchedulingStrategy(
                        placement_group,
                        placement_group_bundle_index=s)
                device_indices = list(range(self.n_model))
            else:
                device_indices = list(range(s * self.n_model,
                                            (s + 1) * self.n_model))
            cls = ray_tpu.remote(**stage_opts)(PipelineStage)
            self.stages.append(cls.remote(
                config, s, n_stages, seed=seed, device_index=s,
                remat_policy=policies[s], n_virtual=n_virtual,
                train=train, learning_rate=learning_rate,
                weight_decay=weight_decay, clip_norm=clip_norm,
                optimizer_factory=optimizer_factory,
                mailbox_deadline_s=deadline,
                dp=dp, fsdp=fsdp, grad_transport=grad_transport,
                shard_weight_update=shard_weight_update,
                quant_block_size=quant_block_size,
                quant_stochastic=quant_stochastic,
                stage_mesh=stage_mesh,
                device_indices=(device_indices if self._stage_mesh
                                else None)))
        ray_tpu.get([a.ping.remote() for a in self.stages], timeout=300)

    # ---------------------------------------------------------- steps
    def _split(self, batch: Dict[str, Any]):
        import numpy as np

        ids = np.asarray(batch["input_ids"])
        mask = batch.get("loss_mask")
        mask = np.asarray(mask) if mask is not None else None
        m = self.n_microbatches
        if ids.shape[0] % m:
            raise ValueError(f"batch {ids.shape[0]} not divisible by "
                             f"{m} microbatches")
        if self._stage_mesh and (ids.shape[0] // m) % self.n_model:
            raise ValueError(
                f"microbatch rows ({ids.shape[0] // m}) not divisible "
                f"by the stage mesh dp*fsdp = {self.dp}*{self.fsdp} "
                f"= {self.n_model}")
        ids_mb = np.split(ids, m)
        mask_mb = np.split(mask, m) if mask is not None else [None] * m
        # per-microbatch label-token counts — known to the driver
        # without running the model, so the last chunk's backward seeds
        # (d total / d loss_i = n_i / N) can be fed up front
        ns = [float(mk[:, 1:].sum()) if mk is not None
              else float(i.shape[0] * (i.shape[1] - 1))
              for i, mk in zip(ids_mb, mask_mb)]
        return ids_mb, mask_mb, ns

    def step(self, batch: Dict[str, Any]) -> PipelineStepResult:
        res = (self._step_serial if self.serial
               else self._step_1f1b)(batch)
        self._record_step_telemetry(batch, res)
        return res

    def _record_step_telemetry(self, batch: Dict[str, Any],
                               res: PipelineStepResult) -> None:
        """Per-step training telemetry into the fleet metrics plane:
        step wall, tokens/s, measured bubble, grad norm and an MFU
        gauge from the bench FLOP model — the live versions of what
        ``bench.py --pipeline`` records offline."""
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            m = runtime_metrics()
            m.train_step_wall.observe(res.wall_s)
            m.pipeline_bubble.set(res.bubble_fraction)
            m.train_loss.set(res.loss)
            if res.grad_norm is not None:
                m.train_grad_norm.set(res.grad_norm)
            if res.wall_s > 0:
                import numpy as np
                ids = np.asarray(batch["input_ids"])
                tokens_per_s = float(ids.size) / res.wall_s
                m.train_tokens_per_s.set(tokens_per_s)
                try:
                    from ray_tpu.parallel.mesh import chip_spec
                    achieved = tokens_per_s * \
                        self.config.flops_per_token(ids.shape[1])
                    peak = chip_spec().bf16_flops * self.n_stages
                    m.train_mfu.set(100.0 * achieved / peak)
                except Exception:
                    pass
            rec = _recorder()
            if rec is not None:
                rec.maybe_flush()
            w = None
            try:
                from ray_tpu.core.global_state import try_global_worker
                w = try_global_worker()
            except Exception:
                pass
            if w is not None and getattr(w, "metrics_reporter",
                                         None) is not None:
                w.metrics_reporter.maybe_report()
        except Exception:
            pass

    def _opt_tail(self) -> Tuple[Optional[float], Optional[int]]:
        """Train-mode tail after the backwards drain: reduce the
        per-stage squared grad norms (scalars), fan the global value
        back out, and run every stage's fused optimizer step
        concurrently. No gradient or parameter bytes through the
        driver — the reduction is S floats each way."""
        import ray_tpu

        if not self.train:
            return None, None
        sq = ray_tpu.get([a.grad_sq_norm.remote() for a in self.stages],
                         timeout=self.step_timeout_s)
        gsq = float(sum(sq))
        mets = ray_tpu.get([a.apply_opt.remote(gsq)
                            for a in self.stages],
                           timeout=self.step_timeout_s)
        return mets[0]["grad_norm"], mets[0]["step"]

    def _step_1f1b(self, batch: Dict[str, Any]) -> PipelineStepResult:
        import numpy as np

        import ray_tpu
        from ray_tpu.core import streaming

        S, M, v = self.n_stages, self.n_microbatches, self.n_virtual
        K = self.n_chunks
        ids_mb, mask_mb, ns = self._split(batch)
        total_n = sum(ns)
        t0 = time.perf_counter()
        hold = []  # keep routed refs alive until the step completes
        last = self.stages[-1]  # chunk K-1 lives on the last actor
        # batched prefeed: stage 0's token microbatches in one call,
        # the last stage's targets + loss cotangents (scalar n_i / N,
        # known up front) in another — 2 actor calls instead of 3M
        hold.append(self.stages[0].feed.remote(
            acts={(0, i): ids_mb[i] for i in range(M)}))
        hold.append(last.feed.remote(
            targets={i: (ids_mb[i], mask_mb[i]) for i in range(M)},
            grads={(K - 1, i): np.float32(ns[i] / total_n)
                   for i in range(M)}))
        gens = [a.run.options(num_returns="streaming").remote(M)
                for a in self.stages]
        orders = [one_f_one_b_order(s, S, M, v) for s in range(S)]
        cursors = [0] * S
        loss_refs: Dict[int, Any] = {}
        by_gen = {id(g): s for s, g in enumerate(gens)}
        active = list(gens)
        deadline = time.monotonic() + self.step_timeout_s
        try:
            while active:
                ready, _ = streaming.wait_any(
                    active, timeout=max(deadline - time.monotonic(), 0.0))
                if not ready:
                    raise TimeoutError(
                        f"pipeline step stalled: no stage produced an "
                        f"item within {self.step_timeout_s}s")
                for g in ready:
                    s = by_gen[id(g)]
                    try:
                        ref = g.next_ref(timeout=1.0)
                    except StopIteration:
                        active.remove(g)
                        continue
                    op, i, ch = orders[s][cursors[s]]
                    cursors[s] += 1
                    if op == "F" and ch < K - 1:
                        hold.append(
                            self.stages[(ch + 1) % S]
                            .put_activation.remote(ch + 1, i, ref))
                    elif op == "F":
                        # tiny loss dicts: batch the gets after drain
                        loss_refs[i] = ref
                    elif op == "B" and ch > 0:
                        hold.append(
                            self.stages[(ch - 1) % S]
                            .put_grad.remote(ch - 1, i, ref))
                    hold.append(ref)
            items = ray_tpu.get([loss_refs[i] for i in range(M)],
                                timeout=60)
            losses = {i: (it["loss"], it["n_tokens"])
                      for i, it in enumerate(items)}
            grad_norm, opt_step = self._opt_tail()
        except BaseException:
            self._cleanup(gens)
            raise
        wall = time.perf_counter() - t0
        stats = ray_tpu.get(
            [a.step_stats.remote() for a in self.stages], timeout=60)
        mb = [losses[i] for i in range(M)]
        loss = sum(l * n for l, n in mb) / total_n
        return PipelineStepResult(
            loss=loss, n_tokens=total_n, microbatch_losses=mb,
            stage_stats=stats, wall_s=wall, grad_norm=grad_norm,
            step=opt_step)

    def _step_serial(self, batch: Dict[str, Any]) -> PipelineStepResult:
        """No-overlap baseline: each microbatch walks every chunk's
        forward, then every chunk's backward, with a full barrier per
        call — what pipelining exists to beat."""
        import numpy as np

        import ray_tpu

        S, M, K = self.n_stages, self.n_microbatches, self.n_chunks
        ids_mb, mask_mb, ns = self._split(batch)
        total_n = sum(ns)
        t0 = time.perf_counter()
        ray_tpu.get([a.reset_step.remote() for a in self.stages],
                    timeout=60)
        losses = []
        for i in range(M):
            act = ray_tpu.get(
                self.stages[0].forward_one.remote(0, i, ids_mb[i]),
                timeout=self.step_timeout_s)
            for ch in range(1, K):
                actor = self.stages[ch % S]
                out = actor.forward_one.remote(
                    ch, i, act, ids_mb[i], mask_mb[i]) \
                    if ch == K - 1 else \
                    actor.forward_one.remote(ch, i, act)
                act = ray_tpu.get(out, timeout=self.step_timeout_s)
            losses.append((act["loss"], act["n_tokens"]))
            g: Any = np.float32(ns[i] / total_n)
            for ch in range(K - 1, -1, -1):
                g = ray_tpu.get(
                    self.stages[ch % S].backward_one.remote(ch, i, g),
                    timeout=self.step_timeout_s)
        grad_norm, opt_step = self._opt_tail()
        wall = time.perf_counter() - t0
        stats = ray_tpu.get(
            [a.step_stats.remote() for a in self.stages], timeout=60)
        loss = sum(l * n for l, n in losses) / total_n
        return PipelineStepResult(
            loss=loss, n_tokens=total_n, microbatch_losses=losses,
            stage_stats=stats, wall_s=wall, grad_norm=grad_norm,
            step=opt_step)

    # ---------------------------------------------------- checkpoints
    def save_checkpoint(self) -> Dict[str, Any]:
        """Gather per-stage parts and merge them into the canonical
        single-program ``{"params", "opt_state", "step"}`` layout
        (checkpointing is an explicit call, not per-step traffic)."""
        import ray_tpu
        parts = ray_tpu.get(
            [a.stage_checkpoint.remote() for a in self.stages],
            timeout=self.step_timeout_s)
        return merge_stage_checkpoints(self.config, parts)

    def load_checkpoint(self, state: Dict[str, Any]) -> None:
        """Load a canonical train state — saved from ANY
        ``(n_stages, n_virtual)`` layout — into this pipeline."""
        import ray_tpu
        parts = split_train_state(self.config, state, self.n_stages,
                                  self.n_virtual)
        ray_tpu.get(
            [a.load_state.remote(p)
             for a, p in zip(self.stages, parts)],
            timeout=self.step_timeout_s)

    def stream_checkpoint_refs(self, timeout_s: Optional[float] = None
                               ) -> List[List[Any]]:
        """Per-stage block-ref lists from
        :meth:`PipelineStage.stream_checkpoint`, gathered over the
        streaming layer with a bounded overall deadline. The refs can
        be forwarded straight into another pipeline's
        ``load_state_blocks`` calls (worker-to-worker byte movement) or
        fetched and merged via :func:`merge_stage_checkpoints`. A stage
        actor dying mid-stream surfaces the streaming layer's typed
        error here — never a hang."""
        from ray_tpu.core import streaming

        timeout_s = timeout_s if timeout_s is not None \
            else self.step_timeout_s
        gens = [a.stream_checkpoint.options(
            num_returns="streaming").remote() for a in self.stages]
        blocks: List[List[Any]] = [[] for _ in self.stages]
        by_gen = {id(g): s for s, g in enumerate(gens)}
        active = list(gens)
        deadline = time.monotonic() + timeout_s
        try:
            while active:
                ready, _ = streaming.wait_any(
                    active,
                    timeout=max(deadline - time.monotonic(), 0.0))
                if not ready:
                    raise TimeoutError(
                        f"checkpoint stream stalled: no stage produced "
                        f"a block within {timeout_s}s")
                for g in ready:
                    try:
                        ref = g.next_ref(timeout=1.0)
                    except StopIteration:
                        active.remove(g)
                        continue
                    blocks[by_gen[id(g)]].append(ref)
        except BaseException:
            for g in gens:
                try:
                    g.close()
                except Exception:
                    pass
            raise
        return blocks

    def save_checkpoint_streaming(self,
                                  timeout_s: Optional[float] = None,
                                  refs: Optional[List[List[Any]]] = None
                                  ) -> Dict[str, Any]:
        """The canonical checkpoint via the streaming gather — same
        result as :meth:`save_checkpoint`, but each stage's state
        arrives as per-chunk blocks (exactly-once stream items) instead
        of one monolithic unary return. Pass ``refs`` from an earlier
        :meth:`stream_checkpoint_refs` call to merge without streaming
        the stages a second time (the elastic path forwards the same
        refs peer-to-peer AND keeps a driver-side merged copy)."""
        import ray_tpu

        timeout_s = timeout_s if timeout_s is not None \
            else self.step_timeout_s
        if refs is None:
            refs = self.stream_checkpoint_refs(timeout_s)
        parts = []
        for stage_refs in refs:
            items = ray_tpu.get(stage_refs, timeout=timeout_s)
            part: Dict[str, Any] = {"chunks": {}}
            for b in items:
                if b.get("block") == "params":
                    part["chunks"][int(b["chunk"])] = b["params"]
                else:
                    part.update(
                        stage=b["stage"], n_stages=b["n_stages"],
                        n_virtual=b["n_virtual"],
                        opt_state=b.get("opt_state"),
                        step=b.get("step", 0))
            parts.append(part)
        return merge_stage_checkpoints(self.config, parts)

    # -------------------------------------------------------- cleanup
    def abort(self) -> None:
        """Quiesce every stage: unblock pending mailbox takes with a
        typed error and drain queued items, waiting (bounded) for the
        acks — the elastic re-plan entry point. After this the stages
        are idle and immediately reusable; nothing is left to trip the
        mailbox take-deadline."""
        self._cleanup([])

    def _cleanup(self, gens) -> None:
        """Failure path: unblock + drain every stage mailbox, then
        drop all stream state — typed error out, no hang, no leaked
        stream refs. The abort acks are awaited (bounded, dead actors
        skipped) so a fire-and-forget abort cannot land inside the
        NEXT step's freshly-started ``run`` and kill it spuriously."""
        import ray_tpu
        refs = []
        for a in self.stages:
            try:
                refs.append(a.abort.remote())
            except Exception:
                pass
        for g in gens:
            try:
                g.close()
            except Exception:
                pass
        for r in refs:
            try:
                ray_tpu.get(r, timeout=5.0)
            except Exception:
                pass

    def grads(self, timeout: float = 120.0):
        """Per-stage accumulated parameter-gradient trees (host),
        keyed by global chunk id; with ``n_virtual == 1`` each stage's
        single chunk tree is returned bare (legacy shape)."""
        import ray_tpu
        parts = ray_tpu.get(
            [a.get_grads.remote() for a in self.stages],
            timeout=timeout)
        if self.n_virtual == 1:
            return [p[s] for s, p in enumerate(parts)]
        return parts

    def shutdown(self) -> None:
        import ray_tpu
        for a in self.stages:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
