"""Collective communication API.

Equivalent of the reference's ``ray.util.collective``
(``python/ray/util/collective/collective.py``: ``init_collective_group``
:120, ``allreduce`` :258, ``barrier`` :298, ``reduce`` :311, ``broadcast``
:373, ``allgather`` :423, ``reducescatter`` :472, ``send``/``recv``)
re-designed for TPU, where eager collectives don't exist — every collective
is staged into a compiled XLA program (SURVEY.md §7 hard part 1).

Backends:

- ``"xla"`` — in-graph collectives over a device mesh. Eager-looking calls
  dispatch cached jitted stubs keyed by (group, op, shape, dtype); within a
  process they run over the caller's local devices; once
  ``jax.distributed`` is initialized (multi-host rendezvous below), the
  same stubs are global-SPMD and ride ICI/DCN. This replaces NCCL.
- ``"host"`` — control-plane collectives for cross-actor *host* (CPU)
  values, via the controller KV store (the role GLOO plays in the
  reference). Rendezvous mirrors the reference's ``NCCLUniqueIDStore``
  named actor (``collective_group/nccl_collective_group.py:28-68``) using
  the internal KV instead.

``quantized_allreduce`` / ``quantized_reducescatter`` are the int8
blockwise-quantized variants (EQuARX, arXiv:2506.17615): local shards are
quantized against per-block f32 scales, reduced in f32 accumulators, the
reduced chunks requantized for the gather leg, and dequantized at the
edge. Wire format in ``parallel.quantization``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

_groups: Dict[str, "Group"] = {}
_lock = threading.Lock()


class Group:
    def __init__(self, name: str, world_size: int, rank: int, backend: str):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        # Sequence numbers are tracked per op kind (and per peer pair for
        # p2p) so an asymmetric op — a send between two ranks, say — can't
        # desynchronize the keys the whole group uses for its next barrier.
        self._seqs: Dict[str, int] = {}
        self._stubs: Dict[Tuple, object] = {}
        self._mesh = None
        # Host-backend KV hygiene: keys this rank wrote, per op kind, as
        # {kind: [(seq, key), ...]}; consumed lazily by _gc (see below).
        self._written: Dict[str, List[Tuple[int, bytes]]] = {}
        self._bcast_pending: List[Tuple[bytes, List[bytes]]] = []

    # ---- xla backend ----
    def mesh(self):
        if self._mesh is None:
            from ray_tpu.parallel.mesh import MeshSpec, build_mesh
            import jax
            devices = jax.devices()
            if self.world_size > len(devices):
                raise ValueError(
                    f"xla collective group {self.name!r}: world_size "
                    f"{self.world_size} exceeds {len(devices)} devices")
            self._mesh = build_mesh(MeshSpec(tp=self.world_size),
                                    devices[:self.world_size])
        return self._mesh

    def _stub(self, op: str, shape, dtype, **kw):
        key = (op, tuple(shape), str(dtype), tuple(sorted(kw.items())))
        stub = self._stubs.get(key)
        if stub is None:
            stub = _build_stub(self.mesh(), op, **kw)
            self._stubs[key] = stub
        return stub

    def next_seq(self, kind: str) -> int:
        self._seqs[kind] = self._seqs.get(kind, 0) + 1
        return self._seqs[kind]


def axis_world_size(mesh, axes) -> int:
    """Total rank count across the named mesh axes."""
    n = 1
    for a in axes:
        n *= int(mesh.shape[a])
    return n


def quantized_psum(x, axes, world: int,
                   block_size: Optional[int] = None,
                   stochastic_rounding: bool = False,
                   key=None, mean: bool = False):
    """Two-leg int8-quantized all-reduce of a per-rank tensor, callable
    INSIDE a ``shard_map`` region (EQuARX, arXiv:2506.17615): quantize
    the local payload blockwise, accumulate partial sums in f32 via
    ``psum_scatter``, REquantize the reduced chunk, then all-gather
    int8 values + per-block f32 scales — so the gather leg moves real
    int8 bytes across the ``axes`` links, not f32 tensors — and
    dequantize at the edge. Chunk boundaries round up to whole quant
    blocks so no block straddles two ranks' chunks. Returns the reduced
    tensor in ``x``'s shape (f32).

    This is the reduction the eager collective stubs compile
    (:func:`_build_stub`) AND the one each pipeline stage runs over its
    own dp×fsdp mesh (``parallel.mpmd_pipeline``) — one wire format,
    every topology."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel import quantization as qz

    block = int(block_size or qz.DEFAULT_BLOCK_SIZE)
    n = x.size
    chunk = qz._padded_len(-(-n // world), block)
    padded = jnp.pad(x.astype(jnp.float32).reshape(-1),
                     (0, chunk * world - n))
    q, s = qz.quantize_int8(padded, block, stochastic_rounding, key)
    sent = qz.dequantize_int8(q, s)                    # f32 accum leg
    mine = jax.lax.psum_scatter(sent.reshape(world, chunk), axes,
                                scatter_dimension=0, tiled=False)
    q2, s2 = qz.quantize_int8(mine, block)             # gather leg
    qg = jax.lax.all_gather(q2, axes, axis=0, tiled=False)
    sg = jax.lax.all_gather(s2, axes, axis=0, tiled=False)
    full = (qg.astype(jnp.float32) * sg[..., None]).reshape(-1)
    if mean:
        full = full / world
    return full[:n].reshape(x.shape)


def psum_tree(tree, axes, world: int, transport: str = "fp32",
              block_size: Optional[int] = None,
              stochastic_rounding: bool = False, key=None,
              mean: bool = False):
    """Reduce every leaf of a pytree across the named mesh axes, inside
    a ``shard_map`` region: ``transport="fp32"`` is a plain ``psum``
    (exact); ``"int8"`` routes each leaf through
    :func:`quantized_psum` — real int8 values + f32 scales on the
    gather leg. With ``stochastic_rounding`` each leaf folds its index
    into ``key`` so no two leaves share a rounding stream."""
    import jax

    if transport == "fp32":
        red = jax.lax.pmean if mean else jax.lax.psum
        return jax.tree.map(lambda g: red(g, axes), tree)
    leaves, treedef = jax.tree.flatten(tree)
    out = []
    for i, g in enumerate(leaves):
        k = jax.random.fold_in(key, i) if key is not None else None
        out.append(quantized_psum(
            g, axes, world, block_size=block_size,
            stochastic_rounding=stochastic_rounding, key=k, mean=mean))
    return jax.tree.unflatten(treedef, out)


def _build_stub(mesh, op: str, **kw):
    """Compile one collective as a shard_map program over the mesh.

    Eager-call semantics match ``ray.util.collective``'s multi-rank model
    mapped onto one SPMD program: the input is the per-rank tensors stacked
    on dim 0 (world, \\*shape); ranks = mesh devices in axis order.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from ray_tpu.util.jax_compat import shard_map

    axes = mesh.axis_names
    reduce_op = kw.get("reduce_op", "sum")

    def _red(x, ax):
        return {"sum": jax.lax.psum, "max": jax.lax.pmax,
                "min": jax.lax.pmin, "mean": jax.lax.pmean}[reduce_op](x, ax)

    if op == "allreduce":
        # (world, *shape) sharded on dim 0 -> reduced (*shape), replicated
        def f(x):
            return _red(x[0], axes)
        return jax.jit(shard_map(
            f, mesh=mesh, in_specs=P(axes), out_specs=P(),
            check_vma=False))
    if op == "allgather":
        # (world, *shape) sharded -> (world, *shape) replicated everywhere
        def f(x):
            return jax.lax.all_gather(x[0], axes, axis=0, tiled=False)
        return jax.jit(shard_map(
            f, mesh=mesh, in_specs=P(axes), out_specs=P(),
            check_vma=False))
    if op == "reducescatter":
        # (world, *shape) -> (world, shape[0]/world, ...): rank i gets the
        # i-th chunk of the elementwise sum
        import jax.numpy as jnp
        world = int(mesh.devices.size)

        def f(x):
            summed = _red(x[0], axes)
            return jnp.stack(jnp.split(summed, world, axis=0))
        return jax.jit(shard_map(
            f, mesh=mesh, in_specs=P(axes), out_specs=P(),
            check_vma=False))
    if op in ("quantized_allreduce", "quantized_reducescatter"):
        # Two-leg quantized reduction (EQuARX, arXiv:2506.17615): each
        # rank int8-quantizes its local payload (send side), partial sums
        # accumulate in f32 via psum_scatter, the reduced chunk is
        # REquantized for the gather leg — so the all-gather moves int8
        # values + per-block f32 scales, not f32 tensors — and the edge
        # dequantizes. Chunk boundaries are rounded up to whole quant
        # blocks so no block ever straddles two ranks' chunks.
        import jax.numpy as jnp

        world = int(mesh.devices.size)
        block = kw.get("block_size")
        sr = bool(kw.get("stochastic_rounding", False))

        def f(x, seed):
            local = x[0]
            key = None
            if sr:
                idx = 0
                for a in axes:
                    idx = idx * mesh.shape[a] + jax.lax.axis_index(a)
                key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
                key = jax.random.fold_in(key, idx)
            out = quantized_psum(local, axes, world, block_size=block,
                                 stochastic_rounding=sr, key=key,
                                 mean=reduce_op == "mean")
            if op == "quantized_reducescatter":
                return jnp.stack(jnp.split(out, world, axis=0))
            return out
        return jax.jit(shard_map(
            f, mesh=mesh, in_specs=(P(axes), P()), out_specs=P(),
            check_vma=False))
    raise ValueError(f"unknown collective {op}")


# ------------------------------------------------------------------ API
def init_collective_group(world_size: int, rank: int,
                          backend: str = "xla",
                          group_name: str = "default") -> None:
    """Join a collective group (call from every participating actor)."""
    with _lock:
        _groups[group_name] = Group(group_name, world_size, rank, backend)
    if backend == "host":
        _host_rendezvous(group_name, world_size, rank)


def _actor_join(actor_self, world_size, rank, backend, group_name):
    init_collective_group(world_size, rank, backend, group_name)
    return rank


def create_collective_group(actors: List, world_size: int, ranks: List[int],
                            backend: str = "xla",
                            group_name: str = "default") -> None:
    """Declarative creation (reference: ``create_collective_group`` :151):
    tell each actor to join via the generic ``__ray_call__`` invoke."""
    import ray_tpu
    ray_tpu.get([
        a.__ray_call__.remote(_actor_join, world_size, r, backend, group_name)
        for a, r in zip(actors, ranks)], timeout=300)


def destroy_collective_group(group_name: str = "default") -> None:
    with _lock:
        _groups.pop(group_name, None)


def get_group(group_name: str = "default") -> Group:
    g = _groups.get(group_name)
    if g is None:
        raise ValueError(f"collective group {group_name!r} not initialized")
    return g


def get_rank(group_name: str = "default") -> int:
    return get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return get_group(group_name).world_size


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


# ---- xla-backend data-plane collectives (device arrays) ----
def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    g = get_group(group_name)
    if g.backend == "host":
        return _host_allreduce(g, tensor, op)
    return g._stub("allreduce", tensor.shape, tensor.dtype,
                   reduce_op=op)(tensor)


def allgather(tensor, group_name: str = "default"):
    g = get_group(group_name)
    if g.backend == "host":
        return _host_allgather(g, tensor)
    return g._stub("allgather", tensor.shape, tensor.dtype)(tensor)


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    g = get_group(group_name)
    if g.backend == "host":
        return _host_reducescatter(g, tensor, op)
    return g._stub("reducescatter", tensor.shape, tensor.dtype,
                   reduce_op=op)(tensor)


def _check_quant_op(op: str) -> None:
    if op not in ("sum", "mean"):
        raise ValueError(
            f"quantized collectives support op='sum'/'mean', got {op!r} "
            "(max/min don't survive blockwise requantization)")


def quantized_allreduce(tensor, group_name: str = "default",
                        op: str = "sum",
                        block_size: Optional[int] = None,
                        stochastic_rounding: bool = False):
    """All-reduce with int8 blockwise-quantized transport: quantize local
    shards, reduce in f32 accumulators, requantize for the gather leg,
    dequantize at the edge. Same calling convention as :func:`allreduce`;
    the result carries the quantization error of both wire legs (bounded
    by half a quantization step per leg per block — see
    ``parallel.quantization``)."""
    _check_quant_op(op)
    g = get_group(group_name)
    if g.backend == "host":
        return _host_quantized_allreduce(g, tensor, op, block_size)
    seed = g.next_seq("q_ar") if stochastic_rounding else 0
    stub = g._stub("quantized_allreduce", tensor.shape, tensor.dtype,
                   reduce_op=op, block_size=block_size,
                   stochastic_rounding=stochastic_rounding)
    return stub(tensor, np.uint32(seed))


def quantized_reducescatter(tensor, group_name: str = "default",
                            op: str = "sum",
                            block_size: Optional[int] = None,
                            stochastic_rounding: bool = False):
    """Reduce-scatter with int8-quantized transport; same calling
    convention (and chunking) as :func:`reducescatter`."""
    _check_quant_op(op)
    g = get_group(group_name)
    if g.backend == "host":
        summed = _host_quantized_allreduce(g, tensor, op, block_size)
        if tensor.shape[0] % g.world_size:
            raise ValueError(
                f"reducescatter dim 0 ({tensor.shape[0]}) not divisible "
                f"by world size {g.world_size}")
        return np.split(summed, g.world_size, axis=0)[g.rank]
    if tensor.shape[1] % g.world_size:
        raise ValueError(
            f"reducescatter chunk dim ({tensor.shape[1]}) not divisible "
            f"by world size {g.world_size}")
    seed = g.next_seq("q_rs") if stochastic_rounding else 0
    stub = g._stub("quantized_reducescatter", tensor.shape, tensor.dtype,
                   reduce_op=op, block_size=block_size,
                   stochastic_rounding=stochastic_rounding)
    return stub(tensor, np.uint32(seed))


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = "sum"):
    out = allreduce(tensor, group_name, op)
    g = get_group(group_name)
    return out if g.rank == dst_rank else tensor


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = get_group(group_name)
    if g.backend == "host":
        return _host_broadcast(g, tensor, src_rank)
    # in-graph: a broadcast is an all-gather of the source shard; with a
    # replicated input this is identity under SPMD
    return tensor


def barrier(group_name: str = "default") -> None:
    g = get_group(group_name)
    if g.backend == "host":
        _host_barrier(g)
        return
    # device barrier: tiny allreduce
    import jax.numpy as jnp
    allreduce(jnp.zeros((g.world_size,), jnp.float32), group_name)


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    g = get_group(group_name)
    seq = g.next_seq(f"p2p/{g.rank}->{dst_rank}")
    _kv_put(_key(g, f"p2p/{g.rank}->{dst_rank}/{seq}"),
            _dumps(np.asarray(tensor)))


def recv(shape, dtype, src_rank: int, group_name: str = "default"):
    g = get_group(group_name)
    seq = g.next_seq(f"p2p/{src_rank}->{g.rank}")
    key = _key(g, f"p2p/{src_rank}->{g.rank}/{seq}")
    return _loads(_kv_take(key)).reshape(shape).astype(dtype)


# ------------------------------------------------ host backend internals
def _kv(self=None):
    from ray_tpu.core.global_state import global_worker
    return global_worker()


def _key(g: Group, suffix: str) -> bytes:
    return f"collective/{g.name}/{suffix}".encode()


def _dumps(arr: np.ndarray) -> bytes:
    import io
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def _loads(blob: bytes) -> np.ndarray:
    import io
    return np.load(io.BytesIO(blob), allow_pickle=False)


def _kv_put(key: bytes, value: bytes) -> None:
    _kv().kv_put(key, value, ns="collective")


def _kv_take(key: bytes, timeout: float = 120.0) -> bytes:
    w = _kv()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = w.kv_get(key, ns="collective")
        if v is not None:
            w.kv_del(key, ns="collective")
            return v
        time.sleep(0.005)
    raise TimeoutError(f"collective recv timed out on {key!r}")


def _kv_wait(key: bytes, timeout: float = 120.0) -> bytes:
    w = _kv()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = w.kv_get(key, ns="collective")
        if v is not None:
            return v
        time.sleep(0.005)
    raise TimeoutError(f"collective wait timed out on {key!r}")


def _host_rendezvous(group_name: str, world_size: int, rank: int) -> None:
    # Join keys persist for the group's lifetime (one tiny key per rank):
    # stragglers that rendezvous late must still find every key.
    g = get_group(group_name)
    _kv_put(_key(g, f"join/{rank}"), b"1")
    for r in range(world_size):
        _kv_wait(_key(g, f"join/{r}"))


def _gc_symmetric(g: Group, kind: str, seq: int, key: bytes) -> None:
    """Lag-2 GC for symmetric ops (every rank writes and reads each seq).

    When this rank starts seq s, every rank has started s-1 (this rank
    finished s-1 only after reading all ranks' s-1 keys, which they write
    on entry), hence every rank has finished s-2 and read our s-2 key —
    so our keys with seq <= s-2 are dead and safe to delete.
    """
    written = g._written.setdefault(kind, [])
    w = _kv()
    while written and written[0][0] <= seq - 2:
        _, old_key = written.pop(0)
        w.kv_del(old_key, ns="collective")
    written.append((seq, key))


def _host_allreduce(g: Group, tensor, op: str):
    arr = np.asarray(tensor)
    seq = g.next_seq("ar")
    key = _key(g, f"ar/{seq}/{g.rank}")
    _gc_symmetric(g, "ar", seq, key)
    _kv_put(key, _dumps(arr))
    parts = [_loads(_kv_wait(_key(g, f"ar/{seq}/{r}")))
             for r in range(g.world_size)]
    stack = np.stack(parts)
    out = {"sum": stack.sum(0), "mean": stack.mean(0),
           "max": stack.max(0), "min": stack.min(0)}[op]
    return out


def _host_allgather(g: Group, tensor):
    arr = np.asarray(tensor)
    seq = g.next_seq("ag")
    key = _key(g, f"ag/{seq}/{g.rank}")
    _gc_symmetric(g, "ag", seq, key)
    _kv_put(key, _dumps(arr))
    return [_loads(_kv_wait(_key(g, f"ag/{seq}/{r}")))
            for r in range(g.world_size)]


def _host_reducescatter(g: Group, tensor, op: str):
    """Host-backend reduce-scatter: every rank contributes its local
    tensor and takes home the ``rank``-th dim-0 chunk of the elementwise
    reduction. Symmetric (every rank writes and reads each seq), so the
    lag-2 GC argument holds exactly as for allreduce/allgather."""
    arr = np.asarray(tensor)
    if arr.shape[0] % g.world_size:
        raise ValueError(
            f"reducescatter dim 0 ({arr.shape[0]}) not divisible by "
            f"world size {g.world_size}")
    seq = g.next_seq("rs")
    key = _key(g, f"rs/{seq}/{g.rank}")
    _gc_symmetric(g, "rs", seq, key)
    _kv_put(key, _dumps(arr))
    parts = [_loads(_kv_wait(_key(g, f"rs/{seq}/{r}")))
             for r in range(g.world_size)]
    stack = np.stack(parts)
    out = {"sum": stack.sum(0), "mean": stack.mean(0),
           "max": stack.max(0), "min": stack.min(0)}[op]
    return np.split(out, g.world_size, axis=0)[g.rank]


def _host_quantized_allreduce(g: Group, tensor, op: str,
                              block_size: Optional[int]):
    """Host-backend quantized all-reduce: each rank publishes int8 block
    values + f32 scales (the actual KV wire bytes shrink ~4x vs the f32
    payload of ``_host_allreduce``); readers dequantize into f32
    accumulators. Single-leg — there is no separate gather hop to
    requantize on the KV-store topology."""
    from ray_tpu.parallel import quantization as qz

    block = int(block_size or qz.DEFAULT_BLOCK_SIZE)
    arr = np.asarray(tensor)
    q, s = qz.quantize_int8_np(arr, block)
    seq = g.next_seq("qar")
    qkey = _key(g, f"qar/{seq}/q/{g.rank}")
    skey = _key(g, f"qar/{seq}/s/{g.rank}")
    _gc_symmetric(g, "qar.q", seq, qkey)
    _gc_symmetric(g, "qar.s", seq, skey)
    _kv_put(qkey, _dumps(q))
    _kv_put(skey, _dumps(s))
    out = np.zeros(arr.shape, np.float32)
    for r in range(g.world_size):
        rq = _loads(_kv_wait(_key(g, f"qar/{seq}/q/{r}")))
        rs = _loads(_kv_wait(_key(g, f"qar/{seq}/s/{r}")))
        out += qz.dequantize_int8_np(rq, rs, arr.shape)
    return out / g.world_size if op == "mean" else out


def _host_broadcast(g: Group, tensor, src_rank: int):
    # Broadcast is asymmetric (receivers never write), so lag-GC's
    # self-synchronization argument doesn't hold; receivers ack instead
    # and the source reaps fully-acked payloads on its next broadcast.
    seq = g.next_seq("bc")
    data_key = _key(g, f"bc/{seq}")
    if g.rank == src_rank:
        w = _kv()
        still_pending = []
        for old_data, acks in g._bcast_pending:
            if all(w.kv_get(a, ns="collective") is not None for a in acks):
                w.kv_del(old_data, ns="collective")
                for a in acks:
                    w.kv_del(a, ns="collective")
            else:
                still_pending.append((old_data, acks))
        g._bcast_pending = still_pending
        _kv_put(data_key, _dumps(np.asarray(tensor)))
        g._bcast_pending.append(
            (data_key, [_key(g, f"bc/{seq}/ack/{r}")
                        for r in range(g.world_size) if r != src_rank]))
        return tensor
    out = _loads(_kv_wait(data_key))
    _kv_put(_key(g, f"bc/{seq}/ack/{g.rank}"), b"1")
    return out


def _host_barrier(g: Group) -> None:
    seq = g.next_seq("bar")
    key = _key(g, f"bar/{seq}/{g.rank}")
    _gc_symmetric(g, "bar", seq, key)
    _kv_put(key, b"1")
    for r in range(g.world_size):
        _kv_wait(_key(g, f"bar/{seq}/{r}"))


# --------------------------------------------- multi-host jax rendezvous
def init_jax_distributed(group_name: str = "train",
                         coordinator_port: int = 8476,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Multi-host SPMD bring-up: the JAX-distributed equivalent of the
    reference's torch TCPStore rendezvous (``train/torch/config.py:64-116``).
    Rank 0 publishes its address in the internal KV; all ranks call
    ``jax.distributed.initialize`` against it. Call before any jax use in
    the process."""
    import socket
    if process_id is None or num_processes is None:
        raise ValueError(
            "init_jax_distributed requires explicit num_processes and "
            "process_id (rank 0 hosts the coordinator)")
    w = _kv()
    key = f"jaxdist/{group_name}/coordinator".encode()
    if process_id == 0:
        addr = f"{socket.gethostbyname(socket.gethostname())}:{coordinator_port}"
        w.kv_put(key, addr.encode(), ns="collective")
    else:
        addr = _kv_wait(key).decode()
    import jax
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=num_processes,
                               process_id=process_id)
