"""Elastic preemption-surviving training: drain → re-lower → resume.

TPU slices come and go — maintenance windows, spot preemption,
stockouts — and the paper's production claim is that the framework, not
the user, absorbs it. This module closes that loop over the pieces the
earlier subsystems built separately: SliceManager draining (provider
maintenance notices → ``DrainNotice`` callbacks), gang placement-group
rescheduling, the MPMD pipeline's per-stage in-memory checkpoints, and
``split_train_state``'s ANY-(S, v, dp) checkpoint re-slicing.

:class:`ElasticTrainer` wraps any :class:`~ray_tpu.parallel.plan.
ParallelPlan` ``TrainProgram`` and survives slice loss live:

1. **quiesce + snapshot** — on a drain notice (graceful path) the
   in-flight step has already completed (notices are consumed at step
   boundaries); the trainer aborts the stage mailboxes (bounded acks,
   queues drained) and snapshots per-stage state **in memory** via
   ``PipelineStage.stream_checkpoint`` — host-copied param chunks and
   canonicalized optimizer state as exactly-once stream blocks, no
   disk round-trip. On a hard mid-step failure (typed actor/stream
   errors) the live state is suspect, so recovery falls back to the
   last periodic snapshot plus the replay buffer.
2. **re-lower** — the plan is rebuilt onto the surviving capacity:
   same grid when another slice is (or will be) available (the drained
   slice's placement group is already RESCHEDULING), else down the
   fold ladder — shrink ``dp``, fold pipeline stages into more virtual
   chunks (``pp/2 × 2v`` keeps the chunk count), and finally collapse
   to the single-program SPMD lowering. Checkpoints are
   lowering-independent, so any rung reloads exactly.
3. **reload + resume** — on a same-grid rebuild the streamed block
   REFS are forwarded straight into the new stage actors'
   ``load_state_blocks`` (bytes move peer-to-peer over the reliable
   layer, never through the driver); across layouts the driver merges
   (:func:`~ray_tpu.parallel.mpmd_pipeline.merge_stage_checkpoints`)
   and the new program re-slices on load. Rolled-back steps are
   re-executed from the replay buffer, so the loss trajectory is
   **exactly** the uninterrupted one, step for step.

Steps-lost math: with ``snapshot_interval=1`` the replay buffer holds
at most the current batch, so a graceful drain loses 0 steps and a
hard kill re-executes exactly 1 (the in-flight step). Interval ``k``
bounds the loss at ``k`` for a kill, amortizing the per-step snapshot
gather.

Recovery emits ``ELASTIC_NOTICE`` / ``ELASTIC_SNAPSHOT`` /
``ELASTIC_RELOWER`` / ``ELASTIC_RESUME`` flight-recorder events
(``core/events.py``); ``ELASTIC_RESUME`` carries ``dur_s`` = the full
notice-to-resume window, so ``tools/timeline.py`` renders the recovery
as a duration slice — the preemption postmortem. ``bench.py
--elastic`` measures recovery wall-clock, steps lost and post-recovery
trajectory parity, gated by ``tools/perf_gate.py --metric elastic``.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.exceptions import RayTpuError
from ray_tpu.parallel.plan import (ParallelPlan, PlanStepResult,
                                   TrainProgram)

logger = logging.getLogger(__name__)

__all__ = ["ElasticTrainer", "ElasticError", "ElasticSnapshotError",
           "ElasticRecoveryError", "RecoveryReport", "fold_plan"]


class ElasticError(RayTpuError):
    """Base for elastic-training failures."""


class ElasticSnapshotError(ElasticError):
    """The in-memory state gather failed (e.g. a stage actor died
    mid-``stage_checkpoint``) — always typed and bounded, never a
    hang; the underlying cause is chained."""


class ElasticRecoveryError(ElasticError):
    """Recovery was attempted ``max_recoveries`` times and the step
    still cannot complete — the cluster is beyond what re-lowering can
    absorb."""


def fold_plan(plan: ParallelPlan) -> Optional[ParallelPlan]:
    """The next rung down the re-lowering ladder when capacity shrank:
    halve ``dp`` first (cheapest — data parallelism is pure
    replication), then fold pipeline stages into more virtual chunks
    per surviving stage (``pp/2 × 2v`` keeps the chunk count, so the
    layer split is unchanged), and finally collapse to the
    single-program SPMD lowering. Returns None when the plan is
    already minimal."""
    if plan.dp > 1:
        return dataclasses.replace(plan, dp=max(1, plan.dp // 2))
    if plan.pp >= 2:
        if plan.pp // 2 >= 2:
            return dataclasses.replace(plan, pp=plan.pp // 2,
                                       virtual=plan.virtual * 2)
        return dataclasses.replace(plan, pp=1, virtual=1)
    if plan.fsdp > 1:
        return dataclasses.replace(plan, fsdp=max(1, plan.fsdp // 2))
    return None


@dataclasses.dataclass
class RecoveryReport:
    """One completed recovery, in order: what triggered it, which plan
    it landed on, and what it cost."""
    trigger: str          # "notice" | "failure" | "regrow"
    reason: str
    from_plan: str
    to_plan: str
    steps_lost: int
    live_snapshot: bool
    snapshot_s: float
    relower_s: float
    total_s: float
    step: int

    def asdict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _recorder():
    try:
        from ray_tpu.core.global_state import try_global_worker
        w = try_global_worker()
        return w.recorder if w is not None else None
    except Exception:
        return None


def _is_recoverable(exc: BaseException) -> bool:
    """Failures the elastic loop absorbs: every typed framework error
    (actor death, delivery failure, lost objects, rpc/get timeouts),
    plain timeouts (pipeline stall / mailbox starvation), and the
    stage-abort RuntimeError. Anything else — a genuine bug, a
    ValueError from a bad batch — propagates untouched."""
    if isinstance(exc, ElasticError):
        return False
    if isinstance(exc, (RayTpuError, TimeoutError)):
        return True
    if isinstance(exc, RuntimeError) and "abort" in str(exc):
        return True
    return False


class ElasticTrainer(TrainProgram):
    """A ``TrainProgram`` that survives slice loss (module docstring).

    Wraps ``plan.build(config, ...)`` and exposes the same
    step/checkpoint/shutdown surface; ``slice_manager`` (optional)
    wires provider maintenance notices in via
    :meth:`~ray_tpu.autoscaler.slices.SliceManager.register_on_drain`.
    ``slice_filter`` (a ``slice_id -> bool`` predicate) scopes the
    trainer to the slices it OWNS on a shared train+serve pool: drain
    notices for foreign slices are ignored and capacity/regrow
    decisions count only owned slices — without it, a colocated serve
    fleet's UP slice would convince a preempted trainer it still has
    capacity. Every build kwarg (``actor_options``, ``step_timeout_s``,
    ``placement_bundle``, ...) is forwarded to each (re-)lowering."""

    def __init__(self, plan: ParallelPlan, config, *,
                 learning_rate: float = 1e-5,
                 weight_decay: float = 0.0,
                 clip_norm: Optional[float] = 1.0,
                 seed: int = 0,
                 slice_manager=None,
                 slice_filter=None,
                 snapshot_interval: int = 1,
                 snapshot_timeout_s: float = 60.0,
                 max_recoveries: int = 8,
                 auto_regrow: bool = True,
                 **build_kwargs):
        if snapshot_interval < 1:
            raise ValueError(
                f"snapshot_interval must be >= 1, got "
                f"{snapshot_interval}")
        self.target_plan = plan
        self.plan = plan
        self.config = config
        self.slice_manager = slice_manager
        self.slice_filter = slice_filter
        self.snapshot_interval = snapshot_interval
        self.snapshot_timeout_s = snapshot_timeout_s
        self.max_recoveries = max_recoveries
        self.auto_regrow = auto_regrow
        self._build_kwargs = dict(build_kwargs)
        self._build_kwargs.update(
            learning_rate=learning_rate, weight_decay=weight_decay,
            clip_norm=clip_norm, seed=seed)
        self._lock = threading.Lock()
        self._notices: collections.deque = collections.deque()
        self._registered = False
        self.recoveries: List[RecoveryReport] = []
        self.steps_lost_total = 0
        self._step_index = 0
        self._replay: List[Dict[str, Any]] = []
        self.program = self._build(plan)
        # step-0 snapshot: recovery is possible before the first step
        self._snapshot = self.program.save_checkpoint()
        self._snapshot_step = 0
        if slice_manager is not None:
            slice_manager.register_on_drain(self._on_drain)
            self._registered = True

    # ------------------------------------------------------- plumbing
    @property
    def lowering(self) -> str:
        return self.plan.lowering

    def _build(self, plan: ParallelPlan) -> TrainProgram:
        return plan.build(self.config, **self._build_kwargs)

    def _on_drain(self, notice) -> None:
        """SliceManager callback — may run on the monitor thread, so
        it only enqueues; the notice is consumed at the next step
        boundary (the quiesce point). A foreign slice's drain (e.g.
        the colocated serve fleet shrinking) is not our loss."""
        if self.slice_filter is not None and \
                not self.slice_filter(notice.slice_id):
            return
        with self._lock:
            self._notices.append(notice)

    def _owned(self, slice_id) -> bool:
        return self.slice_filter is None or self.slice_filter(slice_id)

    def _pop_notices(self) -> List[Any]:
        with self._lock:
            out = list(self._notices)
            self._notices.clear()
        return out

    def _capacity(self) -> Optional[int]:
        """Usable OWNED slices by the manager's books (None without a
        manager): REQUESTED/UP and not draining."""
        if self.slice_manager is None:
            return None
        from ray_tpu.autoscaler.slices import REQUESTED, UP
        return sum(1 for sid, s in self.slice_manager.slices.items()
                   if s.state in (REQUESTED, UP) and self._owned(sid))

    def _choose_plan(self, slice_lost: bool) -> ParallelPlan:
        cap = self._capacity()
        if not slice_lost:
            return self.plan
        if cap is not None and cap >= 1:
            # another slice is up or coming — the rescheduled gang
            # lands there; keep the grid
            return self.plan
        return fold_plan(self.plan) or self.plan

    # ------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, Any]:
        """In-memory canonical snapshot of the live program state —
        streamed per-chunk from the stage actors for pipeline
        lowerings, a host copy for SPMD. Updates the recovery point
        and clears the replay buffer. Raises
        :class:`ElasticSnapshotError` (typed, deadline-bounded — never
        a hang) when the gather fails, e.g. a stage actor killed
        mid-``stage_checkpoint``."""
        try:
            pipeline = getattr(self.program, "pipeline", None)
            if pipeline is not None:
                state = pipeline.save_checkpoint_streaming(
                    timeout_s=self.snapshot_timeout_s)
            else:
                state = self.program.save_checkpoint()
        except Exception as e:
            raise ElasticSnapshotError(
                f"elastic snapshot failed at step {self._step_index}: "
                f"{type(e).__name__}: {e}") from e
        self._snapshot = state
        self._snapshot_step = self._step_index
        self._replay = []
        return state

    # ----------------------------------------------------------- step
    def step(self, batch: Dict[str, Any]) -> PlanStepResult:
        attempts = 0
        while True:
            try:
                self._handle_notices()
                self._maybe_regrow()
                res = self.program.step(batch)
                break
            except Exception as e:
                if not _is_recoverable(e):
                    raise
                attempts += 1
                if attempts > self.max_recoveries:
                    raise ElasticRecoveryError(
                        f"step {self._step_index + 1} still failing "
                        f"after {self.max_recoveries} recovery "
                        f"attempts") from e
                logger.warning(
                    "elastic: step %d failed (%s: %s) — recovering "
                    "(attempt %d/%d)", self._step_index + 1,
                    type(e).__name__, e, attempts, self.max_recoveries)
                self._recover_failure(e)
        self._step_index += 1
        self._replay.append(batch)
        if len(self._replay) >= self.snapshot_interval:
            try:
                self.snapshot()
            except ElasticSnapshotError:
                # the step itself succeeded; keep the replay buffer
                # and let the NEXT step's failure path recover
                logger.warning(
                    "elastic: periodic snapshot failed at step %d — "
                    "keeping %d-step replay buffer",
                    self._step_index, len(self._replay))
        return res

    def _handle_notices(self) -> None:
        notices = self._pop_notices()
        if not notices:
            return
        reason = ",".join(
            f"{getattr(n, 'slice_id', '?')}:"
            f"{getattr(n, 'reason', 'drain')}" for n in notices)
        rec = _recorder()
        if rec is not None:
            from ray_tpu.core.events import ELASTIC_NOTICE
            for n in notices:
                rec.record(ELASTIC_NOTICE,
                           slice=getattr(n, "slice_id", None),
                           reason=getattr(n, "reason", None))
        new_plan = self._choose_plan(slice_lost=True)
        self._relower(new_plan, trigger="notice", reason=reason,
                      live=True)

    def _maybe_regrow(self) -> None:
        if not self.auto_regrow or self.slice_manager is None:
            return
        if self.plan == self.target_plan:
            return
        from ray_tpu.autoscaler.slices import UP
        cap = sum(1 for sid, s in self.slice_manager.slices.items()
                  if s.state == UP and self._owned(sid))
        if cap >= 1:
            self.regrow()

    def regrow(self, plan: Optional[ParallelPlan] = None
               ) -> Optional[RecoveryReport]:
        """Grow the grid back (scale-up): re-lower onto ``plan`` (the
        original target by default) from a live snapshot. No-op when
        already there."""
        target = plan or self.target_plan
        if target == self.plan:
            return None
        self._relower(target, trigger="regrow",
                      reason="capacity-restored", live=True)
        return self.recoveries[-1]

    def _recover_failure(self, exc: BaseException) -> None:
        """Hard mid-step failure: quiesce what survives, let the
        SliceManager observe the damage (dead hosts → drain →
        notices), then re-lower from the last periodic snapshot and
        replay."""
        if self.slice_manager is not None:
            try:
                self.slice_manager.update()
            except Exception:
                logger.exception("elastic: slice manager update failed "
                                 "during recovery")
        notices = self._pop_notices()
        rec = _recorder()
        if rec is not None and notices:
            from ray_tpu.core.events import ELASTIC_NOTICE
            for n in notices:
                rec.record(ELASTIC_NOTICE,
                           slice=getattr(n, "slice_id", None),
                           reason=getattr(n, "reason", None))
        pipeline = getattr(self.program, "pipeline", None)
        if pipeline is not None:
            try:
                pipeline.abort()
            except Exception:
                pass
        new_plan = self._choose_plan(slice_lost=bool(notices))
        self._relower(new_plan, trigger="failure",
                      reason=f"{type(exc).__name__}", live=False,
                      failed_step=True)

    # -------------------------------------------------------- relower
    def _relower(self, new_plan: ParallelPlan, *, trigger: str,
                 reason: str, live: bool,
                 failed_step: bool = False) -> None:
        """The drain → re-lower → resume core: snapshot (live when
        trusted), build the new program, reload (peer-to-peer block
        refs on a same-grid rebuild), tear the old one down, replay
        rolled-back steps, and record the recovery window."""
        import ray_tpu
        from ray_tpu.core.events import (ELASTIC_RELOWER,
                                         ELASTIC_RESUME,
                                         ELASTIC_SNAPSHOT)

        t0 = time.perf_counter()
        rec = _recorder()
        old_program = self.program
        old_pipeline = getattr(old_program, "pipeline", None)
        state = None
        refs = None
        snap_s = 0.0
        if live:
            t_s = time.perf_counter()
            try:
                if old_pipeline is not None:
                    # quiesce: unblock + drain every stage mailbox
                    # (bounded acks), then stream the state out
                    old_pipeline.abort()
                    refs = old_pipeline.stream_checkpoint_refs(
                        self.snapshot_timeout_s)
                    state = old_pipeline.save_checkpoint_streaming(
                        timeout_s=self.snapshot_timeout_s, refs=refs)
                else:
                    state = old_program.save_checkpoint()
            except Exception:
                logger.exception(
                    "elastic: live snapshot failed — falling back to "
                    "the step-%d periodic snapshot", self._snapshot_step)
                state, refs = None, None
            snap_s = time.perf_counter() - t_s
            if rec is not None:
                rec.record(ELASTIC_SNAPSHOT, dur_s=round(snap_s, 6),
                           live=state is not None)

        steps_lost = 0
        if state is not None:
            self._snapshot = state
            self._snapshot_step = self._step_index
            self._replay = []
        else:
            state = self._snapshot
            steps_lost = len(self._replay)
        if failed_step:
            steps_lost += 1

        t_r = time.perf_counter()
        from_desc = self.plan.describe()
        program = self._build(new_plan)
        new_pipeline = getattr(program, "pipeline", None)
        same_grid = (
            refs is not None and new_pipeline is not None
            and (new_plan.pp, new_plan.virtual,
                 new_plan.shard_weight_update)
            == (self.plan.pp, self.plan.virtual,
                self.plan.shard_weight_update))
        loaded = False
        if same_grid:
            # peer-to-peer reload: forward the streamed block refs
            # into the new stage actors — the bytes pull
            # worker-to-worker, the driver never re-serializes them
            try:
                ray_tpu.get(
                    [a.load_state_blocks.remote(*stage_refs)
                     for a, stage_refs in zip(new_pipeline.stages,
                                              refs)],
                    timeout=self.snapshot_timeout_s)
                loaded = True
            except Exception:
                logger.exception(
                    "elastic: peer-to-peer block reload failed — "
                    "falling back to the driver-merged state")
        if not loaded:
            program.load_checkpoint(state)
        relower_s = time.perf_counter() - t_r
        self.program = program
        self.plan = new_plan
        try:
            old_program.shutdown()
        except Exception:
            pass
        if rec is not None:
            rec.record(ELASTIC_RELOWER, from_plan=from_desc,
                       to_plan=new_plan.describe(),
                       dur_s=round(relower_s, 6))

        replayed = list(self._replay)
        for b in replayed:
            # re-execute rolled-back steps: deterministic programs +
            # identical state ⇒ the exact original trajectory. A
            # failure here propagates to the step() retry loop with
            # snapshot and replay buffer intact.
            self.program.step(b)
        if replayed:
            self._snapshot = self.program.save_checkpoint()
            self._snapshot_step = self._step_index
            self._replay = []

        total_s = time.perf_counter() - t0
        report = RecoveryReport(
            trigger=trigger, reason=reason, from_plan=from_desc,
            to_plan=new_plan.describe(), steps_lost=steps_lost,
            live_snapshot=refs is not None or (live and not steps_lost),
            snapshot_s=round(snap_s, 6), relower_s=round(relower_s, 6),
            total_s=round(total_s, 6), step=self._step_index)
        self.recoveries.append(report)
        self.steps_lost_total += steps_lost
        if rec is not None:
            rec.record(ELASTIC_RESUME, dur_s=round(total_s, 6),
                       steps_lost=steps_lost, trigger=trigger,
                       to_plan=new_plan.describe())
            try:
                rec.maybe_flush()
            except Exception:
                pass
        logger.warning(
            "elastic: %s recovery complete in %.2fs — %s -> %s, "
            "%d step(s) re-executed", trigger, total_s, from_desc,
            report.to_plan, steps_lost)

    # ----------------------------------------------------- checkpoint
    def save_checkpoint(self) -> Dict[str, Any]:
        return self.program.save_checkpoint()

    def load_checkpoint(self, state: Dict[str, Any]) -> None:
        self.program.load_checkpoint(state)
        self._snapshot = state
        self._step_index = int(state.get("step", 0))
        self._snapshot_step = self._step_index
        self._replay = []

    # ---------------------------------------------------------- views
    def stats(self) -> Dict[str, Any]:
        return {
            "active_plan": self.plan.describe(),
            "target_plan": self.target_plan.describe(),
            "lowering": self.plan.lowering,
            "step": self._step_index,
            "snapshot_step": self._snapshot_step,
            "recoveries": [r.asdict() for r in self.recoveries],
            "steps_lost_total": self.steps_lost_total,
        }

    def shutdown(self) -> None:
        if self._registered and self.slice_manager is not None:
            try:
                self.slice_manager.unregister_on_drain(self._on_drain)
            except Exception:
                pass
            self._registered = False
        try:
            self.program.shutdown()
        except Exception:
            pass
