"""gRPC ingress proxy.

Reference: ``python/ray/serve/_private/proxy.py`` runs an HTTP *and* a
gRPC proxy per node; the gRPC side (``grpc_util.py``, serve's
``RayServeAPIService``) routes by application name carried in the
request. Here a generic-handler service avoids protoc codegen: one
``Predict`` method takes a JSON payload, routes through the same
DeploymentHandle machinery as HTTP, and returns the JSON result;
``Healthz``/``ListApplications`` mirror the reference's service API.

The wire format is JSON (like the HTTP ingress), NOT pickle: ingress
ports sit on a network trust boundary, and unpickling peer-controlled
bytes would be remote code execution.

Wire contract (UTF-8 JSON bytes):
  /ray_tpu.serve.ServeAPIService/Predict
      request  = {"app": str, "args": [...], "kwargs": {...}}
      response = {"result": ...} or {"error": str}
  /ray_tpu.serve.ServeAPIService/Healthz          -> "OK"
  /ray_tpu.serve.ServeAPIService/ListApplications -> [names]
"""

from __future__ import annotations

import json
from concurrent import futures
from typing import Any

SERVICE = "ray_tpu.serve.ServeAPIService"


class GrpcProxy:
    """Actor hosting the gRPC server (one per cluster, like the HTTP
    proxy actor)."""

    def __init__(self, controller, host: str = "127.0.0.1",
                 port: int = 9000):
        import grpc

        import ray_tpu
        self._controller = controller
        self._ray = ray_tpu
        # app -> (ingress deployment name, handle); re-validated against
        # the controller on every call so redeploys take effect
        self._handles: dict = {}

        proxy = self

        def predict(request: bytes, context) -> bytes:
            try:
                req = json.loads(request.decode() or "{}")
                out = proxy._dispatch(req.get("app", "default"),
                                      tuple(req.get("args", ())),
                                      req.get("kwargs", {}))
                return json.dumps({"result": out}, default=str).encode()
            except BaseException as e:  # noqa: BLE001
                return json.dumps({"error": repr(e)}).encode()

        def healthz(request: bytes, context) -> bytes:
            return json.dumps("OK").encode()

        def list_apps(request: bytes, context) -> bytes:
            apps = self._ray.get(
                self._controller.list_applications.remote())
            return json.dumps(list(apps)).encode()

        ident = lambda b: b  # noqa: E731 — bytes in, bytes out
        handlers = {
            "Predict": grpc.unary_unary_rpc_method_handler(
                predict, request_deserializer=ident,
                response_serializer=ident),
            "Healthz": grpc.unary_unary_rpc_method_handler(
                healthz, request_deserializer=ident,
                response_serializer=ident),
            "ListApplications": grpc.unary_unary_rpc_method_handler(
                list_apps, request_deserializer=ident,
                response_serializer=ident),
        }
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        bound = self._server.add_insecure_port(f"{host}:{port}")
        if bound == 0 and port != 0:
            raise OSError(
                f"gRPC proxy could not bind {host}:{port} "
                f"(port already in use?)")
        self._port = bound
        self._host = host
        self._server.start()

    def _dispatch(self, app: str, args: tuple, kwargs: dict) -> Any:
        ingress = self._ray.get(
            self._controller.get_app_ingress.remote(app))
        if ingress is None:
            raise RuntimeError(f"No application named {app!r}")
        cached = self._handles.get(app)
        if cached is None or cached[0] != ingress:
            from ray_tpu.serve.handle import DeploymentHandle
            cached = (ingress,
                      DeploymentHandle(ingress, self._controller, app))
            self._handles[app] = cached
        return cached[1].remote(*args, **kwargs).result(timeout_s=60)

    def address(self) -> str:
        return f"{self._host}:{self._port}"

    def stop(self) -> None:
        self._server.stop(grace=None)


def _unary(address: str, method: str, payload: bytes,
           timeout_s: float) -> bytes:
    import grpc
    channel = grpc.insecure_channel(address)
    try:
        fn = channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        return fn(payload, timeout=timeout_s)
    finally:
        channel.close()


def grpc_call(address: str, app: str, *args, timeout_s: float = 60.0,
              **kwargs) -> Any:
    """Client helper (reference: serve's gRPC client examples)."""
    out = json.loads(_unary(
        address, "Predict",
        json.dumps({"app": app, "args": list(args),
                    "kwargs": kwargs}).encode(),
        timeout_s))
    if "error" in out:
        raise RuntimeError(f"serve gRPC call failed: {out['error']}")
    return out["result"]


def grpc_healthz(address: str, timeout_s: float = 10.0) -> str:
    return json.loads(_unary(address, "Healthz", b"", timeout_s))


def grpc_list_applications(address: str,
                           timeout_s: float = 10.0) -> list:
    return json.loads(_unary(address, "ListApplications", b"",
                             timeout_s))
