"""gRPC ingress proxy.

Reference: ``python/ray/serve/_private/proxy.py`` runs an HTTP *and* a
gRPC proxy per node; the gRPC side (``grpc_util.py``, serve's
``RayServeAPIService``) routes by application name carried in the
request. Here a generic-handler service avoids protoc codegen: one
``Predict`` method takes a JSON payload, routes through the same
DeploymentHandle machinery as HTTP, and returns the JSON result;
``Healthz``/``ListApplications`` mirror the reference's service API.

The wire format is JSON (like the HTTP ingress), NOT pickle: ingress
ports sit on a network trust boundary, and unpickling peer-controlled
bytes would be remote code execution.

Wire contract (UTF-8 JSON bytes):
  /ray_tpu.serve.ServeAPIService/Predict
      request  = {"app": str, "args": [...], "kwargs": {...}}
      response = {"result": ...} or {"error": str}
  /ray_tpu.serve.ServeAPIService/Healthz          -> "OK"
  /ray_tpu.serve.ServeAPIService/ListApplications -> [names]
"""

from __future__ import annotations

import json
from concurrent import futures
from typing import Any, Optional

SERVICE = "ray_tpu.serve.ServeAPIService"


class _ForwardingServicer:
    """Servicer for a USER-DEFINED gRPC service (reference:
    ``src/ray/protobuf/serve.proto:150`` UserDefinedService +
    ``gRPCOptions.grpc_servicer_functions``): the user passes their
    protoc-generated ``add_XServicer_to_server`` functions, which look
    up RPC method names on this object via ``getattr`` — every method
    resolves to a forwarder that routes the TYPED request message to
    the target application's ingress deployment (the deployment method
    named like the RPC if it exists, else ``__call__``) and returns the
    deployment's TYPED response message, which the generated handler
    serializes with the user's proto."""

    def __init__(self, proxy: "GrpcProxy"):
        self._proxy = proxy

    def __getattr__(self, method_name: str):
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        proxy = self._proxy

        def forward(request, context):
            md = dict(context.invocation_metadata() or ())
            app = md.get("application", "default")
            try:
                return proxy._dispatch(app, (request,), {},
                                       method=method_name)
            except BaseException as e:  # noqa: BLE001
                import grpc
                context.abort(grpc.StatusCode.INTERNAL, repr(e))

        return forward


class GrpcProxy:
    """Actor hosting the gRPC server (one per cluster, like the HTTP
    proxy actor). ``grpc_servicer_functions`` registers user-defined
    proto services alongside the built-in JSON ServeAPIService."""

    def __init__(self, controller, host: str = "127.0.0.1",
                 port: int = 9000, grpc_servicer_functions=()):
        import grpc

        import ray_tpu
        self._controller = controller
        self._ray = ray_tpu
        # app -> (ingress deployment name, handle); re-validated against
        # the controller on every call so redeploys take effect
        self._handles: dict = {}

        proxy = self

        def predict(request: bytes, context) -> bytes:
            try:
                req = json.loads(request.decode() or "{}")
                out = proxy._dispatch(req.get("app", "default"),
                                      tuple(req.get("args", ())),
                                      req.get("kwargs", {}))
                return json.dumps({"result": out}, default=str).encode()
            except BaseException as e:  # noqa: BLE001
                return json.dumps({"error": repr(e)}).encode()

        def healthz(request: bytes, context) -> bytes:
            return json.dumps("OK").encode()

        def list_apps(request: bytes, context) -> bytes:
            apps = self._ray.get(
                self._controller.list_applications.remote())
            return json.dumps(list(apps)).encode()

        ident = lambda b: b  # noqa: E731 — bytes in, bytes out
        handlers = {
            "Predict": grpc.unary_unary_rpc_method_handler(
                predict, request_deserializer=ident,
                response_serializer=ident),
            "Healthz": grpc.unary_unary_rpc_method_handler(
                healthz, request_deserializer=ident,
                response_serializer=ident),
            "ListApplications": grpc.unary_unary_rpc_method_handler(
                list_apps, request_deserializer=ident,
                response_serializer=ident),
        }
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        # user-defined typed services: protoc-generated (or compatible)
        # add_*Servicer_to_server callables, exactly the reference's
        # gRPCOptions.grpc_servicer_functions contract
        servicer = _ForwardingServicer(self)
        for fn in grpc_servicer_functions or ():
            if isinstance(fn, str):
                import importlib
                mod, _, attr = fn.rpartition(".")
                fn = getattr(importlib.import_module(mod), attr)
            fn(servicer, self._server)
        bound = self._server.add_insecure_port(f"{host}:{port}")
        if bound == 0 and port != 0:
            raise OSError(
                f"gRPC proxy could not bind {host}:{port} "
                f"(port already in use?)")
        self._port = bound
        self._host = host
        self._server.start()

    def _dispatch(self, app: str, args: tuple, kwargs: dict,
                  method: Optional[str] = None) -> Any:
        ingress = self._ray.get(
            self._controller.get_app_ingress.remote(app))
        if ingress is None:
            raise RuntimeError(f"No application named {app!r}")
        cached = self._handles.get(app)
        if cached is None or cached[0] != ingress:
            from ray_tpu.serve.handle import DeploymentHandle
            # per-app cache: (ingress, handle, method-routing verdicts);
            # invalidated wholesale on redeploy (ingress change)
            cached = (ingress,
                      DeploymentHandle(ingress, self._controller, app),
                      {})
            self._handles[app] = cached
        handle = cached[1]
        if method:
            has = cached[2].get(method)
            if has is None:
                has = cached[2][method] = self._ray.get(
                    self._controller.app_has_method.remote(app, method))
            if has:
                # typed user-service RPC: route to the deployment
                # method named like the RPC (reference: serve's gRPC
                # ingress maps RPC names onto deployment methods)
                return getattr(handle, method).remote(
                    *args, **kwargs).result(timeout_s=60)
        return handle.remote(*args, **kwargs).result(timeout_s=60)

    def address(self) -> str:
        return f"{self._host}:{self._port}"

    def stop(self) -> None:
        self._server.stop(grace=None)


def _unary(address: str, method: str, payload: bytes,
           timeout_s: float) -> bytes:
    import grpc
    channel = grpc.insecure_channel(address)
    try:
        fn = channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        return fn(payload, timeout=timeout_s)
    finally:
        channel.close()


def grpc_call(address: str, app: str, *args, timeout_s: float = 60.0,
              **kwargs) -> Any:
    """Client helper (reference: serve's gRPC client examples)."""
    out = json.loads(_unary(
        address, "Predict",
        json.dumps({"app": app, "args": list(args),
                    "kwargs": kwargs}).encode(),
        timeout_s))
    if "error" in out:
        raise RuntimeError(f"serve gRPC call failed: {out['error']}")
    return out["result"]


def grpc_healthz(address: str, timeout_s: float = 10.0) -> str:
    return json.loads(_unary(address, "Healthz", b"", timeout_s))


def grpc_list_applications(address: str,
                           timeout_s: float = 10.0) -> list:
    return json.loads(_unary(address, "ListApplications", b"",
                             timeout_s))
