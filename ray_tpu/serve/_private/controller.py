"""ServeController: the reconciling control plane.

Reference: ``python/ray/serve/_private/controller.py:91``
(``run_control_loop`` :365) + ``deployment_state.py:2462``
(``DeploymentState.update``: reconcile target vs actual replicas) +
``autoscaling_policy.py`` (queue-depth replica autoscaling). One
controller actor owns all deployments of all apps: it starts/stops
replica actors, restarts dead ones, probes queue depth for autoscaling,
and versions replica membership so handles refresh lazily.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve._private.replica import Replica

CONTROLLER_NAME = "SERVE_CONTROLLER_ACTOR"


def autoscale_decision(cfg, target_num: int, avg_ongoing: float,
                       avg_queue_depth: Optional[float] = None,
                       avg_ttft_s: Optional[float] = None) -> int:
    """Pure scale policy: the new target replica count for one
    deployment, given the probed signals (delay gating is the
    caller's job — this is the decision, testable without a cluster).

    Scale-up fires on ANY pressure signal: ongoing requests above
    target (the classic queue-depth policy), engine queue depth above
    ``cfg.target_queue_depth``, or engine TTFT above
    ``cfg.target_ttft_s`` (each only when configured AND probed —
    continuous-batching engines admit work immediately, so handle-side
    ongoing counts understate a deep engine backlog). Scale-down
    requires ongoing requests below half target AND no engine
    pressure."""
    up = avg_ongoing > cfg.target_ongoing_requests
    engine_pressure = False
    if cfg.target_queue_depth is not None and avg_queue_depth is not None:
        engine_pressure |= avg_queue_depth > cfg.target_queue_depth
    if cfg.target_ttft_s is not None and avg_ttft_s is not None:
        engine_pressure |= avg_ttft_s > cfg.target_ttft_s
    if (up or engine_pressure) and target_num < cfg.max_replicas:
        return target_num + 1
    if avg_ongoing < cfg.target_ongoing_requests / 2 \
            and not engine_pressure and target_num > cfg.min_replicas:
        return target_num - 1
    return target_num


class _DeploymentInfo:
    def __init__(self, deployment, init_args, init_kwargs):
        self.deployment = deployment
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.target_num = deployment.num_replicas
        self.replicas: List[Any] = []
        self.version = 0
        self.replica_counter = 0
        # delay-gate from DEPLOY time: an epoch-zero stamp would let
        # the first scale decision bypass upscale/downscale_delay_s
        # entirely (observed as a mid-run replica kill the instant
        # engine pressure cleared, ActorDiedError for its streams)
        self._last_scale_up = time.time()
        self._last_scale_down = time.time()


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, _DeploymentInfo] = {}
        self._routes: Dict[str, str] = {}  # route_prefix -> deployment
        self._apps: Dict[str, str] = {}    # app name -> ingress deploy
        # route_prefix -> {"prefill": name, "decode": name}: HTTP
        # ingress for disaggregated pairs (serve/disagg.py) — the proxy
        # drives a DisaggRouter over both fleets instead of a handle
        self._disagg_routes: Dict[str, Dict[str, str]] = {}
        self._lock = threading.RLock()
        # admission config plane: routers poll (seq, policy dict);
        # the dashboard POST endpoint bumps seq on every accepted write
        self._admission_policy: Optional[Dict[str, Any]] = None
        self._admission_policy_seq = 0
        self._stop = threading.Event()
        self._loop = threading.Thread(
            target=self._control_loop, name="serve_control", daemon=True)
        self._loop.start()

    # -- deploy API ---------------------------------------------------
    def deploy(self, name: str, deployment, init_args, init_kwargs,
               route_prefix: Optional[str] = None,
               app_name: Optional[str] = None) -> None:
        with self._lock:
            info = self._deployments.get(name)
            if info is None:
                info = _DeploymentInfo(deployment, init_args, init_kwargs)
                self._deployments[name] = info
            else:
                info.deployment = deployment
                info.init_args = init_args
                info.init_kwargs = init_kwargs
                info.target_num = deployment.num_replicas
                # Version rollout: replace existing replicas.
                self._scale_to(name, info, 0)
            if route_prefix:
                self._routes[route_prefix] = name
            if app_name:
                self._apps[app_name] = name
            self._reconcile_one(name, info)

    def scale_deployment(self, name: str, num_replicas: int) -> int:
        """Imperative scale: pin the deployment's target replica count
        and reconcile now. A downscale runs the same drain path as
        autoscaling — for ``migrate_prefixes`` fleets the victim's warm
        radix-trie chains are exported to a survivor before the kill."""
        with self._lock:
            info = self._deployments.get(name)
            if info is None:
                raise KeyError(f"no deployment named {name!r}")
            info.target_num = max(0, int(num_replicas))
            # pin against the autoscaler immediately re-deciding
            info._last_scale_up = info._last_scale_down = time.time()
            self._reconcile_one(name, info)
            return len(info.replicas)

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            info = self._deployments.pop(name, None)
            if info is not None:
                self._scale_to(name, info, 0)
            self._routes = {r: d for r, d in self._routes.items()
                            if d != name}
            self._disagg_routes = {
                r: pair for r, pair in self._disagg_routes.items()
                if name not in pair.values()}

    def shutdown(self) -> None:
        self._stop.set()
        with self._lock:
            for name, info in list(self._deployments.items()):
                self._scale_to(name, info, 0)
            self._deployments.clear()
            self._routes.clear()
            self._disagg_routes.clear()

    # -- handle/proxy API ---------------------------------------------
    def get_version(self, name: str) -> int:
        with self._lock:
            info = self._deployments.get(name)
            return info.version if info else -1

    def get_membership(self, name: str):
        """Atomic (version, replicas) snapshot — handles must never see
        a replica list from a different version than they cache."""
        with self._lock:
            info = self._deployments.get(name)
            if info is None:
                return -1, []
            return info.version, list(info.replicas)

    def get_replicas(self, name: str) -> List[Any]:
        return self.get_membership(name)[1]

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._routes)

    def get_routes_info(self) -> Dict[str, Dict[str, Any]]:
        """Route table with per-deployment HTTP dispatch flags: the
        proxy picks unary / generator-streaming / ASGI per route
        (reference: proxy asks the controller for app configs)."""
        import inspect
        with self._lock:
            out = {}
            for prefix, name in self._routes.items():
                info = self._deployments.get(name)
                asgi = streaming = False
                if info is not None:
                    fc = info.deployment.func_or_class
                    asgi = bool(getattr(fc, "__serve_asgi__", False))
                    target = fc if not isinstance(fc, type) else \
                        getattr(fc, "__call__", None)
                    streaming = bool(
                        target is not None and (
                            inspect.isgeneratorfunction(target)
                            or inspect.isasyncgenfunction(target)))
                out[prefix] = {"name": name, "asgi": asgi,
                               "streaming": streaming}
            for prefix, pair in self._disagg_routes.items():
                out[prefix] = {"name": pair["decode"], "asgi": False,
                               "streaming": True, "disagg": dict(pair)}
            return out

    def register_disagg_route(self, route_prefix: str, prefill: str,
                              decode: str) -> None:
        """Route HTTP traffic at ``route_prefix`` through the
        disaggregated (prefill, decode) deployment pair."""
        with self._lock:
            if prefill not in self._deployments \
                    or decode not in self._deployments:
                raise ValueError(
                    f"disagg route {route_prefix!r} references unknown "
                    f"deployments {prefill!r}/{decode!r}")
            self._disagg_routes[route_prefix] = {
                "prefill": prefill, "decode": decode}

    # -- admission config plane ---------------------------------------
    def set_admission_policy(self, policy: Dict[str, Any]) -> int:
        """Validate and store a fleet-wide admission policy; routers
        with admission enabled pick it up on their next poll. Returns
        the new seq so callers can confirm propagation."""
        from ray_tpu.serve.admission import AdmissionPolicy
        p = AdmissionPolicy.from_dict(policy)  # ValueError on bad knobs
        with self._lock:
            self._admission_policy = p.to_dict()
            self._admission_policy_seq += 1
            return self._admission_policy_seq

    def get_admission_policy(self):
        """(seq, policy dict | None); seq 0 = never configured."""
        with self._lock:
            d = self._admission_policy
            return self._admission_policy_seq, \
                dict(d) if d is not None else None

    def get_app_ingress(self, app_name: str) -> Optional[str]:
        with self._lock:
            return self._apps.get(app_name)

    def app_has_method(self, app_name: str, method: str) -> bool:
        """Whether the app's ingress deployment defines ``method`` — the
        gRPC proxy maps user-service RPC names onto deployment methods
        (reference: serve's gRPC ingress method routing)."""
        if method.startswith("_"):
            return False
        with self._lock:
            name = self._apps.get(app_name)
            info = self._deployments.get(name) if name else None
            if info is None:
                return False
            fc = info.deployment.func_or_class
            return isinstance(fc, type) and callable(
                getattr(fc, method, None))

    def list_applications(self) -> List[str]:
        with self._lock:
            return sorted(self._apps)

    def list_deployments(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{
                "name": name,
                "num_replicas": len(info.replicas),
                "target_num_replicas": info.target_num,
                "autoscaling": info.deployment.autoscaling_config
                is not None,
            } for name, info in self._deployments.items()]

    # -- reconciliation -----------------------------------------------
    def _make_replica(self, name: str, info: _DeploymentInfo):
        d = info.deployment
        opts: Dict[str, Any] = {"max_concurrency":
                                max(2, d.max_ongoing_requests)}
        rao = dict(d.ray_actor_options)
        opts["num_cpus"] = float(rao.pop("num_cpus", 1.0))
        if "num_tpus" in rao:
            opts["num_tpus"] = float(rao.pop("num_tpus"))
        if "resources" in rao:
            opts["resources"] = rao.pop("resources")
        replica_id = f"{name}#{info.replica_counter}"
        info.replica_counter += 1
        actor_cls = ray_tpu.remote(**opts)(Replica)
        return actor_cls.remote(
            d.func_or_class, info.init_args, info.init_kwargs,
            d.user_config, name, replica_id)

    def _scale_to(self, name: str, info: _DeploymentInfo, n: int) -> None:
        while len(info.replicas) > n:
            replica = info.replicas.pop()
            if info.replicas and getattr(
                    info.deployment, "migrate_prefixes", False):
                # warm-prefix migration: drain the victim's warm
                # radix-trie KV chains into a survivor before the kill,
                # worker-to-worker (the export ref rides straight into
                # the import call). Strictly best-effort and bounded —
                # a wedged victim must never stall the downscale.
                try:
                    ref = replica.prepare_drain.remote(1, 0)
                    survivor = info.replicas[-1]
                    ray_tpu.get(survivor.handle_request.remote(
                        "import_warm_prefixes", ref), timeout=5)
                except Exception:
                    pass
            try:
                ray_tpu.kill(replica)
            except Exception:
                pass
            info.version += 1
        while len(info.replicas) < n:
            info.replicas.append(self._make_replica(name, info))
            info.version += 1

    def _reconcile_one(self, name: str, info: _DeploymentInfo) -> None:
        self._scale_to(name, info, info.target_num)

    def _control_loop(self) -> None:
        tick = 0
        while not self._stop.wait(0.5):
            tick += 1
            try:
                with self._lock:
                    items = list(self._deployments.items())
                for name, info in items:
                    if tick % 6 == 0:  # health probe ~every 3s
                        self._health_check(name, info)
                    self._autoscale(name, info)
                    with self._lock:
                        self._reconcile_one(name, info)
            except Exception:
                pass  # the loop must survive transient errors

    def _health_check(self, name: str, info: _DeploymentInfo) -> None:
        dead = []
        for replica in info.replicas:
            try:
                ray_tpu.get(replica.check_health.remote(), timeout=30)
            except Exception:
                dead.append(replica)
        if dead:
            with self._lock:
                for replica in dead:
                    if replica in info.replicas:
                        info.replicas.remove(replica)
                        info.version += 1
                    try:
                        ray_tpu.kill(replica)
                    except Exception:
                        pass
            # _reconcile_one (caller) restarts replacements.

    def _autoscale(self, name: str, info: _DeploymentInfo) -> None:
        cfg = info.deployment.autoscaling_config
        if cfg is None or not info.replicas:
            return
        try:
            ongoing = ray_tpu.get(
                [r.num_ongoing_requests.remote() for r in info.replicas],
                timeout=10)
        except Exception:
            return
        avg = sum(ongoing) / len(ongoing)
        avg_queue = avg_ttft = None
        if cfg.target_queue_depth is not None \
                or cfg.target_ttft_s is not None:
            # engine-gauge probe (serve_engine_queue_depth / ttft): the
            # per-replica scheduler counters surfaced by Replica.stats
            try:
                stats = ray_tpu.get(
                    [r.stats.remote() for r in info.replicas], timeout=10)
            except Exception:
                stats = []
            queues = [s["engine"].get("queue_depth") for s in stats
                      if isinstance(s, dict) and "engine" in s]
            ttfts = [s["engine"].get("ttft_ewma_s") for s in stats
                     if isinstance(s, dict) and "engine" in s]
            queues = [q for q in queues if q is not None]
            ttfts = [t for t in ttfts if t is not None]
            if queues:
                avg_queue = sum(queues) / len(queues)
            if ttfts:
                avg_ttft = sum(ttfts) / len(ttfts)
        new_target = autoscale_decision(cfg, info.target_num, avg,
                                        avg_queue, avg_ttft)
        now = time.time()
        if new_target > info.target_num and \
                now - info._last_scale_up > cfg.upscale_delay_s:
            info.target_num = new_target
            info._last_scale_up = now
        elif new_target < info.target_num and \
                now - info._last_scale_down > cfg.downscale_delay_s:
            info.target_num = new_target
            info._last_scale_down = now
