"""Replica: the actor executing user deployment code.

Reference: ``python/ray/serve/_private/replica.py`` — wraps the user
class/function, counts in-flight requests (the router probes this for
power-of-two-choices), runs health checks, applies user_config
reconfiguration. Function deployments get a synthesized callable class.
"""

from __future__ import annotations

import contextvars
import inspect
from typing import Any, Dict

#: Request-scoped metadata (reference: serve.context._serve_request_context);
#: read by ``serve.get_multiplexed_model_id()`` inside user code.
_request_context: contextvars.ContextVar[dict] = contextvars.ContextVar(
    "serve_request_context", default={})


def get_multiplexed_model_id() -> str:
    """The model id the current request was routed with (reference:
    ``serve.get_multiplexed_model_id``, python/ray/serve/api.py)."""
    return _request_context.get().get("multiplexed_model_id", "")


def get_request_context() -> Dict[str, Any]:
    """Full request-scoped routing context for the current request:
    ``request_id`` and the router's ``trace`` stamp (sampling verdict,
    enqueue timestamp, routing policy/score, admission verdict) in
    addition to the multiplexed model id. Empty dict outside a
    request."""
    return _request_context.get()


class Replica:
    def __init__(self, func_or_class, init_args, init_kwargs,
                 user_config=None, deployment_name: str = "",
                 replica_id: str = ""):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        self._num_ongoing = 0
        self._num_total = 0
        if isinstance(func_or_class, type):
            self._instance = func_or_class(*init_args, **init_kwargs)
        elif callable(func_or_class):
            fn = func_or_class
            class _FnWrapper:
                def __call__(self, *a, **kw):
                    return fn(*a, **kw)
            self._instance = _FnWrapper()
        else:
            raise TypeError(f"Not deployable: {func_or_class!r}")
        if user_config is not None:
            self.reconfigure(user_config)

    async def handle_request(self, method_name: str, *args, **kwargs):
        self._num_ongoing += 1
        self._num_total += 1
        try:
            method = getattr(self._instance, method_name)
            out = method(*args, **kwargs)
            if inspect.iscoroutine(out):
                out = await out
            return out
        finally:
            self._num_ongoing -= 1

    async def handle_request_ctx(self, ctx: dict, method_name: str,
                                 *args, **kwargs):
        """Like handle_request, with request-scoped context (multiplexed
        model id) visible to user code via get_multiplexed_model_id()."""
        token = _request_context.set(ctx or {})
        try:
            return await self.handle_request(method_name, *args, **kwargs)
        finally:
            _request_context.reset(token)

    # -- streaming (reference: RayServeHandle options(stream=True) →
    # DeploymentResponseGenerator): the handle calls this with
    # num_returns="streaming", so each yielded item becomes its own
    # core object, eagerly reported and consumer-paced by the core
    # backpressure window — there is no replica-held live-generator
    # table and no next_chunks polling protocol anymore. Early consumer
    # termination cancels this task; the finally/close path restores
    # the ongoing-count used for load balancing.
    async def handle_request_stream(self, ctx: dict, method_name: str,
                                    *args, **kwargs):
        self._num_ongoing += 1
        self._num_total += 1
        try:
            token = _request_context.set(ctx or {})
            try:
                method = getattr(self._instance, method_name)
                out = method(*args, **kwargs)
                if inspect.iscoroutine(out):
                    out = await out
            finally:
                _request_context.reset(token)
            if not (inspect.isgenerator(out) or inspect.isasyncgen(out)
                    or hasattr(out, "__iter__")):
                raise TypeError(
                    f"options(stream=True) requires {method_name!r} to "
                    f"return a generator, got {type(out).__name__}")
            is_async = inspect.isasyncgen(out)
            it = out if is_async else iter(out)
            while True:
                # the request context must be visible to the generator
                # BODY, which only runs inside this pull — and each pull
                # of an async generator runs in a fresh task context, so
                # a one-shot set at creation would not stick
                token = _request_context.set(ctx or {})
                try:
                    if is_async:
                        try:
                            item = await it.__anext__()
                        except StopAsyncIteration:
                            break
                    else:
                        try:
                            item = next(it)
                        except StopIteration:
                            break
                finally:
                    _request_context.reset(token)
                yield item
        finally:
            self._num_ongoing -= 1

    async def prepare_drain(self, min_hits: int = 1,
                            max_blocks: int = 0):
        """Downscale hook: before the controller kills this replica,
        ask an engine-aware deployment for its warm-prefix export so a
        survivor can adopt it (warm-prefix migration). Deployments
        without ``export_warm_prefixes`` drain with nothing to say."""
        fn = getattr(self._instance, "export_warm_prefixes", None)
        if fn is None:
            return None
        out = fn(min_hits=min_hits, max_blocks=max_blocks)
        if inspect.iscoroutine(out):
            out = await out
        return out

    def num_ongoing_requests(self) -> int:
        return self._num_ongoing

    def reconfigure(self, user_config) -> None:
        fn = getattr(self._instance, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    def check_health(self) -> bool:
        fn = getattr(self._instance, "check_health", None)
        if fn is not None:
            fn()
        return True

    def stats(self) -> Dict[str, Any]:
        import os
        # pid lets gauge-aware routers map this replica onto the fleet
        # metrics plane's per-origin rows when direct probes go quiet
        out = {"replica_id": self.replica_id,
               "ongoing": self._num_ongoing,
               "total": self._num_total,
               "pid": os.getpid()}
        # engine-aware deployments (LLMServer & friends) expose their
        # scheduler counters; surface them for the autoscaler's
        # engine-gauge scale-up signals (queue depth, TTFT)
        fn = getattr(self._instance, "stats", None)
        if callable(fn):
            try:
                engine = fn()
                if isinstance(engine, dict):
                    out["engine"] = engine
            except Exception:
                pass
        return out
