"""Replica: the actor executing user deployment code.

Reference: ``python/ray/serve/_private/replica.py`` — wraps the user
class/function, counts in-flight requests (the router probes this for
power-of-two-choices), runs health checks, applies user_config
reconfiguration. Function deployments get a synthesized callable class.
"""

from __future__ import annotations

import inspect
import time
from typing import Any, Callable, Dict, Optional


class Replica:
    def __init__(self, func_or_class, init_args, init_kwargs,
                 user_config=None, deployment_name: str = "",
                 replica_id: str = ""):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        self._num_ongoing = 0
        self._num_total = 0
        if isinstance(func_or_class, type):
            self._instance = func_or_class(*init_args, **init_kwargs)
        elif callable(func_or_class):
            fn = func_or_class
            class _FnWrapper:
                def __call__(self, *a, **kw):
                    return fn(*a, **kw)
            self._instance = _FnWrapper()
        else:
            raise TypeError(f"Not deployable: {func_or_class!r}")
        if user_config is not None:
            self.reconfigure(user_config)

    async def handle_request(self, method_name: str, *args, **kwargs):
        self._num_ongoing += 1
        self._num_total += 1
        try:
            method = getattr(self._instance, method_name)
            out = method(*args, **kwargs)
            if inspect.iscoroutine(out):
                out = await out
            return out
        finally:
            self._num_ongoing -= 1

    def num_ongoing_requests(self) -> int:
        return self._num_ongoing

    def reconfigure(self, user_config) -> None:
        fn = getattr(self._instance, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    def check_health(self) -> bool:
        fn = getattr(self._instance, "check_health", None)
        if fn is not None:
            fn()
        return True

    def stats(self) -> Dict[str, Any]:
        return {"replica_id": self.replica_id,
                "ongoing": self._num_ongoing,
                "total": self._num_total}
