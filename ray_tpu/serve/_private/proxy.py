"""HTTP proxy: JSON-over-HTTP ingress to deployments.

Reference: ``python/ray/serve/_private/proxy.py`` (uvicorn/ASGI proxy on
every node + ``ProxyRouter``). This build runs one threaded HTTP server
actor: ``POST/GET {route_prefix}`` → route table from the controller →
``handle.remote(json_body)`` → JSON response. Threaded (not ASGI)
because replica calls are blocking object-store gets.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import ray_tpu


class HTTPProxy:
    def __init__(self, controller, host: str = "127.0.0.1",
                 port: int = 8000):
        from ray_tpu.serve.handle import DeploymentHandle
        self._controller = controller
        self._handles: Dict[str, DeploymentHandle] = {}
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence request logging
                pass

            def _handle(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length) if length else b""
                    payload = json.loads(body) if body else None
                    result = proxy._dispatch(self.path, payload)
                    out = json.dumps(result).encode()
                    self.send_response(200)
                except KeyError:
                    out = json.dumps({"error": "no route"}).encode()
                    self.send_response(404)
                except Exception as e:
                    out = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            do_GET = do_POST = _handle

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="serve_http",
            daemon=True)
        self._thread.start()

    def _dispatch(self, path: str, payload: Any) -> Any:
        from ray_tpu.serve.handle import DeploymentHandle
        routes = ray_tpu.get(self._controller.get_routes.remote())
        # Longest-prefix match (reference ProxyRouter semantics).
        match = None
        for prefix in sorted(routes, key=len, reverse=True):
            if path == prefix or path.startswith(
                    prefix.rstrip("/") + "/") or prefix == "/":
                match = prefix
                break
        if match is None:
            raise KeyError(path)
        name = routes[match]
        if name not in self._handles:
            self._handles[name] = DeploymentHandle(name, self._controller)
        resp = self._handles[name].remote(payload) \
            if payload is not None else self._handles[name].remote()
        return resp.result(timeout_s=60)

    def address(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        self._server.shutdown()
