"""HTTP ingress proxy: asyncio server with streaming + ASGI dispatch.

Reference: ``python/ray/serve/_private/proxy.py`` (per-node uvicorn/ASGI
proxy + ``ProxyRouter`` longest-prefix routing, streaming responses
wired to handle generators). This build keeps the reference's dispatch
model without requiring uvicorn: a stdlib asyncio HTTP/1.1 server whose
blocking object-store pulls run on a thread pool, with three dispatch
modes per route (flags from ``ServeController.get_routes_info``):

- **unary** — legacy JSON-over-HTTP: parse body as JSON, call the
  handle, JSON the result (back-compat with round-3 clients).
- **streaming** — deployments whose ``__call__`` is a (async) generator
  stream chunks to the client as they are produced, via
  ``DeploymentResponseGenerator`` over a core ``ObjectRefGenerator``
  (items pushed as produced, consumer-paced by the core backpressure
  window, delivery covered by the reliable-transport guarantees).
- **asgi** — ``@serve.ingress`` deployments: the whole request ships to
  the replica, the ASGI app's send() events stream back and are written
  to the socket incrementally (FastAPI StreamingResponse works
  end-to-end).

Responses close the connection (``Connection: close``) — body framing
by EOF keeps the writer trivial and curl/browser compatible.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional, Tuple

import ray_tpu
from ray_tpu.serve.http import Request, Response
from ray_tpu.serve.request_trace import new_request_id

MAX_BODY = 256 << 20          # reject absurd request bodies
ROUTE_CACHE_TTL_S = 1.0


class HTTPProxy:
    def __init__(self, controller, host: str = "127.0.0.1",
                 port: int = 8000, fallback_ephemeral: bool = True):
        #: per-node proxies all try the SAME configured port (one per
        #: host on a real pod); co-located nodes (single-host test
        #: clusters) lose the race and fall back to an ephemeral port
        self._fallback_ephemeral = fallback_ephemeral
        self._controller = controller
        self._handles: Dict[str, Any] = {}
        self._stream_handles: Dict[str, Any] = {}
        # (prefill, decode) -> DisaggRouter, for disagg-flagged routes
        self._disagg_routers: Dict[Tuple[str, str], Any] = {}
        self._routes: Dict[str, dict] = {}
        self._routes_at = 0.0
        self._routes_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="serve-http")
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._server = None
        self.port = None
        self.host = host
        self._thread = threading.Thread(
            target=self._run_loop, args=(host, port),
            name="serve_http", daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)
        if self.port is None:
            raise RuntimeError("HTTP proxy failed to bind "
                               f"{host}:{port}")

    # ------------------------------------------------------------ server
    def _run_loop(self, host: str, port: int) -> None:
        asyncio.set_event_loop(self._loop)

        async def boot():
            try:
                self._server = await asyncio.start_server(
                    self._serve_conn, host, port)
            except OSError:
                if not (self._fallback_ephemeral and port):
                    raise
                self._server = await asyncio.start_server(
                    self._serve_conn, host, 0)
            self.port = self._server.sockets[0].getsockname()[1]
            self._started.set()

        try:
            self._loop.run_until_complete(boot())
        except OSError:
            self._started.set()
            return
        self._loop.run_forever()

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            req = await self._read_request(reader)
            if req is None:
                return
            await self._dispatch(req, writer)
        except _HTTPError as e:
            try:
                await self._write_simple(writer, e.status,
                                         {"error": e.message})
            except Exception:
                pass
        except Exception as e:  # noqa: BLE001
            try:
                await self._write_simple(
                    writer, 500, {"error": str(e)})
            except Exception:
                pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _read_request(self, reader) -> Optional[Request]:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError):
            return None
        lines = head.decode("latin-1").split("\r\n")
        method, target, _ = lines[0].split(" ", 2)
        headers = []
        for ln in lines[1:]:
            if not ln:
                continue
            k, _, v = ln.partition(":")
            headers.append((k.strip(), v.strip()))
        path, _, query = target.partition("?")
        length = 0
        chunked = False
        for k, v in headers:
            lk = k.lower()
            if lk == "content-length":
                length = int(v)
            elif lk == "transfer-encoding" and "chunked" in v.lower():
                chunked = True
        if chunked:
            body = await self._read_chunked(reader)
        elif length:
            if length > MAX_BODY:
                raise _HTTPError(413, "request body too large")
            body = await reader.readexactly(length)
        else:
            body = b""
        return Request(method, path, query, headers, body)

    @staticmethod
    async def _read_chunked(reader) -> bytes:
        out = bytearray()
        while True:
            size_line = await reader.readline()
            size = int(size_line.strip().split(b";")[0], 16)
            if size == 0:
                await reader.readline()
                return bytes(out)
            if len(out) + size > MAX_BODY:
                raise ValueError("chunked body too large")
            out += await reader.readexactly(size)
            await reader.readline()  # trailing CRLF

    # ------------------------------------------------------------ routes
    def _refresh_routes(self) -> None:
        # blocking: call from the thread pool, never the event loop
        routes = ray_tpu.get(
            self._controller.get_routes_info.remote())
        with self._routes_lock:
            self._routes = routes
            self._routes_at = time.monotonic()

    def _match(self, path: str) -> Optional[dict]:
        # longest-prefix match (reference ProxyRouter semantics)
        with self._routes_lock:
            routes = self._routes
        for prefix in sorted(routes, key=len, reverse=True):
            if path == prefix or path.startswith(
                    prefix.rstrip("/") + "/") or prefix == "/":
                return routes[prefix]
        return None

    async def _route_for(self, path: str, loop) -> Optional[dict]:
        with self._routes_lock:
            stale = time.monotonic() - self._routes_at \
                > ROUTE_CACHE_TTL_S
        if stale:
            await loop.run_in_executor(self._pool, self._refresh_routes)
        found = self._match(path)
        if found is None and not stale:
            # never 404 off a cached table alone: a route deployed
            # moments ago must be visible immediately
            await loop.run_in_executor(self._pool, self._refresh_routes)
            found = self._match(path)
        return found

    def _handle_for(self, name: str, stream: bool, req=None,
                    request_id: Optional[str] = None):
        from ray_tpu.serve.handle import DeploymentHandle
        table = self._stream_handles if stream else self._handles
        h = table.get(name)
        if h is None:
            h = DeploymentHandle(name, self._controller)
            if stream:
                h = h.options(stream=True)
            table[name] = h
        # session affinity for multi-turn clients: every request
        # carrying the same x-session-id lands on the same replica, so
        # the conversation's shared prefix stays warm in that replica's
        # radix KV cache (options() shares the cached handle's router —
        # load/affinity state spans all sessions)
        if req is not None:
            # absent headers read back as "" — keep them None so the
            # per-request options() copy (request_id is always set now)
            # doesn't turn "no x-priority header" into a 400
            sid = req.header("x-session-id") or None
            tenant = req.header("x-tenant") or None
            priority = req.header("x-priority") or None
            if sid or tenant or priority or request_id:
                try:
                    h = h.options(stream=stream, session_id=sid,
                                  tenant=tenant, priority=priority,
                                  request_id=request_id)
                except ValueError:
                    raise _HTTPError(
                        400, f"unknown x-priority {priority!r}")
        return h

    @staticmethod
    def _request_id_for(req: Request) -> str:
        """Trace identity for this HTTP request: honour the client's
        ``x-request-id`` (so their logs join our waterfalls), else mint
        one. Echoed back in the ``X-Request-Id`` response header and in
        429/500 error bodies either way."""
        return req.header("x-request-id") or new_request_id()

    # ---------------------------------------------------------- dispatch
    async def _dispatch(self, req: Request,
                        writer: asyncio.StreamWriter) -> None:
        loop = asyncio.get_running_loop()
        route = await self._route_for(req.path, loop)
        if route is None:
            await self._write_simple(writer, 404, {"error": "no route"})
            return
        if route.get("disagg"):
            await self._dispatch_disagg(route, req, writer, loop)
        elif route["asgi"]:
            await self._dispatch_asgi(route, req, writer, loop)
        elif route["streaming"]:
            await self._dispatch_stream(route, req, writer, loop)
        else:
            await self._dispatch_unary(route, req, writer, loop)

    @staticmethod
    def _payload(req: Request) -> Any:
        return json.loads(req.body) if req.body else None

    async def _dispatch_unary(self, req_route, req, writer, loop):
        rid = self._request_id_for(req)
        handle = self._handle_for(req_route["name"], stream=False,
                                  req=req, request_id=rid)

        def call():
            payload = self._payload(req)
            resp = handle.remote(payload) if payload is not None \
                else handle.remote()
            return resp.result(timeout_s=60)

        try:
            result = await loop.run_in_executor(self._pool, call)
        except Exception as e:  # noqa: BLE001
            await self._write_error(writer, e, request_id=rid)
            return
        if isinstance(result, Response):
            await self._write_head(writer, result.status, result.headers
                                   + [("X-Request-Id", rid),
                                      ("Content-Length",
                                       str(len(result.body)))])
            writer.write(result.body)
            await writer.drain()
            return
        await self._write_simple(writer, 200, result,
                                 extra_headers=[("X-Request-Id", rid)])

    async def _dispatch_stream(self, req_route, req, writer, loop):
        rid = self._request_id_for(req)
        handle = self._handle_for(req_route["name"], stream=True,
                                  req=req, request_id=rid)

        def start():
            payload = self._payload(req)
            return handle.remote(payload) if payload is not None \
                else handle.remote()

        try:
            gen = await loop.run_in_executor(self._pool, start)
            # core streaming: tokens arrive as the replica produces
            # them (STREAM_ITEM push), so each next() returns the next
            # token without a polling round-trip
            it = iter(gen)
            first = await loop.run_in_executor(
                self._pool, next, it, _END)
        except Exception as e:  # noqa: BLE001
            # admission sheds before headers go out, so a 429 is still
            # expressible here (unlike mid-stream failures below)
            await self._write_error(writer, e, request_id=rid)
            return
        await self._write_head(
            writer, 200,
            [("Content-Type", "text/plain; charset=utf-8"),
             ("X-Request-Id", rid),
             ("X-Accel-Buffering", "no")])
        try:
            chunk = first
            while chunk is not _END:
                writer.write(_as_bytes(chunk))
                await writer.drain()
                chunk = await loop.run_in_executor(
                    self._pool, next, it, _END)
        except BaseException:  # noqa: BLE001
            # headers are out: closing mid-body IS the error signal —
            # a second "500" head spliced into the body would corrupt
            # the stream. Cancel so the replica's live stream (and its
            # ongoing-count used for load balancing) is not leaked.
            gen.cancel()

    async def _dispatch_disagg(self, req_route, req, writer, loop):
        """Disaggregated route: drive the (prefill, decode) pair
        through a cached :class:`~ray_tpu.serve.disagg.DisaggRouter`
        instead of a single-deployment handle. Body: a prompt-id list,
        or ``{"prompt": [...], "max_new_tokens": n}``; tokens stream
        back exactly like a colocated streaming route."""
        rid = self._request_id_for(req)
        pair = req_route["disagg"]
        key = (pair["prefill"], pair["decode"])
        router = self._disagg_routers.get(key)
        if router is None:
            from ray_tpu.serve.disagg import DisaggRouter
            router = DisaggRouter(pair["prefill"], pair["decode"],
                                  self._controller)
            self._disagg_routers[key] = router
        sid = req.header("x-session-id") or None
        payload = self._payload(req)
        if isinstance(payload, dict):
            prompt = payload.get("prompt") or []
            mnt = payload.get("max_new_tokens")
        else:
            prompt, mnt = payload or [], None

        def start():
            it = router.options(
                stream=True, session_id=sid,
                request_id=rid).generate.remote(prompt, mnt)
            return it, iter(it)

        try:
            gen, it = await loop.run_in_executor(self._pool, start)
            first = await loop.run_in_executor(
                self._pool, next, it, _END)
        except Exception as e:  # noqa: BLE001
            await self._write_error(writer, e, request_id=rid)
            return
        await self._write_head(
            writer, 200,
            [("Content-Type", "text/plain; charset=utf-8"),
             ("X-Request-Id", rid),
             ("X-Accel-Buffering", "no")])
        try:
            chunk = first
            while chunk is not _END:
                writer.write(_as_bytes(chunk))
                await writer.drain()
                chunk = await loop.run_in_executor(
                    self._pool, next, it, _END)
        except BaseException:  # noqa: BLE001
            # headers are out: closing mid-body is the error signal
            gen.close()

    async def _dispatch_asgi(self, req_route, req, writer, loop):
        handle = self._handle_for(req_route["name"], stream=True)

        def start():
            # internal dunder method: bypass the public __getattr__
            # (which refuses underscore names)
            return handle._route("__serve_asgi_stream__", (req,), {})

        try:
            gen = await loop.run_in_executor(self._pool, start)
            it = iter(gen)
            first = await loop.run_in_executor(
                self._pool, next, it, _END)
        except Exception as e:  # noqa: BLE001
            await self._write_simple(writer, 500, {"error": str(e)})
            return
        started = False
        try:
            event = first
            while event is not _END:
                if event["type"] == "http.response.start":
                    headers = [
                        (k.decode("latin-1"), v.decode("latin-1"))
                        for k, v in event.get("headers", [])]
                    headers = [(k, v) for k, v in headers
                               if k.lower() not in (
                                   "connection", "transfer-encoding")]
                    await self._write_head(
                        writer, int(event["status"]), headers)
                    started = True
                elif event["type"] == "http.response.body":
                    if not started:
                        await self._write_head(writer, 200, [])
                        started = True
                    body = event.get("body", b"")
                    if body:
                        writer.write(body)
                        await writer.drain()
                event = await loop.run_in_executor(
                    self._pool, next, it, _END)
            if not started:
                await self._write_simple(writer, 500,
                                         {"error": "empty ASGI reply"})
        except BaseException:  # noqa: BLE001
            gen.cancel()
            if not started:
                await self._write_simple(
                    writer, 500, {"error": "stream failed"})

    # ------------------------------------------------------------ output
    async def _write_error(self, writer, e: BaseException,
                           request_id: Optional[str] = None) -> None:
        """Typed error mapping: an admission shed is the CLIENT's
        signal to back off (429 + tenant/priority/reason so it can
        retry with a higher class), not a server fault. Both bodies
        carry ``request_id`` — the same id the SHED/FAILED waterfall is
        filed under, so ``ray-tpu trace <id>`` explains the error."""
        from ray_tpu.exceptions import AdmissionRejectedError
        if isinstance(e, AdmissionRejectedError):
            rid = e.request_id or request_id or ""
            await self._write_simple(
                writer, 429,
                {"error": str(e), "tenant": e.tenant,
                 "priority": e.priority, "reason": e.reason,
                 "request_id": rid},
                extra_headers=[("X-Request-Id", rid)] if rid else None)
            return
        await self._write_simple(
            writer, 500,
            {"error": str(e), "request_id": request_id or ""},
            extra_headers=([("X-Request-Id", request_id)]
                           if request_id else None))

    @staticmethod
    async def _write_head(writer, status: int,
                          headers) -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  429: "Too Many Requests",
                  500: "Internal Server Error"}.get(status, "")
        out = [f"HTTP/1.1 {status} {reason}".encode()]
        seen_ct = False
        for k, v in headers:
            seen_ct = seen_ct or k.lower() == "content-type"
            out.append(f"{k}: {v}".encode("latin-1"))
        if not seen_ct:
            out.append(b"Content-Type: application/octet-stream")
        out.append(b"Connection: close")
        writer.write(b"\r\n".join(out) + b"\r\n\r\n")
        await writer.drain()

    async def _write_simple(self, writer, status: int, payload: Any,
                            extra_headers=None) -> None:
        body = json.dumps(payload).encode()
        await self._write_head(
            writer, status,
            [("Content-Type", "application/json"),
             ("Content-Length", str(len(body)))]
            + list(extra_headers or []))
        writer.write(body)
        await writer.drain()

    # --------------------------------------------------------------- api
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def node_id(self) -> Optional[str]:
        return ray_tpu.get_runtime_context().get_node_id()

    def stop(self) -> None:
        def shutdown():
            if self._server is not None:
                self._server.close()
            self._loop.stop()
        self._loop.call_soon_threadsafe(shutdown)
        self._pool.shutdown(wait=False)


_END = object()


class _HTTPError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def _as_bytes(chunk: Any) -> bytes:
    if isinstance(chunk, (bytes, bytearray)):
        return bytes(chunk)
    if isinstance(chunk, str):
        return chunk.encode()
    return (json.dumps(chunk) + "\n").encode()
