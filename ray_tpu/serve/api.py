"""serve.run / serve.start / serve.shutdown — the public entry points.

Reference: ``python/ray/serve/api.py`` (``serve.run`` :522). The
controller is a named singleton actor; ``run`` walks the bound app DAG
depth-first, deploying inner deployments first and substituting their
DeploymentHandles into outer constructor args (model composition).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve._private.controller import (
    CONTROLLER_NAME, ServeController)

_proxy_actor = None
_proxy_actors: Dict[str, Any] = {}   # node id hex -> proxy actor
_grpc_proxy_actor = None


def _get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        cls = ray_tpu.remote(num_cpus=0.5, name=CONTROLLER_NAME,
                             lifetime="detached",
                             max_concurrency=16)(ServeController)
        return cls.remote()


def _controller_or_none():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return None


def run(target: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", blocking: bool = False,
        _local_testing_mode: bool = False) -> DeploymentHandle:
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError(
            "serve.run expects a bound deployment (use .bind())")
    controller = _get_or_create_controller()

    apps: Dict[str, Application] = {}
    target._collect(apps)  # topological: dependencies first

    handles: Dict[str, DeploymentHandle] = {}
    for dep_name, app in apps.items():
        def sub(v):
            if isinstance(v, Application):
                return handles[v.deployment.name]
            return v
        init_args = tuple(sub(a) for a in app.init_args)
        init_kwargs = {k: sub(v) for k, v in app.init_kwargs.items()}
        is_ingress = dep_name == target.deployment.name
        ray_tpu.get(controller.deploy.remote(
            dep_name, app.deployment, init_args, init_kwargs,
            route_prefix if is_ingress else None,
            name if is_ingress else None))
        handles[dep_name] = DeploymentHandle(dep_name, controller)

    handle = handles[target.deployment.name]
    if blocking:  # pragma: no cover - interactive use
        while True:
            time.sleep(1)
    return handle


def start(http_options: Optional[Dict[str, Any]] = None,
          grpc_options: Optional[Dict[str, Any]] = None,
          **kwargs) -> None:
    """Start the ingress proxies (reference ``serve.start``). HTTP
    starts when ``http_options`` is given or when neither option is
    given (legacy default); gRPC starts only when ``grpc_options`` is
    given — a gRPC-only start must not grab the default HTTP port.

    One HTTP proxy runs on EVERY alive node (reference: proxy-per-node
    behind ProxyRouter) unless ``http_options={"location": "HeadOnly"}``.
    On a real pod each node binds the same configured port; when several
    nodes share one host (tests), secondary proxies take ephemeral
    ports — ``proxy_addresses()`` lists them all."""
    global _proxy_actor, _grpc_proxy_actor
    want_http = http_options is not None or grpc_options is None
    http_options = http_options or {}
    controller = _get_or_create_controller()
    if want_http and _proxy_actor is None:
        from ray_tpu.serve._private.proxy import HTTPProxy
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        host = http_options.get("host", "127.0.0.1")
        port = http_options.get("port", 8000)
        location = http_options.get("location", "EveryNode")
        nodes = [n for n in ray_tpu.nodes() if n.get("alive")]
        local_hex = ray_tpu.get_runtime_context().get_node_id()
        if location != "EveryNode":
            nodes = [n for n in nodes if n["node_id"] == local_hex]
        for n in nodes or [{"node_id": local_hex}]:
            nid = n["node_id"]
            # every node's proxy tries the SAME configured port (one
            # proxy per host on a real pod); co-located nodes in
            # single-host test clusters lose the bind race and fall
            # back to an ephemeral port inside HTTPProxy
            cls = ray_tpu.remote(
                num_cpus=0, max_concurrency=16,
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=nid, soft=True))(HTTPProxy)
            actor = cls.remote(controller, host, port)
            _proxy_actors[nid] = actor
            if nid == local_hex or _proxy_actor is None:
                _proxy_actor = actor
    if grpc_options is not None and _grpc_proxy_actor is None:
        from ray_tpu.serve._private.grpc_proxy import GrpcProxy
        gcls = ray_tpu.remote(num_cpus=0.25,
                              max_concurrency=16)(GrpcProxy)
        _grpc_proxy_actor = gcls.remote(
            controller, grpc_options.get("host", "127.0.0.1"),
            grpc_options.get("port", 9000),
            grpc_options.get("grpc_servicer_functions", ()))


def grpc_proxy_address() -> Optional[str]:
    if _grpc_proxy_actor is None:
        return None
    return ray_tpu.get(_grpc_proxy_actor.address.remote())


def proxy_address() -> Optional[str]:
    if _proxy_actor is None:
        return None
    return ray_tpu.get(_proxy_actor.address.remote())


def proxy_addresses() -> Dict[str, str]:
    """All per-node proxy addresses, keyed by node id hex."""
    return {nid: ray_tpu.get(a.address.remote())
            for nid, a in _proxy_actors.items()}


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    controller = _controller_or_none()
    if controller is None:
        raise RuntimeError("Serve is not running")
    return DeploymentHandle(deployment_name, controller, app_name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = _controller_or_none()
    if controller is None:
        raise RuntimeError("Serve is not running")
    ingress = ray_tpu.get(controller.get_app_ingress.remote(name))
    if ingress is None:
        raise RuntimeError(f"No application named {name!r}")
    return DeploymentHandle(ingress, controller, name)


def status() -> Dict[str, Any]:
    controller = _controller_or_none()
    if controller is None:
        return {"deployments": []}
    return {"deployments": ray_tpu.get(
        controller.list_deployments.remote())}


def delete(name: str) -> None:
    controller = _controller_or_none()
    if controller is not None:
        ray_tpu.get(controller.delete_deployment.remote(name))


def shutdown() -> None:
    global _proxy_actor, _grpc_proxy_actor
    controller = _controller_or_none()
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown.remote(), timeout=30)
        except Exception:
            pass
        try:
            ray_tpu.kill(controller)
        except Exception:
            pass
    for actor in set(_proxy_actors.values()) | (
            {_proxy_actor} if _proxy_actor is not None else set()):
        try:
            ray_tpu.get(actor.stop.remote(), timeout=10)
            ray_tpu.kill(actor)
        except Exception:
            pass
    _proxy_actors.clear()
    _proxy_actor = None
    if _grpc_proxy_actor is not None:
        try:
            ray_tpu.get(_grpc_proxy_actor.stop.remote(), timeout=10)
            ray_tpu.kill(_grpc_proxy_actor)
        except Exception:
            pass
        _grpc_proxy_actor = None
