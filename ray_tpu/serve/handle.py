"""DeploymentHandle: client-side router — gauge-aware by default.

Reference: ``python/ray/serve/handle.py`` + ``_private/router.py:259``
and ``replica_scheduler/pow_2_scheduler.py:44``. Three routing
policies (``options(routing_policy=...)``, default ``"gauge"``):

- ``"gauge"`` — route on the per-replica ENGINE gauges (free decode
  slots, free KV blocks, queue depth, TTFT EWMA from
  ``Replica.stats()``), probed asynchronously and cached for
  ``gauge_refresh_s``; replicas without engine gauges (plain
  deployments) fall back to power-of-two-choices. When direct probes
  go quiet the router backfills from the controller's fleet metrics
  plane (``/api/v0/metrics/fleet``), matching rows to replicas by pid.
- ``"pow2"`` — classic power-of-two-choices on the router's own
  outstanding-refs count per replica plus live streams.
- ``"round_robin"`` — cycle the membership list (the pre-gauge
  baseline; ``bench_serve --fleet`` measures gauge routing against it).

``options(session_id=...)`` adds **session affinity**: every call with
the same session id lands on the same replica while it lives, so a
multi-turn conversation's shared prefix KV blocks are HIT in that
replica's radix cache instead of re-prefetched cold elsewhere.
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional

import ray_tpu


def gauge_score(g: Dict[str, Any]) -> float:
    """Desirability of a replica from its engine gauges (higher is
    better): capacity to start decoding now (free slots), room for new
    sequences' KV (free blocks), minus admission backlog and the
    latency users are currently seeing (TTFT EWMA)."""
    free_slots = g.get("free_slots") or 0
    total_slots = free_slots + (g.get("active_slots") or 0)
    slots_frac = free_slots / total_slots if total_slots else 0.0
    total_blocks = g.get("total_blocks") or 0
    blocks_frac = (g.get("free_blocks") or 0) / total_blocks \
        if total_blocks else 0.0
    queue = g.get("queue_depth") or 0
    ttft = g.get("ttft_ewma_s") or 0.0
    return 2.0 * slots_frac + blocks_frac - 0.5 * queue \
        - min(float(ttft), 2.0)


def _ship_failure(tracer, trace, err: BaseException) -> None:
    """Client-observed terminal failure: the replica (possibly dead —
    SIGKILL mid-decode) cannot ship this request's trace, so the
    router part does, ending it in a FAILED span naming the typed
    error. No-op without a trace; single-shot per trace."""
    if trace is None or tracer is None:
        return
    try:
        from ray_tpu.serve import request_trace as RT
        trace.span(RT.FAILED, time.time(), None,
                   error=type(err).__name__, detail=str(err)[:200])
        tracer.finish(trace)
    except Exception:
        pass


class DeploymentResponse:
    """Future-like result of ``handle.remote()`` (reference
    ``handle.py:DeploymentResponse``). Submission to a dead replica
    only surfaces at get-time in this runtime, so the dead-replica
    retry lives HERE: on actor death, the originating handle refreshes
    membership and re-routes once."""

    def __init__(self, ref, retry=None, tracer=None, trace=None):
        self._ref = ref
        self._retry = retry  # () -> DeploymentResponse, single-shot
        self._tracer = tracer
        self._trace = trace

    def result(self, timeout_s: Optional[float] = None):
        try:
            out = ray_tpu.get(self._ref, timeout=timeout_s)
            self._trace = None   # replica-side trace owns the outcome
            return out
        except Exception as e:
            if self._retry is not None and _is_actor_death(e):
                retry, self._retry = self._retry, None
                self._trace = None   # the retry mints a fresh trace
                return retry().result(timeout_s=timeout_s)
            trace, self._trace = self._trace, None
            _ship_failure(self._tracer, trace, e)
            raise

    def _to_object_ref(self):
        return self._ref


def _is_actor_death(e: BaseException) -> bool:
    from ray_tpu.exceptions import ActorDiedError, ActorError
    return isinstance(e, (ActorDiedError, ActorError))


class DeploymentResponseGenerator:
    """Streaming response of ``options(stream=True)`` (reference:
    ``handle.py:DeploymentResponseGenerator``): a thin value-yielding
    view over a core :class:`~ray_tpu.ObjectRefGenerator` — the replica
    executes the method as a streaming generator task, each item is its
    own object reported as produced, and the core credit window paces
    the producer. Iterating yields materialized values; ``cancel()``
    (or GC of an abandoned generator) cancels the replica-side task and
    frees unconsumed items. A replica death before the first item
    re-routes once, like unary ``DeploymentResponse``."""

    def __init__(self, gen, router=None, rkey=None, retry=None,
                 tracer=None, trace=None):
        self._gen = gen          # core ObjectRefGenerator
        self._router = router
        self._rkey = rkey
        self._retry = retry      # () -> DeploymentResponseGenerator
        self._tracer = tracer
        self._trace = trace
        self._started = False
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        try:
            ref = next(self._gen)
        except StopIteration:
            self._trace = None   # clean end: the replica shipped it
            self._finish()
            raise
        except Exception as e:
            if not self._started and self._retry is not None \
                    and _is_actor_death(e):
                # membership was stale and the replica is gone: resync
                # and re-route this stream once
                retry, self._retry = self._retry, None
                self._finish()
                fresh = retry()
                self._gen = fresh._gen
                self._router = fresh._router
                self._rkey = fresh._rkey
                self._tracer = fresh._tracer
                self._trace = fresh._trace
                self._done = False
                return next(self)
            trace, self._trace = self._trace, None
            _ship_failure(self._tracer, trace, e)
            self._finish()
            raise
        self._started = True
        try:
            return ray_tpu.get(ref)
        except BaseException as e:
            # a mid-stream exception is delivered as the failing item:
            # the stream is over — release the router's stream count.
            # A dead replica cannot ship its trace, so the router part
            # records the FAILED terminal here.
            trace, self._trace = self._trace, None
            _ship_failure(self._tracer, trace, e)
            self._finish()
            raise

    def _finish(self) -> None:
        if not self._done:
            self._done = True
            if self._router is not None:
                self._router.stream_finished(self._rkey)

    def cancel(self) -> None:
        if not self._done:
            self._finish()
            self._gen.close()

    def __del__(self):
        try:
            self.cancel()
        except Exception:
            pass


class _Router:
    """Shared routing state: membership, per-replica load, model
    affinity. One _Router is shared by a handle and every configured
    copy made via ``options()``, so load tracking spans them all."""

    #: seconds a gauge snapshot stays fresh before a new async probe
    gauge_refresh_s = 0.5
    #: direct-probe silence after which the fleet plane backfills
    gauge_stale_s = 3.0
    #: gauge-score bonus for a replica whose radix trie already holds a
    #: first-turn request's prefix (worth ~a free-slot fraction — a
    #: warm prefix beats marginal capacity, but never a dead replica)
    prefix_match_bonus = 1.5
    #: seconds between admission-policy refreshes from the controller
    admission_policy_poll_s = 2.0

    def __init__(self, deployment_name: str, controller):
        self.deployment_name = deployment_name
        self.controller = controller
        self.version = -1
        self.replicas: List[Any] = []
        # stable replica key (actor id hex) -> outstanding unary refs
        self.outstanding: Dict[bytes, List[Any]] = {}
        # stable replica key -> live stream count
        self.streams: Dict[bytes, int] = {}
        # model id -> stable replica key (soft affinity, reference:
        # multiplexed model routing in replica_scheduler)
        self.model_affinity: Dict[str, bytes] = {}
        # session id -> stable replica key: multi-turn stickiness so a
        # session's shared prefix blocks stay where its KV lives
        self.session_affinity: Dict[str, bytes] = {}
        self.policy = "gauge"
        # -- gauge cache: rkey -> {"t": monotonic, <engine stats>}
        self.gauges: Dict[bytes, Dict[str, Any]] = {}
        self._gauge_refs: Dict[bytes, Any] = {}   # in-flight probes
        self._pids: Dict[int, bytes] = {}         # replica pid -> rkey
        self._last_probe = 0.0
        self._rr_next = 0
        # SLO-aware admission (serve/admission.py); shared across
        # options() copies like the rest of the router so per-tenant
        # budget accounting spans them. None = admit everything.
        self.admission = None
        self._last_policy_poll = 0.0
        # per-request tracer (serve/request_trace.py): mints
        # request_ids + the 1-in-N sampling verdict at the routing
        # tier; shared across options() copies so the sample cadence
        # spans them. Built lazily (needs the runtime config).
        self.tracer = None

    def _get_tracer(self):
        if self.tracer is None:
            from ray_tpu.serve.request_trace import RequestTracer
            cfg = None
            try:
                from ray_tpu.core.global_state import try_global_worker
                cfg = getattr(try_global_worker(), "config", None)
            except Exception:
                pass
            self.tracer = RequestTracer(cfg, part="router")
        return self.tracer

    @staticmethod
    def _key(replica) -> bytes:
        aid = getattr(replica, "_actor_id", None)
        return aid.binary() if aid is not None else id(replica)

    def refresh(self, force: bool = False) -> None:
        version = ray_tpu.get(
            self.controller.get_version.remote(self.deployment_name))
        if version != self.version or force:
            # Atomic snapshot: version and replica list must agree.
            version, replicas = ray_tpu.get(
                self.controller.get_membership.remote(self.deployment_name))
            self.replicas = replicas
            self.version = version
            live = {self._key(r) for r in replicas}
            # stable keys survive a membership change for replicas that
            # remain; state for removed replicas is dropped, and affinity
            # to a vanished replica is invalidated rather than silently
            # pointing at a different one
            self.outstanding = {k: v for k, v in self.outstanding.items()
                                if k in live}
            self.streams = {k: v for k, v in self.streams.items()
                            if k in live}
            self.model_affinity = {m: k for m, k in
                                   self.model_affinity.items() if k in live}
            self.session_affinity = {
                s: k for s, k in self.session_affinity.items()
                if k in live}
            self.gauges = {k: v for k, v in self.gauges.items()
                           if k in live}
            self._gauge_refs = {k: v for k, v in self._gauge_refs.items()
                                if k in live}
            self._pids = {p: k for p, k in self._pids.items()
                          if k in live}

    def load(self, replica) -> int:
        k = self._key(replica)
        refs = self.outstanding.setdefault(k, [])
        if refs:
            ready, pending = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=0)
            self.outstanding[k] = list(pending)
        return len(self.outstanding[k]) + self.streams.get(k, 0)

    # -- gauge probing ------------------------------------------------
    def _poll_gauges(self) -> None:
        """Harvest completed async ``Replica.stats`` probes (never
        blocks the request path) and launch a fresh round when the
        cache ages past ``gauge_refresh_s``."""
        now = time.monotonic()
        for k, ref in list(self._gauge_refs.items()):
            try:
                ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
            except Exception:
                del self._gauge_refs[k]
                continue
            if not ready:
                continue
            del self._gauge_refs[k]
            try:
                s = ray_tpu.get(ref)
            except Exception:
                self.gauges.pop(k, None)
                continue
            if isinstance(s, dict):
                g = dict(s.get("engine") or {})
                g["ongoing"] = s.get("ongoing")
                g["t"] = now
                self.gauges[k] = g
                pid = s.get("pid")
                if pid is not None:
                    self._pids[int(pid)] = k
        if now - self._last_probe >= self.gauge_refresh_s:
            self._last_probe = now
            for r in self.replicas:
                k = self._key(r)
                if k not in self._gauge_refs:
                    try:
                        self._gauge_refs[k] = r.stats.remote()
                    except Exception:
                        pass

    def _poll_admission_policy(self) -> None:
        """Refresh the admission controller's shed rules from the
        serve controller's config plane (fed by the dashboard's
        ``POST /api/v0/admission/policy``). Rate-limited; a newer seq
        swaps the policy in place, keeping budget spend windows."""
        if self.admission is None:
            return
        now = time.monotonic()
        if now - self._last_policy_poll < self.admission_policy_poll_s:
            return
        self._last_policy_poll = now
        try:
            seq, d = ray_tpu.get(
                self.controller.get_admission_policy.remote())
        except Exception:
            return
        if d is None or seq <= self.admission.policy_seq:
            return
        from ray_tpu.serve.admission import AdmissionPolicy
        try:
            self.admission.set_policy(AdmissionPolicy.from_dict(d),
                                      seq=seq)
        except ValueError:
            pass  # controller validated on write; never fail a route

    def _fleet_backfill(self) -> None:
        """Direct probes gone quiet (replica event loops saturated):
        fall back to the controller's metrics plane —
        ``/api/v0/metrics/fleet`` aggregates every replica's engine
        gauges — and map rows onto replicas by pid."""
        if not self._pids:
            return
        try:
            from ray_tpu.util.state import fleet_metrics
            rows = fleet_metrics(window_s=10.0).get("rows") or []
        except Exception:
            return
        now = time.monotonic()
        for row in rows:
            k = self._pids.get(row.get("pid"))
            if k is None:
                continue
            # a fleet row is only as fresh as its origin's last metric
            # report: stamping it "now" would let a long-dead replica's
            # numbers route traffic forever. Rows older than the
            # staleness bound are skipped (pow2 fallback); adopted rows
            # carry their ring timestamp so they age out naturally.
            age = float(row.get("last_report_s") or 0.0)
            if age > self.gauge_stale_s:
                continue
            g = self.gauges.setdefault(k, {})
            if now - g.get("t", 0.0) <= self.gauge_stale_s:
                continue   # direct probe is fresher
            if row.get("queue_depth") is not None:
                g["queue_depth"] = row["queue_depth"]
            if row.get("ttft_p50_ms") is not None:
                g["ttft_ewma_s"] = row["ttft_p50_ms"] / 1e3
            g["t"] = now - age

    @staticmethod
    def _has_signal(g: Dict[str, Any]) -> bool:
        return any(key in g for key in
                   ("free_slots", "queue_depth", "ttft_ewma_s"))

    def _fresh_gauges(self) -> Dict[bytes, Dict[str, Any]]:
        now = time.monotonic()
        fresh = {k: g for k, g in self.gauges.items()
                 if now - g.get("t", 0.0) <= self.gauge_stale_s
                 and self._has_signal(g)}
        if not fresh:
            self._fleet_backfill()
            fresh = {k: g for k, g in self.gauges.items()
                     if now - g.get("t", 0.0) <= self.gauge_stale_s
                     and self._has_signal(g)}
        return fresh

    def pick(self, model_id: Optional[str],
             session_id: Optional[str] = None,
             policy: Optional[str] = None,
             prefix_fp: Optional[int] = None):
        """Returns (replica, stable_key). ``prefix_fp`` (a
        ``prefix_cache.prefix_fingerprint`` of the request's leading KV
        block — typically its system prompt) steers a FIRST-turn
        request toward the replica whose radix trie already caches that
        prefix; once a session is pinned, affinity wins and the
        fingerprint is moot."""
        n = len(self.replicas)
        by_key = {self._key(r): r for r in self.replicas}
        policy = policy or self.policy
        if session_id is not None:
            k = self.session_affinity.get(session_id)
            if k is not None and k in by_key:
                # sticky: this session's earlier turns' prefix blocks
                # live (warm) in this replica's radix cache
                return by_key[k], k
        if model_id is not None:
            k = self.model_affinity.get(model_id)
            if k is not None and k in by_key:
                # soft affinity: keep one model's requests on one replica
                # so its weights stay resident
                return by_key[k], k
        replica = None
        if n == 1:
            replica = self.replicas[0]
        elif policy == "round_robin":
            replica = self.replicas[self._rr_next % n]
            self._rr_next += 1
        elif policy == "gauge":
            self._poll_gauges()
            fresh = self._fresh_gauges()

            def score(g):
                s = gauge_score(g)
                if prefix_fp is not None and prefix_fp in \
                        (g.get("prefix_fingerprints") or ()):
                    # cold-session placement: the replica's trie
                    # already holds this request's prefix blocks —
                    # prefill there skips them instead of recomputing
                    s += self.prefix_match_bonus
                return s

            scored = [(score(fresh[self._key(r)]), i, r)
                      for i, r in enumerate(self.replicas)
                      if self._key(r) in fresh]
            if scored:
                # in-flight work this router already routed but the
                # gauges haven't seen yet still counts against a
                # replica (prevents herding between probe rounds)
                best = max(scored, key=lambda t: (
                    t[0] - 0.25 * self.load(t[2]), -t[1]))
                replica = best[2]
        if replica is None:
            # pow2 (or gauge fallback: no engine gauges yet/at all)
            i, j = random.sample(range(n), 2)
            a, b = self.replicas[i], self.replicas[j]
            replica = a if self.load(a) <= self.load(b) else b
        k = self._key(replica)
        if model_id is not None:
            self.model_affinity[model_id] = k
        if session_id is not None:
            self.session_affinity[session_id] = k
        return replica, k

    def stream_started(self, k: bytes) -> None:
        self.streams[k] = self.streams.get(k, 0) + 1

    def stream_finished(self, k: bytes) -> None:
        n = self.streams.get(k, 0) - 1
        if n > 0:
            self.streams[k] = n
        else:
            self.streams.pop(k, None)


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._route(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 app_name: str = "default", _router: Optional[_Router] = None,
                 _stream: bool = False, _model_id: Optional[str] = None,
                 _session_id: Optional[str] = None,
                 _routing_policy: Optional[str] = None,
                 _prefix_fingerprint: Optional[int] = None,
                 _tenant: Optional[str] = None,
                 _priority=None,
                 _request_id: Optional[str] = None):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._controller = controller
        self._router = _router or _Router(deployment_name, controller)
        self._stream = _stream
        self._model_id = _model_id
        self._session_id = _session_id
        self._routing_policy = _routing_policy
        self._prefix_fingerprint = _prefix_fingerprint
        self._tenant = _tenant
        self._priority = _priority
        self._request_id = _request_id

    # -- admission ----------------------------------------------------
    def enable_admission(self, policy=None):
        """Attach SLO-aware admission (``serve/admission.py``) to this
        handle's shared router: subsequent calls through this handle or
        any ``options()`` copy pass through per-tenant token budgets
        and priority shedding, raising
        :class:`~ray_tpu.exceptions.AdmissionRejectedError` when shed.
        Returns the :class:`~ray_tpu.serve.admission.
        AdmissionController` (for ``stats()``)."""
        from ray_tpu.serve.admission import AdmissionController
        if not isinstance(policy, AdmissionController):
            policy = AdmissionController(policy)
        self._router.admission = policy
        return policy

    # -- routing ------------------------------------------------------
    def _route(self, method: str, args, kwargs):
        r = self._router
        r.refresh()
        if not r.replicas:
            raise RuntimeError(
                f"Deployment {self.deployment_name!r} has no replicas")
        # Mint the request's trace identity HERE — the routing tier is
        # the first hop that sees every request (proxy-supplied ids
        # arrive via options(request_id=...)). The router is also the
        # sampling authority: the 1-in-N verdict rides the call context
        # to the replica, which materialises the waterfall and ships.
        tracer = r._get_tracer()
        trace = tracer.begin(request_id=self._request_id)
        rid = trace.request_id if trace is not None else self._request_id
        t_enqueue = time.time()
        if r.admission is not None:
            # Shed BEFORE pick: a rejected request must never touch a
            # replica queue (that queue depth is exactly what the shed
            # is protecting). Freshest engine gauges decide overload.
            r._poll_admission_policy()
            r._poll_gauges()
            try:
                r.admission.admit(
                    self._tenant, self._priority, r._fresh_gauges(),
                    tokens=kwargs.get("max_tokens"), request_id=rid)
            except Exception as e:
                # terminal at the router: the replica never sees this
                # request, so the router part ships the (QUEUED, SHED)
                # waterfall — a shed request is traceable from its id
                if trace is not None:
                    from ray_tpu.serve import request_trace as RT
                    now = time.time()
                    trace.span(RT.QUEUED, t_enqueue, now)
                    trace.span(RT.SHED, now, None,
                               error=type(e).__name__,
                               reason=getattr(e, "reason", None),
                               tenant=self._tenant,
                               priority=str(self._priority)
                               if self._priority is not None else None)
                    tracer.finish(trace)
                raise
        # Unwrap chained responses so downstream gets values, not
        # wrapper objects (reference: DeploymentResponse passing).
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: (v._to_object_ref()
                      if isinstance(v, DeploymentResponse) else v)
                  for k, v in kwargs.items()}
        replica, rkey = r.pick(self._model_id, self._session_id,
                               self._routing_policy,
                               prefix_fp=self._prefix_fingerprint)
        ctx = {"multiplexed_model_id": self._model_id or ""}
        if trace is not None:
            g = r.gauges.get(rkey)
            ctx["request_id"] = rid
            ctx["trace"] = {
                "sampled": trace.sampled,
                "enqueue_ts": t_enqueue,
                "policy": self._routing_policy or r.policy,
                "score": round(gauge_score(g), 4) if g else None,
                "admission": "admitted" if r.admission is not None
                else "bypass",
            }
        if self._stream:
            # core streaming generator task: the replica method's items
            # arrive as first-class objects with backpressure and the
            # runtime's delivery/fault guarantees — no replica-held
            # generator state, no chunk polling
            gen = replica.handle_request_stream.options(
                num_returns="streaming").remote(
                    ctx, method, *args, **kwargs)
            r.stream_started(rkey)

            def retry_on_dead_replica():
                r.refresh(force=True)
                return self._route(method, args, kwargs)

            return DeploymentResponseGenerator(
                gen, r, rkey, retry=retry_on_dead_replica,
                tracer=tracer, trace=trace)
        if trace is not None or self._model_id is not None:
            ref = replica.handle_request_ctx.remote(
                ctx, method, *args, **kwargs)
        else:
            ref = replica.handle_request.remote(method, *args, **kwargs)
        r.outstanding.setdefault(rkey, []).append(ref)

        def retry_on_dead_replica():
            # Membership was stale: resync and re-route once.
            r.refresh(force=True)
            return self._route(method, args, kwargs)

        return DeploymentResponse(ref, retry=retry_on_dead_replica,
                                  tracer=tracer, trace=trace)

    def remote(self, *args, **kwargs):
        return self._route("__call__", args, kwargs)

    def __getattr__(self, name: str) -> _MethodCaller:
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def options(self, *, stream: bool = False,
                multiplexed_model_id: Optional[str] = None,
                session_id: Optional[str] = None,
                routing_policy: Optional[str] = None,
                prefix_fingerprint: Optional[int] = None,
                tenant: Optional[str] = None,
                priority=None,
                request_id: Optional[str] = None,
                **kwargs) -> "DeploymentHandle":
        """Configured copy of this handle (reference: handle.options).
        ``session_id`` pins every call to one replica while it lives
        (multi-turn prefix-cache affinity); ``routing_policy`` selects
        "gauge" (default) / "pow2" / "round_robin";
        ``prefix_fingerprint`` (``serve.prefix_fingerprint(tokens,
        kv_block_size)``) steers a first-turn request to the replica
        whose radix cache already holds that prefix; ``tenant`` /
        ``priority`` ("low"/"normal"/"high" or int) tag calls for
        SLO-aware admission when :meth:`enable_admission` is on;
        ``request_id`` pins the next call's trace identity (the HTTP
        proxy forwards the client's ``x-request-id`` through here — an
        unset id is minted fresh per call).
        Unknown options raise rather than silently no-op."""
        if kwargs:
            raise TypeError(
                f"unsupported handle options: {sorted(kwargs)}")
        if routing_policy not in (None, "gauge", "pow2", "round_robin"):
            raise ValueError(
                f"unknown routing_policy {routing_policy!r}")
        if priority is not None:
            from ray_tpu.serve.admission import priority_value
            priority_value(priority)   # raises ValueError on unknown
        return DeploymentHandle(
            self.deployment_name, self._controller, self.app_name,
            _router=self._router, _stream=stream,
            _model_id=multiplexed_model_id, _session_id=session_id,
            _routing_policy=routing_policy,
            _prefix_fingerprint=prefix_fingerprint,
            _tenant=tenant, _priority=priority,
            _request_id=request_id)

    def __reduce__(self):
        # options survive pickling; router state is rebuilt on the far
        # side (membership is fetched fresh there anyway)
        return (_rebuild_handle,
                (self.deployment_name, self._controller, self.app_name,
                 self._stream, self._model_id, self._session_id,
                 self._routing_policy, self._prefix_fingerprint,
                 self._tenant, self._priority, self._request_id))


def _rebuild_handle(deployment_name, controller, app_name, stream,
                    model_id, session_id=None, routing_policy=None,
                    prefix_fingerprint=None, tenant=None,
                    priority=None, request_id=None):
    return DeploymentHandle(deployment_name, controller, app_name,
                            _stream=stream, _model_id=model_id,
                            _session_id=session_id,
                            _routing_policy=routing_policy,
                            _prefix_fingerprint=prefix_fingerprint,
                            _tenant=tenant, _priority=priority,
                            _request_id=request_id)
