"""DeploymentHandle: client-side router with power-of-two-choices.

Reference: ``python/ray/serve/handle.py`` + ``_private/router.py:259``
and ``replica_scheduler/pow_2_scheduler.py:44`` — pick two candidate
replicas, route to the less loaded. Load here is the router's own
outstanding-refs count per replica (completed refs are drained with a
zero-timeout wait) plus live streams, refreshed replica membership comes
from the controller when its version bumps (simplified LongPollHost).
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

import ray_tpu


class DeploymentResponse:
    """Future-like result of ``handle.remote()`` (reference
    ``handle.py:DeploymentResponse``). Submission to a dead replica
    only surfaces at get-time in this runtime, so the dead-replica
    retry lives HERE: on actor death, the originating handle refreshes
    membership and re-routes once."""

    def __init__(self, ref, retry=None):
        self._ref = ref
        self._retry = retry  # () -> DeploymentResponse, single-shot

    def result(self, timeout_s: Optional[float] = None):
        try:
            return ray_tpu.get(self._ref, timeout=timeout_s)
        except Exception as e:
            if self._retry is not None and _is_actor_death(e):
                retry, self._retry = self._retry, None
                return retry().result(timeout_s=timeout_s)
            raise

    def _to_object_ref(self):
        return self._ref


def _is_actor_death(e: BaseException) -> bool:
    from ray_tpu.exceptions import ActorDiedError, ActorError
    return isinstance(e, (ActorDiedError, ActorError))


class DeploymentResponseGenerator:
    """Streaming response of ``options(stream=True)`` (reference:
    ``handle.py:DeploymentResponseGenerator``): a thin value-yielding
    view over a core :class:`~ray_tpu.ObjectRefGenerator` — the replica
    executes the method as a streaming generator task, each item is its
    own object reported as produced, and the core credit window paces
    the producer. Iterating yields materialized values; ``cancel()``
    (or GC of an abandoned generator) cancels the replica-side task and
    frees unconsumed items. A replica death before the first item
    re-routes once, like unary ``DeploymentResponse``."""

    def __init__(self, gen, router=None, rkey=None, retry=None):
        self._gen = gen          # core ObjectRefGenerator
        self._router = router
        self._rkey = rkey
        self._retry = retry      # () -> DeploymentResponseGenerator
        self._started = False
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        try:
            ref = next(self._gen)
        except StopIteration:
            self._finish()
            raise
        except Exception as e:
            if not self._started and self._retry is not None \
                    and _is_actor_death(e):
                # membership was stale and the replica is gone: resync
                # and re-route this stream once
                retry, self._retry = self._retry, None
                self._finish()
                fresh = retry()
                self._gen = fresh._gen
                self._router = fresh._router
                self._rkey = fresh._rkey
                self._done = False
                return next(self)
            self._finish()
            raise
        self._started = True
        try:
            return ray_tpu.get(ref)
        except BaseException:
            # a mid-stream exception is delivered as the failing item:
            # the stream is over — release the router's stream count
            self._finish()
            raise

    def _finish(self) -> None:
        if not self._done:
            self._done = True
            if self._router is not None:
                self._router.stream_finished(self._rkey)

    def cancel(self) -> None:
        if not self._done:
            self._finish()
            self._gen.close()

    def __del__(self):
        try:
            self.cancel()
        except Exception:
            pass


class _Router:
    """Shared routing state: membership, per-replica load, model
    affinity. One _Router is shared by a handle and every configured
    copy made via ``options()``, so load tracking spans them all."""

    def __init__(self, deployment_name: str, controller):
        self.deployment_name = deployment_name
        self.controller = controller
        self.version = -1
        self.replicas: List[Any] = []
        # stable replica key (actor id hex) -> outstanding unary refs
        self.outstanding: Dict[bytes, List[Any]] = {}
        # stable replica key -> live stream count
        self.streams: Dict[bytes, int] = {}
        # model id -> stable replica key (soft affinity, reference:
        # multiplexed model routing in replica_scheduler)
        self.model_affinity: Dict[str, bytes] = {}

    @staticmethod
    def _key(replica) -> bytes:
        aid = getattr(replica, "_actor_id", None)
        return aid.binary() if aid is not None else id(replica)

    def refresh(self, force: bool = False) -> None:
        version = ray_tpu.get(
            self.controller.get_version.remote(self.deployment_name))
        if version != self.version or force:
            # Atomic snapshot: version and replica list must agree.
            version, replicas = ray_tpu.get(
                self.controller.get_membership.remote(self.deployment_name))
            self.replicas = replicas
            self.version = version
            live = {self._key(r) for r in replicas}
            # stable keys survive a membership change for replicas that
            # remain; state for removed replicas is dropped, and affinity
            # to a vanished replica is invalidated rather than silently
            # pointing at a different one
            self.outstanding = {k: v for k, v in self.outstanding.items()
                                if k in live}
            self.streams = {k: v for k, v in self.streams.items()
                            if k in live}
            self.model_affinity = {m: k for m, k in
                                   self.model_affinity.items() if k in live}

    def load(self, replica) -> int:
        k = self._key(replica)
        refs = self.outstanding.setdefault(k, [])
        if refs:
            ready, pending = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=0)
            self.outstanding[k] = list(pending)
        return len(self.outstanding[k]) + self.streams.get(k, 0)

    def pick(self, model_id: Optional[str]):
        """Returns (replica, stable_key)."""
        n = len(self.replicas)
        by_key = {self._key(r): r for r in self.replicas}
        if model_id is not None:
            k = self.model_affinity.get(model_id)
            if k is not None and k in by_key:
                # soft affinity: keep one model's requests on one replica
                # so its weights stay resident
                return by_key[k], k
        if n == 1:
            replica = self.replicas[0]
        else:
            i, j = random.sample(range(n), 2)
            a, b = self.replicas[i], self.replicas[j]
            replica = a if self.load(a) <= self.load(b) else b
        k = self._key(replica)
        if model_id is not None:
            self.model_affinity[model_id] = k
        return replica, k

    def stream_started(self, k: bytes) -> None:
        self.streams[k] = self.streams.get(k, 0) + 1

    def stream_finished(self, k: bytes) -> None:
        n = self.streams.get(k, 0) - 1
        if n > 0:
            self.streams[k] = n
        else:
            self.streams.pop(k, None)


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._route(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 app_name: str = "default", _router: Optional[_Router] = None,
                 _stream: bool = False, _model_id: Optional[str] = None):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._controller = controller
        self._router = _router or _Router(deployment_name, controller)
        self._stream = _stream
        self._model_id = _model_id

    # -- routing ------------------------------------------------------
    def _route(self, method: str, args, kwargs):
        r = self._router
        r.refresh()
        if not r.replicas:
            raise RuntimeError(
                f"Deployment {self.deployment_name!r} has no replicas")
        # Unwrap chained responses so downstream gets values, not
        # wrapper objects (reference: DeploymentResponse passing).
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: (v._to_object_ref()
                      if isinstance(v, DeploymentResponse) else v)
                  for k, v in kwargs.items()}
        replica, rkey = r.pick(self._model_id)
        if self._stream:
            # core streaming generator task: the replica method's items
            # arrive as first-class objects with backpressure and the
            # runtime's delivery/fault guarantees — no replica-held
            # generator state, no chunk polling
            ctx = {"multiplexed_model_id": self._model_id or ""}
            gen = replica.handle_request_stream.options(
                num_returns="streaming").remote(
                    ctx, method, *args, **kwargs)
            r.stream_started(rkey)

            def retry_on_dead_replica():
                r.refresh(force=True)
                return self._route(method, args, kwargs)

            return DeploymentResponseGenerator(
                gen, r, rkey, retry=retry_on_dead_replica)
        if self._model_id is not None:
            ctx = {"multiplexed_model_id": self._model_id}
            ref = replica.handle_request_ctx.remote(
                ctx, method, *args, **kwargs)
        else:
            ref = replica.handle_request.remote(method, *args, **kwargs)
        r.outstanding.setdefault(rkey, []).append(ref)

        def retry_on_dead_replica():
            # Membership was stale: resync and re-route once.
            r.refresh(force=True)
            return self._route(method, args, kwargs)

        return DeploymentResponse(ref, retry=retry_on_dead_replica)

    def remote(self, *args, **kwargs):
        return self._route("__call__", args, kwargs)

    def __getattr__(self, name: str) -> _MethodCaller:
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def options(self, *, stream: bool = False,
                multiplexed_model_id: Optional[str] = None,
                **kwargs) -> "DeploymentHandle":
        """Configured copy of this handle (reference: handle.options).
        Unknown options raise rather than silently no-op."""
        if kwargs:
            raise TypeError(
                f"unsupported handle options: {sorted(kwargs)}")
        return DeploymentHandle(
            self.deployment_name, self._controller, self.app_name,
            _router=self._router, _stream=stream,
            _model_id=multiplexed_model_id)

    def __reduce__(self):
        # options survive pickling; router state is rebuilt on the far
        # side (membership is fetched fresh there anyway)
        return (_rebuild_handle,
                (self.deployment_name, self._controller, self.app_name,
                 self._stream, self._model_id))


def _rebuild_handle(deployment_name, controller, app_name, stream, model_id):
    return DeploymentHandle(deployment_name, controller, app_name,
                            _stream=stream, _model_id=model_id)
