"""DeploymentHandle: client-side router with power-of-two-choices.

Reference: ``python/ray/serve/handle.py`` + ``_private/router.py:259``
and ``replica_scheduler/pow_2_scheduler.py:44`` — pick two candidate
replicas, route to the less loaded. Load here is the handle's own
outstanding-refs count per replica (completed refs are drained with a
zero-timeout wait), refreshed replica membership comes from the
controller when its version bumps (simplified LongPollHost).
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

import ray_tpu


class DeploymentResponse:
    """Future-like result of ``handle.remote()`` (reference
    ``handle.py:DeploymentResponse``). Submission to a dead replica
    only surfaces at get-time in this runtime, so the dead-replica
    retry lives HERE: on actor death, the originating handle refreshes
    membership and re-routes once."""

    def __init__(self, ref, retry=None):
        self._ref = ref
        self._retry = retry  # () -> DeploymentResponse, single-shot

    def result(self, timeout_s: Optional[float] = None):
        try:
            return ray_tpu.get(self._ref, timeout=timeout_s)
        except Exception as e:
            if self._retry is not None and _is_actor_death(e):
                retry, self._retry = self._retry, None
                return retry().result(timeout_s=timeout_s)
            raise

    def _to_object_ref(self):
        return self._ref


def _is_actor_death(e: BaseException) -> bool:
    from ray_tpu.exceptions import ActorDiedError, ActorError
    return isinstance(e, (ActorDiedError, ActorError))


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._route(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 app_name: str = "default"):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._controller = controller
        self._version = -1
        self._replicas: List[Any] = []
        # replica index -> outstanding refs (drained lazily)
        self._outstanding: Dict[int, List[Any]] = {}

    # -- membership ---------------------------------------------------
    def _refresh(self, force: bool = False) -> None:
        version = ray_tpu.get(
            self._controller.get_version.remote(self.deployment_name))
        if version != self._version or force:
            # Atomic snapshot: version and replica list must agree.
            version, replicas = ray_tpu.get(
                self._controller.get_membership.remote(
                    self.deployment_name))
            self._replicas = replicas
            self._version = version
            self._outstanding = {i: [] for i in range(len(self._replicas))}

    def _load(self, i: int) -> int:
        refs = self._outstanding.setdefault(i, [])
        if refs:
            ready, pending = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=0)
            self._outstanding[i] = list(pending)
        return len(self._outstanding[i])

    # -- routing ------------------------------------------------------
    def _route(self, method: str, args, kwargs) -> DeploymentResponse:
        self._refresh()
        if not self._replicas:
            raise RuntimeError(
                f"Deployment {self.deployment_name!r} has no replicas")
        # Unwrap chained responses so downstream gets values, not
        # wrapper objects (reference: DeploymentResponse passing).
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: (v._to_object_ref()
                      if isinstance(v, DeploymentResponse) else v)
                  for k, v in kwargs.items()}
        n = len(self._replicas)
        if n == 1:
            idx = 0
        else:
            i, j = random.sample(range(n), 2)
            idx = i if self._load(i) <= self._load(j) else j
        replica = self._replicas[idx]
        ref = replica.handle_request.remote(method, *args, **kwargs)
        self._outstanding.setdefault(idx, []).append(ref)

        def retry_on_dead_replica():
            # Membership was stale: resync and re-route once.
            self._refresh(force=True)
            return self._route(method, args, kwargs)

        return DeploymentResponse(ref, retry=retry_on_dead_replica)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._route("__call__", args, kwargs)

    def __getattr__(self, name: str) -> _MethodCaller:
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def options(self, **kwargs) -> "DeploymentHandle":
        return self  # stream/multiplex options accepted for API parity

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self._controller, self.app_name))
