"""SLO-aware multi-tenant admission at the serve router.

The serving side of graceful degradation under train+serve colocation
(the cluster side is ``autoscaler/arbiter.py``): instead of letting an
overload wedge every replica's queue — TTFT for EVERY tenant then
collapses together — the router sheds over-budget and low-priority
traffic with a typed :class:`~ray_tpu.exceptions.
AdmissionRejectedError` BEFORE the request reaches a replica, while
high-priority traffic keeps its TTFT bounded.

Two independent shed rules, checked in order:

1. **Per-tenant token budgets** (``tenant_budgets``: tenant →
   tokens/s, measured over a sliding ``budget_window_s`` window of
   ADMITTED token estimates). A tenant over its budget sheds with
   reason ``"over-budget"`` — unless the request's priority class is
   at/above ``budget_exempt_priority`` (default ``"high"``: paid SLO
   traffic bursts past its budget, the budget protects the fleet from
   the long tail).
2. **Priority shedding under overload.** When the fleet's engine
   gauges show saturation — the LEAST-loaded replica's queue depth is
   at/above ``queue_shed_depth`` or its TTFT EWMA at/above
   ``ttft_shed_s`` (if even the best replica is backed up, routing
   cannot help) — requests whose priority class is below
   ``shed_below_priority`` shed with reason ``"overload"``.

Priority classes are ``"low"`` < ``"normal"`` < ``"high"`` (ints
accepted too). Every shed increments
``serve_admission_rejected_total{tenant,priority}`` and records an
``ARBITER_REJECT`` flight event; admitted requests charge their token
estimate (``max_tokens`` of the call, else ``default_request_tokens``)
to the tenant's window.

Wired through ``handle.options(tenant=..., priority=...)`` and the
HTTP proxy's ``x-tenant`` / ``x-priority`` headers (shed → 429).
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import time
from typing import Any, Dict, Optional, Union

from ray_tpu.exceptions import AdmissionRejectedError

logger = logging.getLogger(__name__)

#: priority classes, lowest first; ints pass through unchanged
PRIORITY_CLASSES = {"low": 0, "normal": 1, "high": 2}


def priority_value(priority: Union[str, int, None]) -> int:
    if priority is None:
        return PRIORITY_CLASSES["normal"]
    if isinstance(priority, bool) or not isinstance(priority,
                                                    (str, int)):
        raise ValueError(f"priority must be a class name or int, "
                         f"got {priority!r}")
    if isinstance(priority, int):
        return priority
    try:
        return PRIORITY_CLASSES[priority]
    except KeyError:
        raise ValueError(
            f"unknown priority class {priority!r} "
            f"(one of {sorted(PRIORITY_CLASSES)})") from None


def priority_name(priority: Union[str, int, None]) -> str:
    v = priority_value(priority)
    for name, val in PRIORITY_CLASSES.items():
        if val == v:
            return name
    return str(v)


@dataclasses.dataclass
class AdmissionPolicy:
    """Shed rules. ``None`` budgets = unlimited."""

    #: tenant -> admitted tokens/s over the sliding window
    tenant_budgets: Optional[Dict[str, float]] = None
    #: sliding window the budget rate is measured over
    budget_window_s: float = 10.0
    #: priority classes at/above this never budget-shed
    budget_exempt_priority: Union[str, int] = "high"
    #: best-replica queue depth at/above which overload shedding starts
    queue_shed_depth: float = 8.0
    #: best-replica TTFT EWMA (s) at/above which overload shedding
    #: starts
    ttft_shed_s: float = 4.0
    #: priority classes BELOW this shed under overload
    shed_below_priority: Union[str, int] = "normal"
    #: token estimate for requests that don't carry ``max_tokens``
    default_request_tokens: int = 32

    def to_dict(self) -> Dict[str, Any]:
        """JSON-safe round-trip form (the dashboard config endpoint's
        wire format)."""
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AdmissionPolicy":
        """Build + validate a policy from a config payload (the
        ``POST /api/v0/admission/policy`` body). Unknown keys are a
        hard error — a typo'd knob must not silently admit
        everything."""
        if not isinstance(d, dict):
            raise ValueError(
                f"admission policy must be an object, got {type(d)}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown admission policy keys {sorted(unknown)} "
                f"(known: {sorted(known)})")
        p = cls(**d)
        if p.budget_window_s <= 0:
            raise ValueError("budget_window_s must be > 0")
        if p.default_request_tokens <= 0:
            raise ValueError("default_request_tokens must be > 0")
        if p.tenant_budgets is not None:
            if not isinstance(p.tenant_budgets, dict):
                raise ValueError("tenant_budgets must be a mapping of "
                                 "tenant -> tokens/s")
            for t, b in p.tenant_budgets.items():
                if not isinstance(b, (int, float)) or \
                        isinstance(b, bool) or b < 0:
                    raise ValueError(
                        f"budget for tenant {t!r} must be a "
                        f"non-negative number, got {b!r}")
        # both priority knobs must resolve now, not at admit time
        priority_value(p.budget_exempt_priority)
        priority_value(p.shed_below_priority)
        return p


class AdmissionController:
    """One per router (shared across ``options()`` copies, like the
    router itself, so budget accounting spans them)."""

    def __init__(self, policy: Optional[AdmissionPolicy] = None,
                 recorder=None,
                 now_fn=time.monotonic):
        self.policy = policy or AdmissionPolicy()
        self._recorder = recorder
        self._now = now_fn
        # tenant -> deque[(ts, tokens)] of admitted estimates
        self._spend: Dict[str, collections.deque] = {}
        self.admitted = 0
        self.rejected = 0
        #: seq of the last policy applied via the config plane
        self.policy_seq = 0

    def set_policy(self, policy: AdmissionPolicy,
                   seq: Optional[int] = None) -> None:
        """Swap the shed rules in place, keeping the per-tenant spend
        windows — a budget refresh must not amnesty tenants that are
        already over their (new) budget."""
        self.policy = policy
        if seq is not None:
            self.policy_seq = seq

    # ------------------------------------------------------- budgets
    def _rate(self, tenant: str, now: float) -> float:
        window = self.policy.budget_window_s
        q = self._spend.get(tenant)
        if not q:
            return 0.0
        while q and now - q[0][0] > window:
            q.popleft()
        return sum(t for _, t in q) / window if q else 0.0

    def _charge(self, tenant: str, tokens: float, now: float) -> None:
        self._spend.setdefault(
            tenant, collections.deque()).append((now, tokens))

    # ------------------------------------------------------ overload
    @staticmethod
    def _best_replica_load(gauges: Dict[Any, Dict[str, Any]]):
        """(min queue depth, min TTFT EWMA) across fresh replica
        gauges — the least-loaded replica decides overload: if even it
        is backed up, no routing choice can absorb the request."""
        depths = [g.get("queue_depth") for g in gauges.values()
                  if g.get("queue_depth") is not None]
        ttfts = [g.get("ttft_ewma_s") for g in gauges.values()
                 if g.get("ttft_ewma_s") is not None]
        return (min(depths) if depths else 0.0,
                min(ttfts) if ttfts else 0.0)

    def overloaded(self, gauges: Dict[Any, Dict[str, Any]]) -> bool:
        q, ttft = self._best_replica_load(gauges)
        return q >= self.policy.queue_shed_depth or \
            ttft >= self.policy.ttft_shed_s

    # --------------------------------------------------------- admit
    def admit(self, tenant: Optional[str],
              priority: Union[str, int, None],
              gauges: Dict[Any, Dict[str, Any]],
              tokens: Optional[float] = None,
              request_id: Optional[str] = None) -> None:
        """Admit (charging the tenant's budget window) or raise
        :class:`AdmissionRejectedError`. ``request_id`` is the trace
        identity minted by the router; sheds stamp it into the error,
        the ARBITER_REJECT event, and (at the caller) the SHED span so
        a 429 body can be joined against its waterfall."""
        tenant = tenant or "default"
        prio = priority_value(priority)
        pname = priority_name(priority)
        tokens = float(tokens if tokens is not None
                       else self.policy.default_request_tokens)
        now = self._now()
        budgets = self.policy.tenant_budgets or {}
        budget = budgets.get(tenant)
        if budget is not None and \
                prio < priority_value(self.policy.
                                      budget_exempt_priority):
            rate = self._rate(tenant, now)
            if rate + tokens / self.policy.budget_window_s > budget:
                self._reject(tenant, pname, "over-budget",
                             f"{rate:.1f} tok/s against a "
                             f"{budget:.1f} tok/s budget",
                             request_id=request_id)
        if prio < priority_value(self.policy.shed_below_priority) \
                and self.overloaded(gauges):
            q, ttft = self._best_replica_load(gauges)
            self._reject(tenant, pname, "overload",
                         f"best replica queue {q:.0f}, "
                         f"ttft {ttft:.2f}s",
                         request_id=request_id)
        self._charge(tenant, tokens, now)
        self.admitted += 1

    def _reject(self, tenant: str, priority: str, reason: str,
                detail: str, request_id: Optional[str] = None) -> None:
        self.rejected += 1
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            runtime_metrics().admission_rejected.inc(
                tags={"tenant": tenant, "priority": priority})
        except Exception:
            pass
        r = self._recorder
        if r is None:
            try:
                from ray_tpu.core.global_state import try_global_worker
                r = getattr(try_global_worker(), "recorder", None)
            except Exception:
                r = None
        if r is not None:
            try:
                r.record("ARBITER_REJECT", tenant=tenant,
                         priority=priority, reason=reason,
                         request_id=request_id or "")
            except Exception:
                pass
        raise AdmissionRejectedError(tenant=tenant, priority=priority,
                                     reason=reason, detail=detail,
                                     request_id=request_id or "")

    def stats(self) -> Dict[str, Any]:
        now = self._now()
        return {
            "admitted": self.admitted,
            "rejected": self.rejected,
            "tenant_rates": {t: round(self._rate(t, now), 2)
                             for t in list(self._spend)},
        }
