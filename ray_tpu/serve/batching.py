"""@serve.batch: dynamic request batching.

Reference: ``python/ray/serve/batching.py`` — calls to the decorated
async method are queued; a background flusher invokes the underlying
function with a LIST of requests once ``max_batch_size`` accumulate or
``batch_wait_timeout_s`` elapses, then fans results back out. On TPU
replicas this is what keeps the MXU fed: one padded jitted call per
batch instead of per request.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = timeout_s
        self._queue: Optional[asyncio.Queue] = None
        self._task = None

    def _ensure(self):
        if self._queue is None:
            self._queue = asyncio.Queue()
            self._task = asyncio.get_event_loop().create_task(
                self._flusher())

    async def submit(self, instance, item):
        self._ensure()
        fut = asyncio.get_event_loop().create_future()
        await self._queue.put((instance, item, fut))
        return await fut

    async def _flusher(self):
        while True:
            instance, item, fut = await self._queue.get()
            batch = [(instance, item, fut)]
            deadline = asyncio.get_event_loop().time() + self._timeout
            while len(batch) < self._max:
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(
                        self._queue.get(), timeout=remaining))
                except asyncio.TimeoutError:
                    break
            items = [b[1] for b in batch]
            futs = [b[2] for b in batch]
            try:
                out = self._fn(batch[0][0], items)
                if asyncio.iscoroutine(out):
                    out = await out
                if len(out) != len(items):
                    raise ValueError(
                        f"@serve.batch function returned {len(out)} "
                        f"results for {len(items)} requests")
                for f, r in zip(futs, out):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:
                for f in futs:
                    if not f.done():
                        f.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorate an async method taking a LIST of requests."""
    def wrap(fn):
        queue = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)

        @functools.wraps(fn)
        async def wrapper(self, item):
            return await queue.submit(self, item)

        wrapper._batch_queue = queue
        return wrapper
    if _fn is not None:
        return wrap(_fn)
    return wrap
