"""@serve.batch: dynamic request batching.

Reference: ``python/ray/serve/batching.py`` — calls to the decorated
async method are queued; a background flusher invokes the underlying
function with a LIST of requests once ``max_batch_size`` accumulate or
``batch_wait_timeout_s`` elapses, then fans results back out. On TPU
replicas this is what keeps the MXU fed: one padded jitted call per
batch instead of per request.

Queues are **per (instance, running event loop)**: the decorator used to
keep ONE queue in its closure, so every replica of a deployment class
shared it — a mixed batch then executed against ``batch[0][0]`` (the
first submitter's ``self``) only, silently feeding other instances'
requests through one instance's weights. Keying by instance fixes that,
and keying by the running loop re-creates the flusher task when a later
caller lives on a different event loop (the old ``_ensure`` pinned the
first caller's loop forever, wedging replicas created on a new loop —
e.g. a restarted async actor).
"""

from __future__ import annotations

import asyncio
import functools
import weakref
from typing import Any, Callable, List, Optional, Tuple


class _BatchQueue:
    """One queue + flusher bound to one (instance, event loop)."""

    def __init__(self, fn, instance, max_batch_size: int,
                 timeout_s: float):
        self._fn = fn
        self._instance = instance
        self._max = max_batch_size
        self._timeout = timeout_s
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task = asyncio.get_event_loop().create_task(self._flusher())

    async def submit(self, item):
        fut = asyncio.get_event_loop().create_future()
        await self._queue.put((item, fut))
        return await fut

    async def _flusher(self):
        while True:
            item, fut = await self._queue.get()
            batch = [(item, fut)]
            deadline = asyncio.get_event_loop().time() + self._timeout
            while len(batch) < self._max:
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(
                        self._queue.get(), timeout=remaining))
                except asyncio.TimeoutError:
                    break
            items = [b[0] for b in batch]
            futs = [b[1] for b in batch]
            try:
                out = self._fn(self._instance, items)
                if asyncio.iscoroutine(out):
                    out = await out
                if len(out) != len(items):
                    raise ValueError(
                        f"@serve.batch function returned {len(out)} "
                        f"results for {len(items)} requests")
                for f, r in zip(futs, out):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:
                for f in futs:
                    if not f.done():
                        f.set_exception(e)


class _QueueRegistry:
    """Queues keyed per (instance, running loop). Instances are held
    weakly so a torn-down replica's queue can be collected; a dead or
    changed loop gets a fresh queue + flusher (the old flusher task
    died with its loop)."""

    def __init__(self, fn, max_batch_size: int, timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = timeout_s
        self._queues: "weakref.WeakKeyDictionary[Any, Tuple]" = \
            weakref.WeakKeyDictionary()

    def __getstate__(self):
        # Deployment classes are cloudpickled to replica actors with
        # this registry hanging off the decorated method. Queues and
        # flusher tasks are process-local (bound to instances and event
        # loops that don't travel) — ship only the config.
        return {"_fn": self._fn, "_max": self._max,
                "_timeout": self._timeout}

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._queues = weakref.WeakKeyDictionary()

    def queue_for(self, instance) -> _BatchQueue:
        loop = asyncio.get_event_loop()
        try:
            entry = self._queues.get(instance)
        except TypeError:   # unhashable/non-weakrefable instance
            entry = getattr(instance, "__serve_batch_queue__", None)
        if entry is not None:
            q_loop, q = entry
            if q_loop is loop and not loop.is_closed():
                return q
        q = _BatchQueue(self._fn, instance, self._max, self._timeout)
        try:
            self._queues[instance] = (loop, q)
        except TypeError:
            setattr(instance, "__serve_batch_queue__", (loop, q))
        return q


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorate an async method taking a LIST of requests."""
    def wrap(fn):
        registry = _QueueRegistry(fn, max_batch_size,
                                  batch_wait_timeout_s)

        @functools.wraps(fn)
        async def wrapper(self, item):
            return await registry.queue_for(self).submit(item)

        wrapper._batch_registry = registry
        return wrapper
    if _fn is not None:
        return wrap(_fn)
    return wrap
