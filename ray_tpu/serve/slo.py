"""slo.py — per-phase SLO budgets and the request-trace watchdog.

Tail-based sampling (serve/request_trace.py) only ships 1-in-N fast
requests; this watchdog is what makes the *interesting* tail ship too.
Each serve replica evaluates three per-phase budgets as a request moves
through its pipeline:

- ``queue_s``            router enqueue -> engine admission wait
- ``ttft_s``             router enqueue -> first token (the user-facing
                         TTFT, queue wait included — satellite 2)
- ``inter_token_p99_s``  p99 of the request's inter-token gaps

The moment a budget trips, the request's trace flips to always-ship
(``trace.ship = True``) and ``serve_slo_violations_total{phase}`` is
incremented — so a p99-slow request is auto-captured at the controller
even when the 1-in-N sample missed it, with zero standing cost for
requests that stay inside budget.

Budgets come from config knobs ``slo_queue_s`` / ``slo_ttft_s`` /
``slo_inter_token_p99_s`` (env ``RAY_TPU_SLO_*``); a budget <= 0 is
disabled.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

from ray_tpu.serve.request_trace import (MAX_GAPS_PER_REQUEST,
                                         RequestTrace)

#: SLO phase labels (the metric's ``phase`` tag and the key under the
#: trace's ``slo`` dict — deliberately lowercase to read as budget
#: names, not span phases).
QUEUE = "queue"
TTFT = "ttft"
INTER_TOKEN_P99 = "inter_token_p99"


@dataclass(frozen=True)
class SLOBudget:
    """Per-phase latency budgets (seconds); <= 0 disables a budget."""
    queue_s: float = 1.0
    ttft_s: float = 5.0
    inter_token_p99_s: float = 1.0

    @classmethod
    def from_config(cls, config=None) -> "SLOBudget":
        if config is None:
            return cls()
        return cls(
            queue_s=float(getattr(config, "slo_queue_s", 1.0)),
            ttft_s=float(getattr(config, "slo_ttft_s", 5.0)),
            inter_token_p99_s=float(
                getattr(config, "slo_inter_token_p99_s", 1.0)))


def p99(values) -> float:
    """Nearest-rank p99 (== max for fewer than 100 samples, which is
    the right bias for short generations: one bad stall should trip)."""
    vs = sorted(values)
    if not vs:
        return 0.0
    return vs[max(0, math.ceil(0.99 * len(vs)) - 1)]


class SLOWatchdog:
    """Evaluates SLOBudget against one replica's requests. Stateless
    across requests (all state lives on the RequestTrace); one instance
    per engine."""

    def __init__(self, budget: Optional[SLOBudget] = None):
        self.budget = budget or SLOBudget()
        self._metrics = None
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            self._metrics = runtime_metrics()
        except Exception:
            pass

    # ------------------------------------------------------ budget obs
    def observe_queue(self, trace: Optional[RequestTrace],
                      wait_s: float) -> bool:
        b = self.budget.queue_s
        if trace is None or b <= 0 or wait_s <= b:
            return False
        return self._trip(trace, QUEUE, wait_s, b)

    def observe_ttft(self, trace: Optional[RequestTrace],
                     ttft_s: float) -> bool:
        b = self.budget.ttft_s
        if trace is None or b <= 0 or ttft_s <= b:
            return False
        return self._trip(trace, TTFT, ttft_s, b)

    def observe_gap(self, trace: Optional[RequestTrace],
                    gap_s: float) -> bool:
        """Feed one inter-token gap; trips when the request's running
        p99 exceeds budget."""
        b = self.budget.inter_token_p99_s
        if trace is None or b <= 0:
            return False
        if len(trace.gaps) < MAX_GAPS_PER_REQUEST:
            trace.gaps.append(gap_s)
        if gap_s <= b:          # a p99 can only newly trip on a new max
            return False
        q = p99(trace.gaps)
        if q <= b:
            return False
        return self._trip(trace, INTER_TOKEN_P99, q, b)

    # ------------------------------------------------------------ trip
    def _trip(self, trace: RequestTrace, phase: str, value: float,
              budget: float) -> bool:
        first = phase not in trace.slo
        trace.slo[phase] = {"value": value, "budget": budget}
        trace.ship = True
        if first and self._metrics is not None:
            try:
                self._metrics.serve_slo_violations.inc(
                    tags={"phase": phase})
            except Exception:
                pass
        return True
