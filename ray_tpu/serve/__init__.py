"""ray_tpu.serve: model serving (reference: ``python/ray/serve/``).

Public surface mirrors ``ray.serve``: ``@serve.deployment``,
``serve.run``, DeploymentHandle composition, ``@serve.batch`` dynamic
batching, queue-depth autoscaling, and a JSON-over-HTTP proxy.
"""

from ray_tpu.serve.api import (
    delete,
    get_app_handle,
    get_deployment_handle,
    grpc_proxy_address,
    proxy_address,
    proxy_addresses,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.admission import (
    AdmissionController, AdmissionPolicy, PRIORITY_CLASSES)
from ray_tpu.serve.http import Request, Response, ingress
from ray_tpu.serve.batching import batch
from ray_tpu.serve.deployment import (
    Application, AutoscalingConfig, Deployment, deployment)
from ray_tpu.serve.disagg import (
    DisaggHandoffError, DisaggRouter, deploy_disaggregated,
    kv_ship_bytes, migrate_warm_prefixes, pack_kv_blocks,
    unpack_kv_blocks)
from ray_tpu.serve.handle import (
    DeploymentHandle, DeploymentResponse, DeploymentResponseGenerator)
from ray_tpu.serve._private.replica import get_multiplexed_model_id
from ray_tpu.serve.llm_engine import (
    EngineConfig, EngineDeadError, LLMEngine, LLMServer,
    RequestTooLargeError)
from ray_tpu.serve.prefix_cache import PrefixBlockPool, prefix_fingerprint

__all__ = [
    "AdmissionController",
    "AdmissionPolicy",
    "Application",
    "AutoscalingConfig",
    "PRIORITY_CLASSES",
    "Deployment",
    "DeploymentHandle",
    "DisaggHandoffError",
    "DisaggRouter",
    "deploy_disaggregated",
    "kv_ship_bytes",
    "migrate_warm_prefixes",
    "pack_kv_blocks",
    "unpack_kv_blocks",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "EngineConfig",
    "EngineDeadError",
    "LLMEngine",
    "LLMServer",
    "PrefixBlockPool",
    "RequestTooLargeError",
    "prefix_fingerprint",
    "batch",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "grpc_proxy_address",
    "ingress",
    "proxy_address",
    "proxy_addresses",
    "Request",
    "Response",
    "run",
    "shutdown",
    "start",
    "status",
]
