"""HTTP request/response model + the ASGI ingress adapter.

Reference: ``python/ray/serve/api.py`` (``@serve.ingress`` wraps a
FastAPI/ASGI app into a deployment class) and
``_private/http_util.py`` (``ASGIReceiveProxy`` / response streaming).
TPU-native shape: the proxy ships a picklable request snapshot to the
replica; the replica runs the ASGI app and streams its send() events
back through the ordinary deployment streaming channel (a core
streaming generator task — ``Replica.handle_request_stream`` with
``num_returns="streaming"``), so FastAPI ``StreamingResponse`` bodies
flow to the HTTP client chunk by chunk without the proxy ever
importing the user's app.
"""

from __future__ import annotations

import json as _json
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qsl

__all__ = ["Request", "Response", "ingress"]


class Request:
    """Picklable HTTP request snapshot handed to deployments.

    Plain deployments may accept it (reference: Starlette Request);
    the ASGI adapter reconstitutes a full scope from it."""

    __slots__ = ("method", "path", "query_string", "headers", "body")

    def __init__(self, method: str, path: str, query_string: str = "",
                 headers: Optional[List[Tuple[str, str]]] = None,
                 body: bytes = b""):
        self.method = method
        self.path = path
        self.query_string = query_string
        self.headers = headers or []
        self.body = body

    def header(self, name: str, default: str = "") -> str:
        name = name.lower()
        for k, v in self.headers:
            if k.lower() == name:
                return v
        return default

    @property
    def query_params(self) -> Dict[str, str]:
        return dict(parse_qsl(self.query_string))

    def json(self) -> Any:
        return _json.loads(self.body) if self.body else None

    @property
    def text(self) -> str:
        return self.body.decode("utf-8", errors="replace")


class Response:
    """Returned by plain deployments to control status/headers/body."""

    __slots__ = ("status", "headers", "body")

    def __init__(self, body: Any = b"", status: int = 200,
                 headers: Optional[List[Tuple[str, str]]] = None,
                 content_type: Optional[str] = None):
        self.status = status
        self.headers = list(headers or [])
        if isinstance(body, str):
            body = body.encode()
            content_type = content_type or "text/plain; charset=utf-8"
        elif not isinstance(body, (bytes, bytearray)):
            body = _json.dumps(body).encode()
            content_type = content_type or "application/json"
        self.body = bytes(body)
        if content_type and not any(
                k.lower() == "content-type" for k, _ in self.headers):
            self.headers.append(("Content-Type", content_type))


def _scope_from_request(req: Request) -> dict:
    return {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": req.method,
        "scheme": "http",
        "path": req.path,
        "raw_path": req.path.encode(),
        "root_path": "",
        "query_string": req.query_string.encode(),
        "headers": [(k.lower().encode(), v.encode())
                    for k, v in req.headers],
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 0),
    }


def ingress(app):
    """Class decorator mounting an ASGI app as the deployment's HTTP
    surface (reference: ``serve.ingress``, python/ray/serve/api.py).

    Usage::

        fastapi_app = FastAPI()

        @serve.deployment
        @serve.ingress(fastapi_app)
        class MyApp:
            ...

    The decorated class gains ``__serve_asgi_stream__`` — an async
    generator the proxy drives with ``options(stream=True)``; each
    yielded item is one ASGI send() event, so streaming responses reach
    the client incrementally."""

    def decorator(cls):
        import asyncio
        import inspect

        class ASGIIngress(cls):
            __serve_asgi__ = True

            async def __serve_asgi_stream__(self, request: Request):
                scope = _scope_from_request(request)
                queue: "asyncio.Queue" = asyncio.Queue()
                body = request.body
                sent = False

                async def receive():
                    nonlocal sent
                    if not sent:
                        sent = True
                        return {"type": "http.request", "body": body,
                                "more_body": False}
                    # app awaits disconnect after the response: park
                    # forever — the task is cancelled when the stream
                    # generator is closed
                    await asyncio.Event().wait()

                async def send(event):
                    await queue.put(event)

                target = app
                # support bound sub-app factories: attribute name of an
                # ASGI app on the instance
                if isinstance(target, str):
                    target = getattr(self, target)
                task = asyncio.ensure_future(target(scope, receive, send))
                try:
                    while True:
                        get = asyncio.ensure_future(queue.get())
                        done, _ = await asyncio.wait(
                            {get, task},
                            return_when=asyncio.FIRST_COMPLETED)
                        if get in done:
                            event = get.result()
                            yield event
                            if event.get("type") == "http.response.body" \
                                    and not event.get("more_body"):
                                break
                        else:
                            get.cancel()
                            # app finished (or crashed) without a final
                            # body event
                            exc = task.exception()
                            if exc is not None:
                                raise exc
                            while not queue.empty():
                                yield queue.get_nowait()
                            break
                finally:
                    if not task.done():
                        task.cancel()

        ASGIIngress.__name__ = getattr(cls, "__name__", "ASGIIngress")
        ASGIIngress.__qualname__ = ASGIIngress.__name__
        ASGIIngress.__module__ = getattr(cls, "__module__", __name__)
        return ASGIIngress

    return decorator
