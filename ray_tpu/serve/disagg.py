"""Disaggregated prefill/decode serving: KV hand-off + joint routing.

Splits a serving fleet into **prefill replicas** (chunked prefill only,
``EngineConfig(trace_part="prefill")``) and **decode replicas** (decode
slots + the paged-attention kernel). A request's prompt runs through the
prefill replica's chunked-prefill trunk into page-aligned KV blocks,
which ship to the chosen decode replica as a hand-off payload and are
adopted into its block pool + radix trie before the first decode tick
(``LLMEngine.prefill_export`` / ``submit_adopt``).

The shipping itself is the runtime's own machinery, not a side channel:
the prefill call's ObjectRef is passed as a top-level argument of the
decode replica's actor call, so the decode worker pulls the payload
worker-to-worker (PUL/PRQ/PSH/CAK) — the KV slab rides the zero-copy
out-of-band serializer, and the actor calls ride the reliable layer
(ACL is in ``RELIABLE_TYPES``). The payload never transits the router.

Wire formats (``EngineConfig.kv_wire``):

- ``"bf16"`` — the cache's native dtype shipped raw (bit-exact; an f32
  cache ships f32). Greedy decode after adoption is bit-identical to a
  colocated run. The default.
- ``"int8"`` — blockwise symmetric int8 (``parallel/quantization.py``):
  1 byte/element + one f32 scale per 256-element block, ~2x smaller
  than bf16 on the wire at a bounded dequant error.

:class:`DisaggRouter` scores the (prefill, decode) pair jointly off the
per-replica engine gauges — decode side wants free KV blocks + slots
(``handle.gauge_score``), prefill side wants a shallow queue + chunk
backlog — with decode-side session affinity preserved so multi-turn
requests land where their earlier KV lives. The same export/adopt
machinery powers **warm-prefix migration on downscale**: a draining
replica's warm ref-0 radix-trie chains (``export_warm_prefixes``) are
adopted by a survivor (``import_warm_prefixes``), see
:func:`migrate_warm_prefixes` and ``Deployment(migrate_prefixes=True)``.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

import ray_tpu
from ray_tpu.exceptions import RayTpuError


class DisaggHandoffError(RayTpuError):
    """The prefill->decode KV hand-off failed terminally: every retry
    pair died or errored before the first decoded token. The router
    surfaces this (typed) instead of a bare actor error so callers can
    distinguish a hand-off failure from an in-decode failure."""


# ------------------------------------------------------------ KV codec
def pack_kv_blocks(k: np.ndarray, v: np.ndarray,
                   wire: str = "bf16") -> Dict[str, Any]:
    """Pack gathered KV block slabs ``[n_layers, n_blocks, block_size,
    kv_heads, head_dim]`` for the wire. ``"bf16"`` ships the arrays in
    their native dtype (bit-exact roundtrip); ``"int8"`` quantizes each
    slab blockwise (``quantize_int8_np``). ``wire_bytes`` is the actual
    transport footprint as the zero-copy serializer would ship it."""
    if wire not in ("bf16", "int8"):
        raise ValueError(f"unknown kv wire format {wire!r}")
    k = np.ascontiguousarray(k)
    v = np.ascontiguousarray(v)
    if k.shape != v.shape:
        raise ValueError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    out: Dict[str, Any] = {"wire": wire, "shape": list(k.shape),
                           "dtype": str(k.dtype)}
    if wire == "bf16":
        out["k"], out["v"] = k, v
        payload: List[np.ndarray] = [k, v]
    else:
        from ray_tpu.parallel.quantization import quantize_int8_np
        out["k"], out["k_scales"] = quantize_int8_np(k)
        out["v"], out["v_scales"] = quantize_int8_np(v)
        payload = [out["k"], out["k_scales"], out["v"], out["v_scales"]]
    try:
        from ray_tpu.core.protocol import wire_sizeof
        out["wire_bytes"] = int(wire_sizeof(payload))
    except Exception:
        out["wire_bytes"] = int(sum(a.nbytes for a in payload))
    return out


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency; provides bfloat16 et al.
        return np.dtype(getattr(ml_dtypes, name))


def unpack_kv_blocks(kv: Dict[str, Any], dtype=None
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Invert :func:`pack_kv_blocks`: ``(k, v)`` numpy slabs
    ``[n_layers, n_blocks, block_size, kv_heads, head_dim]``, cast to
    ``dtype`` (default: the dtype they were packed from)."""
    shape = tuple(kv["shape"])
    tgt = np.dtype(dtype) if dtype is not None else _np_dtype(kv["dtype"])
    if kv["wire"] == "bf16":
        k, v = np.asarray(kv["k"]), np.asarray(kv["v"])
        if k.dtype != tgt:
            k, v = k.astype(tgt), v.astype(tgt)
    elif kv["wire"] == "int8":
        from ray_tpu.parallel.quantization import dequantize_int8_np
        k = dequantize_int8_np(kv["k"], kv["k_scales"], shape=shape,
                               dtype=tgt)
        v = dequantize_int8_np(kv["v"], kv["v_scales"], shape=shape,
                               dtype=tgt)
    else:
        raise ValueError(f"unknown kv wire format {kv['wire']!r}")
    if k.shape != shape:
        raise ValueError(
            f"unpacked shape {k.shape} != packed shape {shape}")
    return k, v


def kv_ship_bytes(n_blocks: int, block_size: int, kv_heads: int,
                  head_dim: int, n_layers: int, wire: str = "bf16",
                  dtype_bytes: int = 2) -> int:
    """Analytic wire footprint of one hand-off: ``2 (k+v) * n_layers *
    n_blocks * block_size * kv_heads * head_dim`` elements at
    ``dtype_bytes`` each for ``"bf16"``, or 1 byte/element plus one f32
    scale per 256-element quant block for ``"int8"`` (the README's
    bytes-per-ship math; the measured ``wire_bytes`` adds only pickle
    framing on top of this)."""
    numel = 2 * n_layers * n_blocks * block_size * kv_heads * head_dim
    if wire == "bf16":
        return numel * dtype_bytes
    from ray_tpu.parallel.quantization import wire_bytes as _wb
    # two slabs quantized independently (k and v)
    half = numel // 2
    return 2 * _wb(half, transport="int8")


# ------------------------------------------------------- joint routing
def prefill_score(g: Dict[str, Any]) -> float:
    """Desirability of a prefill replica (higher is better): shallow
    admission queue and little chunk backlog. Free decode slots are
    meaningless on a prefill-only fleet — every request holds a slot for
    exactly one chunk train — so the queue IS the signal."""
    queue = g.get("queue_depth") or 0
    prefilling = g.get("prefilling") or 0
    return -(float(queue) + 0.5 * float(prefilling))


class _DisaggMethod:
    def __init__(self, router: "DisaggRouter", opts: Dict[str, Any]):
        self._router = router
        self._opts = opts

    def remote(self, prompt_ids, max_new_tokens=None, eos_token_id=None):
        return self._router.generate(
            prompt_ids, max_new_tokens, eos_token_id=eos_token_id,
            **self._opts)


class _DisaggOptions:
    """``handle.options(...)`` shim so the bench harness's ``run_load``
    drives a :class:`DisaggRouter` exactly like a DeploymentHandle:
    ``router.options(stream=True).generate.remote(prompt, n)``."""

    def __init__(self, router: "DisaggRouter", opts: Dict[str, Any]):
        self._router = router
        self._opts = opts

    @property
    def generate(self) -> _DisaggMethod:
        return _DisaggMethod(self._router, self._opts)


class DisaggRouter:
    """Client-side router for a disaggregated pair of fleets.

    Holds one ``_Router`` per fleet (same membership/gauge machinery as
    a DeploymentHandle) and scores the (prefill, decode) pair jointly:
    the additive joint score decomposes into a per-side argmax, so each
    side picks its best candidate off the freshest gauges — decode by
    ``gauge_score`` (+ session affinity, which wins outright, + the
    prefix-fingerprint bonus), prefill by :func:`prefill_score`. Both
    sides fall back to power-of-two-choices on stale gauges.

    ``generate`` is a synchronous token generator: a pair death before
    the first token is retried on a fresh pair (membership resynced,
    dead pair excluded); exhaustion raises :class:`DisaggHandoffError`.
    """

    #: pair re-picks after an actor death before the first token
    max_retries = 2

    def __init__(self, prefill_deployment: str, decode_deployment: str,
                 controller=None):
        from ray_tpu.serve.handle import _Router
        if controller is None:
            from ray_tpu.serve import api as serve_api
            controller = serve_api._controller_or_none()
            if controller is None:
                raise RuntimeError("Serve is not running")
        self.prefill = _Router(prefill_deployment, controller)
        self.decode = _Router(decode_deployment, controller)
        self._lock = threading.Lock()
        self.stats: Dict[str, int] = {
            "requests": 0, "retries": 0, "handoff_errors": 0}

    # -- pair scoring -------------------------------------------------
    def _pick_decode(self, session_id, prefix_fp, exclude):
        from ray_tpu.serve.handle import gauge_score
        r = self.decode
        by_key = {r._key(rep): rep for rep in r.replicas}
        if session_id is not None:
            k = r.session_affinity.get(session_id)
            if k is not None and k in by_key and k not in exclude:
                return by_key[k], k
        cands = [rep for rep in r.replicas
                 if r._key(rep) not in exclude] or list(r.replicas)
        r._poll_gauges()
        fresh = r._fresh_gauges()

        def score(g):
            s = gauge_score(g)
            if prefix_fp is not None and prefix_fp in \
                    (g.get("prefix_fingerprints") or ()):
                s += r.prefix_match_bonus
            return s

        scored = [(score(fresh[r._key(rep)]), i, rep)
                  for i, rep in enumerate(cands)
                  if r._key(rep) in fresh]
        if scored:
            best = max(scored, key=lambda t: (
                t[0] - 0.25 * r.load(t[2]), -t[1]))
            rep = best[2]
        else:
            rep = self._pow2(r, cands)
        k = r._key(rep)
        if session_id is not None:
            r.session_affinity[session_id] = k
        return rep, k

    def _pick_prefill(self, exclude):
        r = self.prefill
        cands = [rep for rep in r.replicas
                 if r._key(rep) not in exclude] or list(r.replicas)
        r._poll_gauges()
        fresh = r._fresh_gauges()
        scored = [(prefill_score(fresh[r._key(rep)]), i, rep)
                  for i, rep in enumerate(cands)
                  if r._key(rep) in fresh]
        if scored:
            best = max(scored, key=lambda t: (
                t[0] - 0.25 * r.load(t[2]), -t[1]))
            rep = best[2]
        else:
            rep = self._pow2(r, cands)
        return rep, r._key(rep)

    @staticmethod
    def _pow2(router, cands):
        if len(cands) == 1:
            return cands[0]
        a, b = random.sample(cands, 2)
        return a if router.load(a) <= router.load(b) else b

    def pick_pair(self, session_id: Optional[str] = None,
                  prefix_fp: Optional[int] = None,
                  exclude_prefill: Sequence[bytes] = (),
                  exclude_decode: Sequence[bytes] = ()):
        """Returns ``(prefill_replica, pkey, decode_replica, dkey)``."""
        with self._lock:
            self.prefill.refresh()
            self.decode.refresh()
            if not self.prefill.replicas or not self.decode.replicas:
                raise RuntimeError(
                    f"disagg fleets incomplete: "
                    f"{len(self.prefill.replicas)} prefill / "
                    f"{len(self.decode.replicas)} decode replicas")
            dc, dkey = self._pick_decode(
                session_id, prefix_fp, set(exclude_decode))
            pf, pkey = self._pick_prefill(set(exclude_prefill))
        return pf, pkey, dc, dkey

    # -- request path -------------------------------------------------
    def options(self, *, stream: bool = True,
                session_id: Optional[str] = None,
                prefix_fingerprint: Optional[int] = None,
                request_id: Optional[str] = None,
                routing_policy: Optional[str] = None,
                **kwargs) -> _DisaggOptions:
        """Handle-compatible surface for the bench harness. Disagg
        requests are always streamed and always gauge-routed;
        ``routing_policy`` is accepted (and ignored beyond validation)
        so ``run_load``'s handle_opts pass through unchanged."""
        if kwargs:
            raise TypeError(
                f"unsupported disagg options: {sorted(kwargs)}")
        if routing_policy not in (None, "gauge", "pow2", "round_robin"):
            raise ValueError(f"unknown routing_policy {routing_policy!r}")
        return _DisaggOptions(self, {
            "session_id": session_id,
            "prefix_fp": prefix_fingerprint,
            "request_id": request_id,
        })

    def _mint_ctx(self, request_id: Optional[str]):
        """One request identity spans both fleets: the prefill and
        decode engines trace under the same request id with distinct
        parts (``trace_part``), so the waterfall stitches PREFILL +
        KV_SHIP from one replica with KV_ADOPT + DECODE from the
        other."""
        tracer = self.decode._get_tracer()
        trace = tracer.begin(request_id=request_id) \
            if tracer is not None else None
        rid = trace.request_id if trace is not None else request_id
        ctx: Dict[str, Any] = {"multiplexed_model_id": ""}
        if trace is not None:
            ctx["request_id"] = rid
            ctx["trace"] = {
                "sampled": trace.sampled,
                "enqueue_ts": time.time(),
                "policy": "disagg",
                "score": None,
                "admission": "bypass",
            }
        return rid, ctx

    def generate(self, prompt_ids: Sequence[int],
                 max_new_tokens: Optional[int] = None,
                 eos_token_id: Optional[int] = None, *,
                 session_id: Optional[str] = None,
                 prefix_fp: Optional[int] = None,
                 request_id: Optional[str] = None) -> Iterator[Any]:
        """Disaggregated generate: prefill on one fleet, decode on the
        other, KV shipped between them. Yields exactly what a colocated
        ``LLMServer.generate`` stream would (first token included)."""
        prompt = list(prompt_ids)
        exclude_p: set = set()
        exclude_d: set = set()
        last_err: Optional[BaseException] = None
        with self._lock:
            self.stats["requests"] += 1
        for attempt in range(self.max_retries + 1):
            pf, pkey, dc, dkey = self.pick_pair(
                session_id=session_id, prefix_fp=prefix_fp,
                exclude_prefill=exclude_p, exclude_decode=exclude_d)
            _, ctx = self._mint_ctx(request_id)
            first = True
            try:
                # the ObjectRef rides as a top-level arg: the decode
                # worker pulls the payload from the prefill worker
                # directly (P2P over the reliable layer) — the slab
                # never transits this process
                ref = pf.handle_request_ctx.remote(
                    ctx, "prefill_export", prompt)
                gen = dc.handle_request_stream.options(
                    num_returns="streaming").remote(
                        ctx, "adopt_generate", ref, max_new_tokens,
                        eos_token_id)
                self.decode.stream_started(dkey)
                try:
                    for item_ref in gen:
                        item = ray_tpu.get(item_ref)
                        first = False
                        yield item
                finally:
                    self.decode.stream_finished(dkey)
                return
            except Exception as e:  # noqa: BLE001
                if first and attempt < self.max_retries \
                        and self._retryable(e):
                    last_err = e
                    exclude_p.add(pkey)
                    exclude_d.add(dkey)
                    with self._lock:
                        self.stats["retries"] += 1
                        if session_id is not None:
                            self.decode.session_affinity.pop(
                                session_id, None)
                        self.prefill.refresh(force=True)
                        self.decode.refresh(force=True)
                    continue
                if first:
                    with self._lock:
                        self.stats["handoff_errors"] += 1
                    raise DisaggHandoffError(
                        f"prefill/decode hand-off failed after "
                        f"{attempt + 1} attempt(s): "
                        f"{type(e).__name__}: {e}") from e
                raise   # in-decode failure after first token: not ours
        with self._lock:
            self.stats["handoff_errors"] += 1
        raise DisaggHandoffError(
            f"prefill/decode hand-off failed after "
            f"{self.max_retries + 1} attempt(s): "
            f"{type(last_err).__name__}: {last_err}") from last_err

    @staticmethod
    def _retryable(e: BaseException) -> bool:
        """A death anywhere along the hand-off pair is retryable: the
        prefill actor dying mid-ship surfaces through the decode-side
        stream — as a TaskError wrapping the decode worker's failed
        argument pull — so unwrap task errors before classifying."""
        from ray_tpu.serve.handle import _is_actor_death
        from ray_tpu.exceptions import (ObjectLostError, RpcTimeoutError,
                                        TaskError)
        if _is_actor_death(e) or \
                isinstance(e, (ObjectLostError, RpcTimeoutError)):
            return True
        if isinstance(e, TaskError):
            if e.cause is not None and DisaggRouter._retryable(e.cause):
                return True
            # cross-process TaskErrors carry only the traceback text
            return any(name in (e.traceback_str or "") for name in
                       ("ActorDiedError", "ActorError",
                        "ObjectLostError"))
        return False


# --------------------------------------------------- migration helper
def migrate_warm_prefixes(src_replica, dst_replica, min_hits: int = 1,
                          max_blocks: int = 0,
                          timeout_s: float = 30.0) -> int:
    """Ship ``src``'s warm ref-0 radix-trie chains to ``dst`` (both
    Replica actors): the export ref is passed straight into the import
    call, so the KV slab moves worker-to-worker and never transits the
    caller. Returns the number of blocks the survivor adopted (0 when
    the victim had nothing warm or the survivor had no free blocks)."""
    ref = src_replica.prepare_drain.remote(min_hits, max_blocks)
    n = ray_tpu.get(
        dst_replica.handle_request.remote("import_warm_prefixes", ref),
        timeout=timeout_s)
    return int(n or 0)


# ----------------------------------------------------- fleet assembly
def deploy_disaggregated(model: Dict[str, Any], engine: Dict[str, Any],
                         *, name: str = "llm", num_prefill: int = 1,
                         num_decode: int = 1,
                         decode_slots: Optional[int] = None,
                         kv_wire: Optional[str] = None,
                         migrate_prefixes: bool = False,
                         max_ongoing_requests: int = 100,
                         route_prefix: Optional[str] = None
                         ) -> DisaggRouter:
    """Deploy ``{name}-prefill`` + ``{name}-decode`` LLMServer fleets
    sharing one model/engine config (same seed => identical params =>
    bit-exact hand-off) and return the :class:`DisaggRouter` over them.
    This is the ``disaggregate=`` surface: the decode fleet can run
    more ``decode_slots`` than a colocated replica since it never
    interleaves prefill chunks; ``kv_wire`` picks the hand-off format;
    ``migrate_prefixes`` arms the controller's drain-time warm-prefix
    migration on the decode fleet."""
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    eng = dict(engine)
    if kv_wire is not None:
        eng["kv_wire"] = kv_wire
    # the prefill fleet's engine traces under its own part so the
    # shared request id doesn't dedup its spans against decode's
    pre_eng = dict(eng, trace_part="prefill")
    dec_eng = dict(eng)
    if decode_slots is not None:
        dec_eng["decode_slots"] = decode_slots
    for suffix, ecfg, n, migrate in (
            ("prefill", pre_eng, num_prefill, False),
            ("decode", dec_eng, num_decode, migrate_prefixes)):
        dep = serve.deployment(
            name=f"{name}-{suffix}", num_replicas=n,
            max_ongoing_requests=max_ongoing_requests,
            migrate_prefixes=migrate)(serve.LLMServer)
        serve.run(dep.bind(model=model, engine=ecfg),
                  name=f"{name}-{suffix}", route_prefix=None)
    controller = serve_api._get_or_create_controller()
    if route_prefix is not None:
        # HTTP ingress: the proxy drives this pair via a DisaggRouter
        ray_tpu.get(controller.register_disagg_route.remote(
            route_prefix, f"{name}-prefill", f"{name}-decode"))
    return DisaggRouter(f"{name}-prefill", f"{name}-decode", controller)
