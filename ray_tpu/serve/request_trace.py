"""request_trace.py — per-request distributed tracing for the serve path.

The metrics plane (PR 11) answers "is the fleet healthy" in aggregate
and the flight recorder (PR 4) traces *task* control hops; this module
makes the *request* a first-class traced object. A ``request_id`` is
minted at the HTTP proxy / ``handle.remote()``, stamped into the
replica-call context by the router (together with its score, policy and
admission verdict), and materialised on the replica into phase spans:

=============  =====================================================
phase          meaning
=============  =====================================================
QUEUED         router enqueue -> engine admission (a decode slot won)
ADMITTED       slot assignment incl. prefix-cache match / CoW forks
PREFILL        one chunked-prefill step (per chunk)
KV_SHIP        disagg hand-off: finished prefill KV blocks in flight
               from the prefill replica to the chosen decode replica
KV_ADOPT       disagg hand-off: decode replica adopting shipped blocks
               into its pool + radix trie (bytes/blocks/wire in attrs)
SPEC_VERIFY    one speculative verify step (drafted/accepted counts)
DECODE         a per-N-token tick of batched decode
WEIGHT_SWAP    an in-flight weight refresh overlapping this request
FIRST_TOKEN    instant: first emitted token (TTFT anchor)
DONE           terminal: completed normally
FAILED         terminal: typed error (named in ``attrs.error``)
SHED           terminal: rejected by admission before any replica
=============  =====================================================

Spans are recorded locally in a bounded per-request buffer at
flight-recorder cost (one dict + append, ~couple µs — bench_serve
guards the <=20µs bound) and ship to the controller as REQUEST_SPANS
(``b"RSP"``) messages riding the PR-2 reliable layer exactly like TEV:
fire-and-forget for the producer, chaos-droppable, exactly-once-effect
at the controller (the store additionally dedups by
``(request_id, part, seq)`` so a dup never doubles a waterfall).

Tail-based sampling keeps the hot-path cost bounded at fleet scale:
every request records, but only slow (SLO budget tripped —
serve/slo.py), failed/shed, and a deterministic 1-in-N sample actually
ship. Fast unsampled requests are recorded and discarded locally,
shipping zero bytes.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

# Canonical phase names. Terminal phases close the waterfall.
QUEUED = "QUEUED"
ADMITTED = "ADMITTED"
PREFILL = "PREFILL"
KV_SHIP = "KV_SHIP"
KV_ADOPT = "KV_ADOPT"
SPEC_VERIFY = "SPEC_VERIFY"
DECODE = "DECODE"
WEIGHT_SWAP = "WEIGHT_SWAP"
FIRST_TOKEN = "FIRST_TOKEN"
DONE = "DONE"
FAILED = "FAILED"
SHED = "SHED"

TERMINAL_PHASES = frozenset({DONE, FAILED, SHED})

#: Render/aggregation order for waterfalls and per-phase breakdowns.
PHASE_ORDER = (QUEUED, ADMITTED, PREFILL, KV_SHIP, KV_ADOPT,
               SPEC_VERIFY, DECODE, WEIGHT_SWAP, FIRST_TOKEN, DONE,
               FAILED, SHED)

#: Cap on spans buffered per request: a pathological 100k-token decode
#: must not make its own trace unbounded. Oldest non-terminal spans are
#: dropped first; the drop is counted in the trace meta.
MAX_SPANS_PER_REQUEST = 512

#: Cap on the inter-token gap reservoir the SLO watchdog evaluates.
MAX_GAPS_PER_REQUEST = 1024


def new_request_id() -> str:
    return "req-" + uuid.uuid4().hex[:16]


class RequestTrace:
    """Span buffer for one request. Cheap by construction: recording a
    span is one dict build + one append under no lock (each trace is
    owned by the single thread driving that request's phase)."""

    __slots__ = ("request_id", "part", "sampled", "ship", "spans",
                 "meta", "slo", "gaps", "status", "t_begin", "dropped")

    def __init__(self, request_id: str, part: str = "engine",
                 sampled: bool = False,
                 meta: Optional[Dict[str, Any]] = None):
        self.request_id = request_id
        self.part = part
        self.sampled = bool(sampled)
        #: flips True the moment an SLO budget trips or the request
        #: fails — tail sampling's "always ship" escape hatch.
        self.ship = bool(sampled)
        self.spans: List[Dict[str, Any]] = []
        self.meta: Dict[str, Any] = dict(meta or {})
        self.slo: Dict[str, Dict[str, float]] = {}
        self.gaps: List[float] = []
        self.status: Optional[str] = None
        self.t_begin = time.time()
        self.dropped = 0

    # ------------------------------------------------------- recording
    def span(self, phase: str, t0: float, t1: Optional[float] = None,
             **attrs: Any) -> None:
        """Record one phase span (wall-clock seconds; ``t1=None`` makes
        an instant). Must stay O(1) and allocation-light: bench_serve
        guards a <=20µs bound on this call."""
        if t1 is None:
            t1 = t0
        elif t1 < t0:
            t1 = t0
        s: Dict[str, Any] = {"request_id": self.request_id,
                             "phase": phase, "t0": t0, "t1": t1}
        if attrs:
            s["attrs"] = attrs
        if len(self.spans) >= MAX_SPANS_PER_REQUEST:
            # drop the oldest non-terminal span; keep the count honest
            self.spans.pop(0)
            self.dropped += 1
        self.spans.append(s)
        if phase in TERMINAL_PHASES:
            self.status = phase
            if phase != DONE:          # FAILED / SHED always ship
                self.ship = True

    def event(self, phase: str, t: Optional[float] = None,
              **attrs: Any) -> None:
        """Instant span (FIRST_TOKEN and friends)."""
        self.span(phase, time.time() if t is None else t, None, **attrs)


class RequestTracer:
    """Per-process tracer: hands out ``RequestTrace`` buffers, applies
    the deterministic 1-in-N baseline sample, and ships finished traces
    that earned it. A bounded ring of recently finished traces is kept
    locally (shipped or not) so a postmortem can look at requests that
    tail sampling discarded."""

    def __init__(self, config=None, part: str = "engine",
                 send=None, sample_n: Optional[int] = None):
        self.part = part
        self.enabled = True
        n = 100
        if config is not None:
            self.enabled = bool(
                getattr(config, "enable_request_trace", True))
            n = int(getattr(config, "trace_sample_n", 100))
        if sample_n is not None:
            n = int(sample_n)
        self.sample_n = n
        self._send = send
        self._proc: Optional[str] = None
        self._count = itertools.count()
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        #: recently finished traces (local ring; postmortem aid)
        self.recent: collections.deque = collections.deque(maxlen=128)
        #: payloads shipped when no runtime is attached (unit tests)
        self.shipped_local: collections.deque = collections.deque(
            maxlen=32)

    # ------------------------------------------------------- lifecycle
    def begin(self, request_id: Optional[str] = None,
              sampled: Optional[bool] = None,
              meta: Optional[Dict[str, Any]] = None
              ) -> Optional[RequestTrace]:
        """Start a trace, or return None when tracing is disabled (all
        call sites treat a None trace as a no-op)."""
        if not self.enabled:
            return None
        if sampled is None:
            n = self.sample_n
            sampled = n > 0 and (next(self._count) % n) == 0
        return RequestTrace(request_id or new_request_id(),
                            part=self.part, sampled=bool(sampled),
                            meta=meta)

    def finish(self, trace: Optional[RequestTrace],
               status: Optional[str] = None,
               err: Optional[BaseException] = None) -> bool:
        """Close a trace; ship it iff sampled, SLO-tripped, or
        failed/shed. Returns whether spans were shipped."""
        if trace is None:
            return False
        if err is not None and trace.status not in TERMINAL_PHASES:
            trace.span(FAILED, time.time(),
                       error=type(err).__name__, detail=str(err)[:200])
        elif status is not None and trace.status is None:
            trace.span(status, time.time())
        self.recent.append(trace)
        if not trace.ship:
            return False
        return self._ship(trace)

    # -------------------------------------------------------- shipping
    def _ship(self, trace: RequestTrace) -> bool:
        with self._lock:
            seq = next(self._seq)
        if self._proc is None:
            # origin process name (the flight recorder's track label):
            # lets the Perfetto export draw flow arrows from request
            # waterfalls into this process's engine/stage slices
            try:
                from ray_tpu.core.global_state import try_global_worker
                w = try_global_worker()
                self._proc = getattr(
                    getattr(w, "recorder", None), "proc", None) or "?"
            except Exception:
                self._proc = "?"
        payload = {
            "request_id": trace.request_id,
            "part": trace.part,
            "proc": self._proc,
            "seq": seq,
            "ts": time.time(),
            "status": trace.status,
            "sampled": trace.sampled,
            "slo": trace.slo,
            "meta": trace.meta,
            "dropped": trace.dropped,
            "spans": trace.spans,
        }
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            runtime_metrics().request_spans_shipped.inc()
        except Exception:
            pass
        sender = self._send
        if sender is not None:
            try:
                sender(payload)
                return True
            except Exception:
                return False
        return _default_send(payload, self.shipped_local)


def _default_send(payload: Dict[str, Any], fallback) -> bool:
    """Lazy ship hook: enqueue an RSP on the attached runtime's reliable
    outbox (fire-and-forget, like a flight-recorder flush). Without a
    runtime the payload lands in the tracer's local deque so tests can
    assert on it."""
    try:
        from ray_tpu.core.global_state import try_global_worker
        from ray_tpu.core import protocol as P
        w = try_global_worker()
        send = getattr(w, "_send", None) if w is not None else None
        stopped = getattr(w, "_stopped", None)
        if stopped is not None and hasattr(stopped, "is_set"):
            stopped = stopped.is_set()    # runtime carries an Event
        if send is not None and not stopped:
            send(P.REQUEST_SPANS, payload)
            return True
    except Exception:
        pass
    fallback.append(payload)
    return False


# ---------------------------------------------------------------------
# controller side
# ---------------------------------------------------------------------

class RequestTraceStore:
    """Controller-resident store of shipped request traces. Internally
    locked (the dashboard reads it directly off the controller object,
    like the metrics plane). Exactly-once-effect: the reliable layer
    dedups retransmits, and this store additionally dedups by
    ``(part, seq)`` per request so even an application-level dup cannot
    double a waterfall. Bounded drop-oldest by finished request."""

    def __init__(self, max_requests: int = 512):
        self.max_requests = int(max_requests)
        self._lock = threading.Lock()
        self._reqs: "collections.OrderedDict[str, Dict[str, Any]]" = \
            collections.OrderedDict()
        self.ingested = 0
        self.deduped = 0

    # ------------------------------------------------------- ingestion
    def ingest(self, payload: Dict[str, Any]) -> bool:
        rid = payload.get("request_id")
        if not rid:
            return False
        key = (payload.get("part", "?"), payload.get("seq", 0))
        with self._lock:
            ent = self._reqs.get(rid)
            if ent is None:
                ent = {"request_id": rid, "parts": set(), "spans": [],
                       "status": None, "slo": {}, "meta": {},
                       "procs": {}, "dropped": 0,
                       "ts": payload.get("ts", 0.0)}
                self._reqs[rid] = ent
                while len(self._reqs) > self.max_requests:
                    self._reqs.popitem(last=False)
            if key in ent["parts"]:
                self.deduped += 1
                return False
            ent["parts"].add(key)
            if payload.get("proc"):
                ent["procs"][payload.get("part", "?")] = payload["proc"]
            ent["spans"].extend(payload.get("spans") or [])
            ent["slo"].update(payload.get("slo") or {})
            ent["meta"].update(payload.get("meta") or {})
            ent["dropped"] += int(payload.get("dropped", 0))
            ent["ts"] = max(ent["ts"], payload.get("ts", 0.0))
            status = payload.get("status")
            # a terminal status from any part wins; FAILED/SHED beats
            # DONE (the failing part saw the request's true end)
            if status and (ent["status"] is None
                           or ent["status"] == DONE):
                ent["status"] = status
            self.ingested += 1
            self._reqs.move_to_end(rid)
            return True

    # --------------------------------------------------------- queries
    @staticmethod
    def _phase_breakdown(spans: List[Dict[str, Any]]
                         ) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for s in spans:
            ph = s.get("phase", "?")
            d = out.setdefault(ph, {"count": 0, "dur_s": 0.0})
            d["count"] += 1
            d["dur_s"] += max(0.0, s.get("t1", 0.0) - s.get("t0", 0.0))
        return out

    @staticmethod
    def _sorted_spans(ent: Dict[str, Any]) -> List[Dict[str, Any]]:
        # sort by start time → monotone phase timestamps in the
        # waterfall even when parts shipped out of order; clamp each
        # span's end to its start (cross-process clock skew must never
        # render a negative-width slice)
        spans = sorted(ent["spans"],
                       key=lambda s: (s.get("t0", 0.0),
                                      s.get("t1", 0.0)))
        for s in spans:
            if s.get("t1", 0.0) < s.get("t0", 0.0):
                s["t1"] = s["t0"]
        return spans

    def rows(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Recent traced requests, newest first, with per-phase
        breakdown (the /api/v0/requests listing)."""
        with self._lock:
            ents = list(self._reqs.values())[-int(limit):]
        rows = []
        for ent in reversed(ents):
            spans = self._sorted_spans(ent)
            t0 = spans[0]["t0"] if spans else 0.0
            t1 = max((s["t1"] for s in spans), default=t0)
            rows.append({
                "request_id": ent["request_id"],
                "status": ent["status"],
                "ts": ent["ts"],
                "dur_s": max(0.0, t1 - t0),
                "n_spans": len(spans),
                "slo": ent["slo"],
                "phases": self._phase_breakdown(spans),
            })
        return rows

    def waterfall(self, request_id: str) -> Optional[Dict[str, Any]]:
        """Full span list for one request (the
        /api/v0/requests/<id> body and `ray-tpu trace` input)."""
        with self._lock:
            ent = self._reqs.get(request_id)
            if ent is None:
                return None
        spans = self._sorted_spans(ent)
        t0 = spans[0]["t0"] if spans else 0.0
        t1 = max((s["t1"] for s in spans), default=t0)
        return {
            "request_id": ent["request_id"],
            "status": ent["status"],
            "ts": ent["ts"],
            "dur_s": max(0.0, t1 - t0),
            "slo": ent["slo"],
            "meta": ent["meta"],
            "procs": dict(ent.get("procs") or {}),
            "dropped": ent["dropped"],
            "phases": self._phase_breakdown(spans),
            "spans": spans,
        }

    def slowest(self) -> Optional[Dict[str, Any]]:
        """Waterfall of the slowest captured request (chaos postmortem
        sidecar)."""
        with self._lock:
            rids = list(self._reqs.keys())
        best, best_dur = None, -1.0
        for rid in rids:
            w = self.waterfall(rid)
            if w is not None and w["dur_s"] > best_dur:
                best, best_dur = w, w["dur_s"]
        return best
