"""Continuous-batching LLM inference engine (the "millions of users"
serving path, ROADMAP item 1).

vLLM-style serving on the repo's own model stack: a paged KV cache in
device memory (``models.transformer.init_kv_cache``), a fixed array of
**decode slots** stepped as ONE batched ``decode_step`` call, and
**chunked prefill** interleaved between decode steps so a new arrival's
time-to-first-token never stalls in-flight streams for more than one
``prefill_chunk``'s worth of compute. New requests are admitted into the
in-flight batch between steps — continuous batching, not static batching:
a finishing stream frees its slot and blocks for the next queued prompt
immediately, so the MXU stays at high occupancy under ragged request
lengths.

Shapes are FIXED at engine construction (``decode_slots`` sequences per
decode call, ``prefill_chunk`` tokens per prefill call, one block table
of ``blocks_per_seq`` entries per slot) and both model functions are
jitted once with donated caches — admission, EOS, and cancellation are
pure host-side bookkeeping and never recompile.

Memory accounting: one KV block holds ``block_size`` tokens ×
``2 (k+v) × n_layers × kv_heads × head_dim × dtype_bytes`` bytes; the
pool is ``num_kv_blocks`` blocks (default: full occupancy — every slot
can hold ``max_seq_len`` tokens — plus one reserved trash block that
idle slots' writes land in). Blocks are **refcounted**
(:mod:`ray_tpu.serve.prefix_cache`): EOS/cancel/error decref instead
of free, full prompt chunks are indexed in a radix trie so a new
request whose prompt shares a prefix (the high-traffic common
system-prompt case) skips prefilling the matched blocks entirely —
copy-on-write covers the fully-matched tail block — and ref-0 blocks
stay warm in the trie until pool pressure evicts them LRU.

Speculative multi-token decode (``spec_tokens > 0``): each decode step
drafts up to k tokens per slot by **prompt lookup** (the sequence's
own history's most recent matching n-gram — no draft model), verifies
them in ONE batched (slots, k+1)-token call jitted once at fixed
shape, and accepts the longest prefix that matches the model's own
greedy argmax — per-token output is bit-identical to one-token-at-a-
time decode by construction. A per-slot acceptance EWMA disables
drafting for sequences it doesn't pay for.

Integration: :class:`LLMServer` is the deployment-facing wrapper —
``generate`` is an async generator, so a Serve replica streams tokens
through the core ``num_returns="streaming"`` machinery and
``handle.options(stream=True)`` / the HTTP proxy work unchanged;
consumer ``close()`` lands in :meth:`LLMEngine.cancel`, which frees the
slot and blocks at the next step boundary.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import functools
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu.exceptions import RayTpuError
from ray_tpu.serve import request_trace as RT


class EngineDeadError(RayTpuError):
    """The engine's step loop died; every queued/in-flight request is
    failed with this (typed — consumers never hang on a dead engine)."""


class RequestTooLargeError(RayTpuError):
    """prompt_len + 1 exceeds the engine's per-request window
    (``max_seq_len``) — the request can never be admitted."""


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Knobs of the serving engine (see README "Serving").

    - ``decode_slots``: sequences decoded per batched step — the
      continuous-batching width and the unit of batch occupancy.
    - ``kv_block_size``: tokens per KV-cache block (paging granularity;
      smaller = less internal fragmentation, more gather indices).
    - ``max_seq_len``: per-request window (prompt + generated tokens);
      sets ``blocks_per_seq`` and the attention gather width.
    - ``prefill_chunk``: prompt tokens processed per engine step — the
      TTFT-vs-inter-token-latency tradeoff knob.
    - ``num_kv_blocks``: KV pool size; 0 = auto (full occupancy + the
      reserved trash block idle slots write into).
    - ``enable_prefix_sharing``: refcounted radix-trie sharing of full
      prompt KV blocks (prefill skips matched prefixes).
    - ``spec_tokens``: draft tokens per slot per decode step via
      prompt-lookup speculation (0 = classic one-token decode).
    - ``spec_ngram``: longest history n-gram tried by the draft lookup.
    - ``spec_min_acceptance``: per-slot acceptance-EWMA floor below
      which drafting is disabled for that sequence.
    - ``capture_logprobs``: the jitted prefill/decode programs also
      return the log-probability of each selected token, so ``detailed``
      streams carry ``(token, policy_version, logprob)`` — the RLHF
      rollout payload. Mutually exclusive with ``spec_tokens > 0``
      (the verify path re-scores positions out of emission order).
    """
    decode_slots: int = 8
    kv_block_size: int = 16
    max_seq_len: int = 256
    prefill_chunk: int = 32
    num_kv_blocks: int = 0
    max_new_tokens: int = 64          # default per-request cap
    eos_token_id: Optional[int] = None
    enable_prefix_sharing: bool = True
    spec_tokens: int = 0
    spec_ngram: int = 3
    spec_min_acceptance: float = 0.1
    capture_logprobs: bool = False
    #: Per-request tracing (serve/request_trace.py): None follows the
    #: runtime config's enable_request_trace; True/False force it for
    #: this engine (bench_serve's trace-overhead on/off legs).
    enable_trace: Optional[bool] = None
    #: Tokens per DECODE trace span — bounds span count for long
    #: generations (a 4k-token decode is ~256 spans at 16, not 4k).
    trace_decode_tick: int = 16
    #: Wire format for disaggregated KV hand-offs (serve/disagg.py):
    #: "bf16" ships blocks raw in the cache's native dtype (bit-exact
    #: adoption — an f32 cache ships f32); "int8" ships blockwise-
    #: quantized values + f32 scales (~4x smaller, quant tolerance).
    kv_wire: str = "bf16"
    #: Part label this engine's trace span batches ship under. The
    #: controller store dedups by (part, seq) per request — a disagg
    #: pair (prefill engine + decode engine) sharing one request_id
    #: MUST ship under distinct parts or one side's spans vanish.
    trace_part: str = "engine"

    @property
    def blocks_per_seq(self) -> int:
        return -(-self.max_seq_len // self.kv_block_size)

    @property
    def resolved_num_blocks(self) -> int:
        if self.num_kv_blocks:
            return self.num_kv_blocks
        return 1 + self.decode_slots * self.blocks_per_seq

    def kv_bytes_per_token(self, model_config) -> int:
        """KV bytes/token — the HBM-budget side of the block math."""
        import jax.numpy as jnp
        c = model_config
        itemsize = jnp.dtype(c.dtype).itemsize
        return 2 * c.n_layers * c.kv_heads * c.head_dim * itemsize


_DONE = object()          # stream-end sentinel on the request queue

# request lifecycle states
_QUEUED, _PREFILL, _DECODE, _FINISHED = range(4)


class _Request:
    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "out", "state", "slot", "blocks", "prefill_pos",
                 "seq_len", "generated", "cancelled", "t_submit",
                 "t_first_token", "history", "hit_blocks", "trie_node",
                 "trie_cursor", "spec_ewma", "spec_disabled", "warmup",
                 "detailed", "trace", "t_enqueue_wall", "queue_wait_s",
                 "last_tok_wall", "tick_t0", "tick_toks", "export",
                 "adopt")

    def __init__(self, rid: int, prompt: List[int], max_new_tokens: int,
                 eos_token_id: Optional[int]):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.out: "queue.Queue" = queue.Queue()
        self.state = _QUEUED
        self.slot: Optional[int] = None
        self.blocks: List[int] = []
        self.prefill_pos = 0          # prompt tokens already in cache
        self.seq_len = 0              # cache positions written
        self.generated = 0            # tokens emitted
        self.cancelled = False
        self.warmup = False       # compile-only request: no telemetry
        self.detailed = False     # stream (tok, version, logprob) tuples
        # -- disaggregated hand-off (serve/disagg.py)
        self.export = False       # terminate at prompt end: ship KV
        self.adopt: Optional[dict] = None   # shipped payload to adopt
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        # -- per-request tracing (serve/request_trace.py)
        self.trace = None             # RequestTrace or None
        self.t_enqueue_wall = 0.0     # router (or submit) wall clock
        self.queue_wait_s = 0.0       # enqueue -> engine admission
        self.last_tok_wall: Optional[float] = None
        self.tick_t0: Optional[float] = None   # open DECODE tick start
        self.tick_toks = 0            # tokens in the open DECODE tick
        # -- prefix sharing (prefix_cache.PrefixBlockPool)
        self.hit_blocks = 0           # prompt blocks prefill skipped
        self.trie_node = None         # deepest trie node of this prompt
        self.trie_cursor = 0          # next full prompt block to index
        # -- speculative decode
        self.history: List[int] = list(prompt)   # tokens 0..seq_len
        self.spec_ewma: Optional[float] = None   # acceptance EWMA
        self.spec_disabled = False


class LLMEngine:
    """Continuous-batching scheduler over the paged decode path.

    Thread model: one background step thread owns the device state
    (caches + slot arrays); ``submit``/``cancel`` only touch the queue
    under a lock and are safe from any thread or event loop. Consumers
    read per-request ``queue.Queue``s fed by the step thread.
    """

    def __init__(self, model_config, engine_config: Optional[EngineConfig]
                 = None, params=None, seed: int = 0,
                 replica_tag: str = ""):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from ray_tpu.models import (decode_step, init_kv_cache,
                                    init_params, prefill)

        self.model_config = model_config
        self.config = engine_config or EngineConfig()
        self.replica_tag = replica_tag
        ec = self.config
        if ec.prefill_chunk < 1 or ec.decode_slots < 1:
            raise ValueError("prefill_chunk and decode_slots must be >= 1")
        if ec.spec_tokens < 0 or ec.spec_ngram < 1:
            raise ValueError("spec_tokens must be >= 0 and spec_ngram "
                             ">= 1")
        if ec.capture_logprobs and ec.spec_tokens > 0:
            raise ValueError(
                "capture_logprobs is incompatible with speculative "
                "decode (spec_tokens > 0): the verify path scores "
                "positions out of emission order")
        if ec.kv_wire not in ("bf16", "int8"):
            raise ValueError(
                f"kv_wire must be 'bf16' or 'int8', got {ec.kv_wire!r}")

        # Carried-over paged-kernel follow-on: at long table windows
        # (>= 4k tokens per sequence) the chunked-prefill side of the
        # paged kernel may win with row blocks > 128 — autotune once
        # (winner persists in the flash autotune cache under paged|
        # keys; off-TPU without an injected timer this is the chip
        # default and the config is left alone).
        window = ec.blocks_per_seq * ec.kv_block_size
        if (getattr(model_config, "paged_block_r_prefill", 0) == 0
                and window >= 4096 and ec.prefill_chunk > 1):
            try:
                from ray_tpu.ops.paged_flash import (
                    autotune_paged_block_r)
                rows = ec.prefill_chunk * (model_config.n_heads
                                           // model_config.kv_heads)
                br = autotune_paged_block_r(
                    ec.kv_block_size, ec.blocks_per_seq, rows,
                    model_config.head_dim,
                    candidates=(32, 64, 128, 256, 512))
                if br:
                    model_config = dataclasses.replace(
                        model_config, paged_block_r_prefill=int(br))
                    self.model_config = model_config
            except Exception:
                pass

        self._params = params if params is not None \
            else init_params(model_config, jax.random.PRNGKey(seed))
        self._cache = init_kv_cache(model_config, ec.resolved_num_blocks,
                                    ec.kv_block_size)

        S, T = ec.decode_slots, ec.blocks_per_seq
        self._np = np
        self._jnp = jnp
        # Host-side slot arrays. Block-table row 0s point idle slots at
        # the reserved trash block, so their (masked-garbage) decode
        # writes never touch a live sequence's blocks.
        self._block_tables = np.zeros((S, T), np.int32)
        self._seq_lens = np.zeros((S,), np.int32)
        self._last_tok = np.zeros((S,), np.int32)
        self._slots: List[Optional[_Request]] = [None] * S
        self._free_slots = list(range(S))
        # refcounted block pool + radix prefix index (block 0 = trash,
        # reserved); sharing off still routes through the pool — match/
        # insert are simply skipped, so the free-list path is one code
        # path either way
        from ray_tpu.serve.prefix_cache import PrefixBlockPool
        self._pool = PrefixBlockPool(ec.resolved_num_blocks,
                                     ec.kv_block_size, reserved=(0,))

        # jit once at the fixed shapes; caches are donated so XLA
        # updates them in place step over step. With capture_logprobs
        # the same programs also return the selected token's logprob
        # (greedy argmax is unchanged — the extra output is the RLHF
        # rollout payload, not a sampling change).
        capture = ec.capture_logprobs

        def _prefill_fn(params, tokens, cache, bt, start, lens):
            logits, cache = prefill(model_config, params, tokens, cache,
                                    bt, start, lens)
            last = jnp.take_along_axis(
                logits, (lens - 1)[:, None, None], axis=1)[:, 0]
            tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
            if capture:
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(last, axis=-1), tok[:, None],
                    axis=-1)[:, 0]
                return tok, lp, cache
            return tok, cache

        def _decode_fn(params, toks, cache, bt, seq_lens):
            logits, cache = decode_step(model_config, params, toks,
                                        cache, bt, seq_lens)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if capture:
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits, axis=-1), tok[:, None],
                    axis=-1)[:, 0]
                return tok, lp, cache
            return tok, cache

        self._jit_prefill = jax.jit(_prefill_fn, donate_argnums=(2,))
        self._jit_decode = jax.jit(_decode_fn, donate_argnums=(2,))

        # speculative verify: the whole slot array steps k+1 tokens per
        # call through the chunked-prefill trunk (positions/write-masks
        # already handle ragged per-slot lengths); per-position argmax
        # comes back for host-side longest-prefix acceptance. Jitted
        # once at (S, k+1) — drafting never recompiles.
        def _verify_fn(params, toks, cache, bt, start, lens):
            logits, cache = prefill(model_config, params, toks, cache,
                                    bt, start, lens)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        self._jit_verify = jax.jit(_verify_fn, donate_argnums=(2,)) \
            if ec.spec_tokens > 0 else None

        # copy-on-write block copy (fully-matched prompt tail): one
        # block's k/v copied src -> dst across all layers; indices are
        # traced scalars, so every CoW reuses the same compiled program
        def _copy_fn(cache, src, dst):
            k = cache["k"]
            v = cache["v"]
            k = jax.lax.dynamic_update_slice_in_dim(
                k, jax.lax.dynamic_slice_in_dim(k, src, 1, axis=1),
                dst, axis=1)
            v = jax.lax.dynamic_update_slice_in_dim(
                v, jax.lax.dynamic_slice_in_dim(v, src, 1, axis=1),
                dst, axis=1)
            return {"k": k, "v": v}

        self._jit_copy = jax.jit(_copy_fn, donate_argnums=(0,))

        # disaggregated hand-off block I/O (serve/disagg.py): gather
        # pulls a request's blocks into one contiguous slab for the
        # wire; scatter adopts a shipped slab into this pool. Both run
        # at the FIXED padded shape (blocks_per_seq ids) so adoption
        # never recompiles — pad ids point at the reserved trash block
        # and pad data is zeros, so the duplicate block-0 writes all
        # write zeros and scatter order cannot matter.
        def _gather_fn(cache, ids):
            return (jnp.take(cache["k"], ids, axis=1),
                    jnp.take(cache["v"], ids, axis=1))

        def _scatter_fn(cache, ids, k_slab, v_slab):
            return {"k": cache["k"].at[:, ids].set(k_slab),
                    "v": cache["v"].at[:, ids].set(v_slab)}

        self._jit_gather = jax.jit(_gather_fn)
        self._jit_scatter = jax.jit(_scatter_fn, donate_argnums=(0,))

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._pending: "collections.deque[_Request]" = collections.deque()
        self._prefilling: "collections.deque[_Request]" = \
            collections.deque()
        #: step-thread op queue: device work posted from actor-call
        #: threads (warm-prefix export/import) runs at the top of the
        #: next step, where the step thread exclusively owns the
        #: donated caches — no cross-thread device races by design
        self._ops: "collections.deque[dict]" = collections.deque()
        self._rid = 0
        self._stop = False
        self._dead: Optional[BaseException] = None

        self._jax = jax

        # -- in-flight weight refresh (MindSpeed-RL style): the learner
        # stages a fresh param tree + version; the step thread swaps the
        # pointer between decode steps. Slots are NEVER drained, so the
        # sync stall is structurally zero — _sync_stall_s exists to
        # PROVE that (any drain path would have to charge it).
        self._staged_weights: Optional[tuple] = None
        self._weight_version = 0
        self._weight_swaps = 0
        self._weight_swap_wall_s = 0.0
        self._sync_stall_s = 0.0

        # -- stats / metrics -------------------------------------------
        self._tokens_total = 0
        self._decode_steps = 0
        self._prefill_chunks = 0
        # device-wall split (the kernel-vs-reference bench reads these):
        # decode wall includes the result sync the step loop does anyway
        self._decode_wall_s = 0.0
        self._prefill_wall_s = 0.0
        # length-aware work accounting: pages a lens-skipping kernel
        # touches per decode step vs the full table window — FLOPs are
        # proportional to pages, so live/window IS the measured
        # work fraction of the paged fast path (any backend)
        self._decode_pages_live = 0
        self._decode_pages_window = 0
        self._prompt_blocks_total = 0   # full prompt blocks seen
        self._cow_copies = 0
        # disagg hand-off accounting (the bench's per-request ship
        # bytes/wall come from here; exports count on the prefill
        # fleet, adopts on the decode fleet)
        self._kv_exports = 0
        self._kv_export_bytes = 0
        self._kv_adopts = 0
        self._kv_adopt_bytes = 0
        self._kv_adopt_blocks = 0
        self._kv_ship_wall_s = 0.0
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_disables = 0
        self._occupancy: Dict[int, int] = collections.defaultdict(int)
        self._t_start = time.monotonic()
        self._last_stats_emit = 0.0
        # EWMA of recent TTFTs: the autoscaler's latency signal (a
        # histogram is right for dashboards, wrong for a scale-up
        # decision that wants "what are users seeing RIGHT NOW")
        self._ttft_ewma: Optional[float] = None
        self._metrics = self._recorder = None
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            self._metrics = runtime_metrics()
        except Exception:
            pass
        try:
            from ray_tpu.core.global_state import try_global_worker
            w = try_global_worker()
            self._recorder = getattr(w, "recorder", None)
        except Exception:
            pass
        # -- per-request tracing + SLO watchdog --------------------------
        # (serve/request_trace.py, serve/slo.py): the engine is the
        # waterfall's single shipper — router annotations arrive in the
        # call context, every phase span is materialised here, and ONE
        # REQUEST_SPANS batch ships at request end iff sampled /
        # SLO-tripped / failed.
        self._tracer = self._slo = None
        self._queue_wait_ewma: Optional[float] = None
        try:
            from ray_tpu.serve.request_trace import RequestTracer
            from ray_tpu.serve.slo import SLOBudget, SLOWatchdog
            cfg = None
            try:
                from ray_tpu.core.global_state import try_global_worker
                cfg = getattr(try_global_worker(), "config", None)
            except Exception:
                pass
            self._tracer = RequestTracer(cfg, part=ec.trace_part)
            if ec.enable_trace is not None:
                self._tracer.enabled = bool(ec.enable_trace)
            self._slo = SLOWatchdog(SLOBudget.from_config(cfg))
        except Exception:
            pass

        # Engine-owned executor for consumer-side queue polls: sharing
        # the actor event loop's default executor would let stream
        # polls and whole actor calls starve each other under load.
        from concurrent.futures import ThreadPoolExecutor
        self._poll_pool = ThreadPoolExecutor(
            2 * ec.decode_slots + 4, thread_name_prefix="llm-engine-poll")

        self._thread = threading.Thread(
            target=self._run, name="llm-engine-step", daemon=True)
        self._thread.start()

    # ------------------------------------------------------- public API
    def stage_weights(self, params, version: int) -> None:
        """Stage a fresh parameter tree for an in-flight refresh. The
        step thread swaps it in at the next step boundary (between
        decode calls) — in-flight sequences finish their current step
        on the old policy and continue on the new one, with every
        emitted token stamped by the version that actually produced it.
        Staging twice before a swap keeps only the newest tree (the
        double buffer holds one pending refresh). Safe from any thread;
        dequantize on the caller's thread, not here."""
        with self._work:
            if self._dead is not None:
                raise EngineDeadError(
                    f"engine step loop died: {self._dead!r}")
            self._staged_weights = (params, int(version))
            self._work.notify_all()

    @property
    def weight_version(self) -> int:
        """Version of the policy the NEXT decode step will run."""
        with self._lock:
            staged = self._staged_weights
            return staged[1] if staged is not None \
                else self._weight_version

    def submit(self, prompt_ids: Sequence[int],
               max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               detailed: bool = False,
               trace_ctx: Optional[Dict[str, Any]] = None,
               _warmup: bool = False, _export: bool = False,
               _adopt: Optional[Dict[str, Any]] = None) -> _Request:
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt")
        ec = self.config
        if len(prompt) + 1 > ec.max_seq_len:
            raise RequestTooLargeError(
                f"prompt of {len(prompt)} tokens + 1 exceeds the engine "
                f"window max_seq_len={ec.max_seq_len}")
        mnt = max_new_tokens if max_new_tokens is not None \
            else ec.max_new_tokens
        eos = eos_token_id if eos_token_id is not None else ec.eos_token_id
        with self._work:
            if self._dead is not None:
                raise EngineDeadError(
                    f"engine step loop died: {self._dead!r}")
            self._rid += 1
            req = _Request(self._rid, prompt, max(1, int(mnt)), eos)
            req.warmup = _warmup
            req.detailed = detailed
            req.export = _export
            req.adopt = _adopt
            if not _warmup:
                self._attach_trace(req, trace_ctx)
            self._pending.append(req)
            self._work.notify_all()
        return req

    def _attach_trace(self, req: _Request,
                      trace_ctx: Optional[Dict[str, Any]]) -> None:
        """Open this request's trace. ``trace_ctx`` is the router's
        stamp (request_id, sampled verdict, enqueue timestamp, routing
        annotations) flattened out of the replica call context; a
        direct ``submit`` (RLHF rollouts, tests) gets a locally-minted
        request_id and the tracer's own 1-in-N sampling decision."""
        tr = self._tracer
        if tr is None or not tr.enabled:
            return
        now = time.time()
        ctx = trace_ctx or {}
        rid = ctx.get("request_id")
        # a caller-pinned id with no explicit sampling verdict (RLHF
        # rollouts stamping ids) keeps the tracer's own 1-in-N; the
        # router always stamps its verdict explicitly
        sampled = ctx.get("sampled") if rid else None
        if sampled is not None:
            sampled = bool(sampled)
        meta = {k: ctx[k] for k in ("policy", "score", "admission")
                if ctx.get(k) is not None}
        trace = tr.begin(request_id=rid, sampled=sampled,
                         meta=meta or None)
        if trace is None:
            return
        req.trace = trace
        # clamp a skewed cross-process enqueue stamp: the QUEUED span
        # must never start in this process's future
        req.t_enqueue_wall = min(float(ctx.get("enqueue_ts") or now),
                                 now)

    def cancel(self, req: _Request) -> None:
        """Mark a request cancelled; the step thread frees its slot and
        blocks at the next step boundary (the generator ``close()``
        path lands here)."""
        with self._work:
            req.cancelled = True
            self._work.notify_all()

    async def generate(self, prompt_ids: Sequence[int],
                       max_new_tokens: Optional[int] = None,
                       eos_token_id: Optional[int] = None,
                       trace_ctx: Optional[Dict[str, Any]] = None):
        """Async token stream for one request. Raises typed errors
        (``EngineDeadError`` / ``RequestTooLargeError``) instead of
        hanging; early ``aclose()`` cancels the request and frees its
        slot + blocks."""
        req = self.submit(prompt_ids, max_new_tokens, eos_token_id,
                          trace_ctx=trace_ctx)
        loop = asyncio.get_running_loop()
        get = functools.partial(req.out.get, timeout=0.2)
        try:
            while True:
                try:
                    item = await loop.run_in_executor(self._poll_pool, get)
                except queue.Empty:
                    if self._dead is not None:
                        raise EngineDeadError(
                            f"engine step loop died: {self._dead!r}")
                    continue
                if item is _DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            self.cancel(req)

    def generate_sync(self, prompt_ids: Sequence[int],
                      max_new_tokens: Optional[int] = None,
                      eos_token_id: Optional[int] = None,
                      timeout_s: float = 120.0,
                      detailed: bool = False,
                      trace_ctx: Optional[Dict[str, Any]] = None):
        """Blocking token stream (tests / direct embedding)."""
        req = self.submit(prompt_ids, max_new_tokens, eos_token_id,
                          detailed=detailed, trace_ctx=trace_ctx)
        deadline = time.monotonic() + timeout_s
        try:
            while True:
                try:
                    item = req.out.get(timeout=0.2)
                except queue.Empty:
                    if self._dead is not None:
                        raise EngineDeadError(
                            f"engine step loop died: {self._dead!r}")
                    if time.monotonic() > deadline:
                        raise TimeoutError("generate_sync timed out")
                    continue
                if item is _DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            self.cancel(req)

    # ------------------------------------------- disagg hand-off API
    def prefill_export(self, prompt_ids: Sequence[int],
                       trace_ctx: Optional[Dict[str, Any]] = None,
                       timeout_s: float = 120.0) -> Dict[str, Any]:
        """Run a prompt through chunked prefill and return the hand-off
        payload (prompt + first token + packed KV slab) instead of
        decoding — the prefill half of the disaggregated pipeline.
        Blocking; see :class:`LLMServer.prefill_export` for the actor
        wrapper."""
        req = self.submit(prompt_ids, max_new_tokens=1,
                          trace_ctx=trace_ctx, _export=True)
        deadline = time.monotonic() + timeout_s
        try:
            while True:
                try:
                    item = req.out.get(timeout=0.2)
                except queue.Empty:
                    if self._dead is not None:
                        raise EngineDeadError(
                            f"engine step loop died: {self._dead!r}")
                    if time.monotonic() > deadline:
                        raise TimeoutError("prefill_export timed out")
                    continue
                if isinstance(item, BaseException):
                    raise item
                if isinstance(item, dict):
                    return item
                if item is _DONE:
                    raise EngineDeadError(
                        "prefill_export stream ended without a payload")
        finally:
            self.cancel(req)

    def submit_adopt(self, payload: Dict[str, Any],
                     max_new_tokens: Optional[int] = None,
                     eos_token_id: Optional[int] = None,
                     detailed: bool = False,
                     trace_ctx: Optional[Dict[str, Any]] = None
                     ) -> _Request:
        """Enqueue a shipped prefill payload for adoption + decode —
        the decode half of the disaggregated pipeline. The returned
        request streams exactly what a colocated ``submit`` of the same
        prompt would have streamed (first token included)."""
        if int(payload.get("block_size", 0)) != self.config.kv_block_size:
            raise ValueError(
                f"shipped block_size {payload.get('block_size')} != "
                f"engine kv_block_size {self.config.kv_block_size}")
        return self.submit(payload["prompt"], max_new_tokens,
                           eos_token_id, detailed=detailed,
                           trace_ctx=trace_ctx, _adopt=payload)

    # --------------------------------------- warm-prefix migration API
    def export_warm_prefixes(self, min_hits: int = 1,
                             max_blocks: int = 0
                             ) -> Optional[Dict[str, Any]]:
        """Package this engine's warm ref-0 radix-trie chains (hits >=
        ``min_hits``) for migration to a surviving replica — the
        drain-path rescue of a trie that would otherwise die with this
        process. Runs on the step thread. Returns None when there is
        nothing worth shipping."""
        ec = self.config
        bs = ec.kv_block_size
        np, jnp = self._np, self._jnp

        def _do():
            with self._lock:
                chains = self._pool.export_chains(
                    min_hits, max_blocks) \
                    if ec.enable_prefix_sharing else []
                # chains share root prefixes: ship each block once
                slab_idx: Dict[int, int] = {}
                entries: List[tuple] = []   # (chunk tokens, block id)
                for chain in chains:
                    for key, blk in chain:
                        if blk not in slab_idx:
                            slab_idx[blk] = len(entries)
                            entries.append((key, blk))
            if not entries:
                return None
            T = ec.blocks_per_seq
            ks, vs = [], []
            for i0 in range(0, len(entries), T):
                grp = entries[i0:i0 + T]
                ids = np.zeros((T,), np.int32)
                ids[:len(grp)] = [b for _, b in grp]
                k, v = self._jit_gather(self._cache, jnp.asarray(ids))
                ks.append(np.asarray(k)[:, :len(grp)])
                vs.append(np.asarray(v)[:, :len(grp)])
            from ray_tpu.serve.disagg import pack_kv_blocks
            kv = pack_kv_blocks(np.concatenate(ks, axis=1),
                                np.concatenate(vs, axis=1), ec.kv_wire)
            payload = {
                "chains": [[(list(key), slab_idx[blk])
                            for key, blk in chain] for chain in chains],
                "kv": kv,
                "n_blocks": len(entries),
                "block_size": bs,
                "wire": ec.kv_wire,
                "wire_bytes": kv["wire_bytes"],
                "src": self.replica_tag,
            }
            if self._metrics is not None:
                try:
                    self._metrics.serve_prefix_migrated.inc(
                        len(entries), tags={"dir": "export"})
                except Exception:
                    pass
            if self._recorder is not None:
                try:
                    self._recorder.record(
                        "PREFIX_MIGRATE", replica=self.replica_tag,
                        dir="export", blocks=len(entries),
                        chains=len(chains))
                except Exception:
                    pass
            return payload

        return self._run_on_step_thread(_do)

    def import_warm_prefixes(self, payload: Dict[str, Any]) -> int:
        """Adopt a migrated warm-prefix payload into this engine's pool
        + radix trie (ref-0 cached blocks, evictable like any local
        cache). Opportunistic by design: chunks already held locally
        are skipped, and import stops at pool pressure rather than
        evicting this replica's own warm cache — migrated cold blocks
        must never displace proven-hot local ones. Runs on the step
        thread; returns the number of blocks adopted."""
        if payload is None:
            return 0
        if int(payload.get("block_size", 0)) != self.config.kv_block_size:
            raise ValueError(
                f"migrated block_size {payload.get('block_size')} != "
                f"engine kv_block_size {self.config.kv_block_size}")
        ec = self.config
        np, jnp = self._np, self._jnp

        def _do():
            from ray_tpu.serve.disagg import unpack_kv_blocks
            k_slab, v_slab = unpack_kv_blocks(
                payload["kv"], dtype=self._cache["k"].dtype)
            plan: List[tuple] = []     # (slab index, local block id)
            with self._lock:
                if not ec.enable_prefix_sharing:
                    return 0
                pool = self._pool
                for chain in payload["chains"]:
                    node = pool._root
                    for key, idx in chain:
                        key = tuple(int(t) for t in key)
                        child = node.children.get(key)
                        if child is not None and not child.detached:
                            node = child
                            continue
                        # pressure guard: free-list only — migration
                        # never evicts local warm cache, and never
                        # recycles a block another import just planned
                        if not pool._free:
                            node = None
                            break
                        blk = pool.allocate(1)[0]
                        nnode, inserted = pool.insert_child(
                            node, key, blk)
                        if not inserted:
                            pool.release([blk])
                            node = nnode
                            if node is None:
                                break
                            continue
                        plan.append((idx, blk))
                        pool.decref(blk)   # ref-0, trie-resident
                        node = nnode
                    # chain truncated: deeper chunks need their parent
            if not plan:
                return 0
            T = ec.blocks_per_seq
            shp = self._cache["k"].shape
            for i0 in range(0, len(plan), T):
                grp = plan[i0:i0 + T]
                ids = np.zeros((T,), np.int32)
                ids[:len(grp)] = [b for _, b in grp]
                k_pad = np.zeros((shp[0], T) + shp[2:], k_slab.dtype)
                v_pad = np.zeros_like(k_pad)
                for j, (idx, _) in enumerate(grp):
                    k_pad[:, j] = k_slab[:, idx]
                    v_pad[:, j] = v_slab[:, idx]
                self._cache = self._jit_scatter(
                    self._cache, jnp.asarray(ids), jnp.asarray(k_pad),
                    jnp.asarray(v_pad))
            self._jax.block_until_ready(self._cache["k"])
            if self._metrics is not None:
                try:
                    self._metrics.serve_prefix_migrated.inc(
                        len(plan), tags={"dir": "import"})
                except Exception:
                    pass
            if self._recorder is not None:
                try:
                    self._recorder.record(
                        "PREFIX_MIGRATE", replica=self.replica_tag,
                        dir="import", blocks=len(plan),
                        chains=len(payload["chains"]))
                except Exception:
                    pass
            return len(plan)

        return self._run_on_step_thread(_do)

    def warmup(self, timeout_s: float = 600.0) -> None:
        """Compile every jitted program (one tiny end-to-end generate)
        and reset the session counters it skewed: the TTFT EWMA would
        otherwise carry the compile wall into the gauge router's
        scoring and starve a freshly-scaled-up replica of traffic."""
        req = self.submit([2, 3], 2, _warmup=True)
        deadline = time.monotonic() + timeout_s
        try:
            while True:
                try:
                    item = req.out.get(timeout=0.2)
                except queue.Empty:
                    if self._dead is not None:
                        raise EngineDeadError(
                            f"engine step loop died: {self._dead!r}")
                    if time.monotonic() > deadline:
                        raise TimeoutError("warmup timed out")
                    continue
                if item is _DONE:
                    break
                if isinstance(item, BaseException):
                    raise item
        finally:
            self.cancel(req)
        with self._lock:
            self._ttft_ewma = None
            self._t_start = time.monotonic()
            self._tokens_total = 0
            self._decode_steps = 0
            self._prefill_chunks = 0
            self._decode_wall_s = self._prefill_wall_s = 0.0
            self._decode_pages_live = self._decode_pages_window = 0
            self._prompt_blocks_total = 0
            self._occupancy.clear()

    def stats(self) -> Dict[str, Any]:
        """Scheduler counters (the autoscaling signal surface): queue
        depth, batch occupancy histogram, tokens/s, leak-check views of
        the slot/block free lists."""
        with self._lock:
            elapsed = max(time.monotonic() - self._t_start, 1e-9)
            ps = self._pool.stats()
            hit_rate = (round(ps["hits_total"]
                              / self._prompt_blocks_total, 4)
                        if self._prompt_blocks_total else None)
            out = {
                "queue_depth": len(self._pending),
                "prefilling": len(self._prefilling),
                "active_slots": sum(1 for r in self._slots
                                    if r is not None),
                "free_slots": len(self._free_slots),
                # reclaimable = free list + ref-0 trie-cached blocks:
                # the leak-check view (cached blocks are warm cache,
                # not leaks — eviction reclaims them on demand)
                "free_blocks": ps["reclaimable"],
                "blocks_cached": ps["cached"],
                "blocks_shared": ps["shared"],
                "total_blocks": self.config.resolved_num_blocks - 1,
                "prefix_hit_blocks_total": ps["hits_total"],
                "prompt_blocks_total": self._prompt_blocks_total,
                "prefix_hit_rate": hit_rate,
                "prefix_evictions_total": ps["evictions_total"],
                "cow_copies_total": self._cow_copies,
                "tokens_total": self._tokens_total,
                "tokens_per_s": round(self._tokens_total / elapsed, 2),
                "decode_steps": self._decode_steps,
                "prefill_chunks": self._prefill_chunks,
                # device-wall split + length-aware work fraction (the
                # paged-kernel bench legs and perf gate read these)
                "decode_wall_s": round(self._decode_wall_s, 4),
                "prefill_wall_s": round(self._prefill_wall_s, 4),
                "decode_pages_live": self._decode_pages_live,
                "decode_pages_window": self._decode_pages_window,
                "decode_block_work_frac": (
                    round(self._decode_pages_live
                          / self._decode_pages_window, 4)
                    if self._decode_pages_window else None),
                "kv_block_size": self.config.kv_block_size,
                "paged_impl": getattr(self.model_config, "paged_impl",
                                      "auto"),
                # trie-root fingerprints: the router's prefix-aware
                # COLD-session placement signal (first-turn requests
                # land where their system prompt's KV already lives)
                "prefix_fingerprints": (
                    self._pool.root_fingerprints()
                    if self.config.enable_prefix_sharing else []),
                "occupancy_hist": dict(self._occupancy),
                "ttft_ewma_s": (round(self._ttft_ewma, 6)
                                if self._ttft_ewma is not None else None),
                # router-enqueue -> engine-admission wait (EWMA): the
                # component that, added to the engine-scoped TTFT,
                # gives the full user-facing TTFT the serve_ttft
                # histogram and the request waterfalls report
                "queue_wait_ewma_s": (
                    round(self._queue_wait_ewma, 6)
                    if self._queue_wait_ewma is not None else None),
                # in-flight weight refresh accounting (RLHF rollout
                # backend): swaps are pointer flips between decode
                # steps, so sync_stall_s — decode time lost waiting on
                # a refresh — must stay 0.0 (the bench gates on it)
                # disagg hand-off accounting: exports tick on the
                # prefill fleet, adopts (+ ship wall measured
                # ship_ts -> adoption-complete) on the decode fleet
                "kv_exports": self._kv_exports,
                "kv_export_bytes": self._kv_export_bytes,
                "kv_adopts": self._kv_adopts,
                "kv_adopt_bytes": self._kv_adopt_bytes,
                "kv_adopt_blocks": self._kv_adopt_blocks,
                "kv_ship_wall_s": round(self._kv_ship_wall_s, 4),
                "weight_version": self._weight_version,
                "weight_swaps": self._weight_swaps,
                "weight_swap_wall_s": round(self._weight_swap_wall_s,
                                            6),
                "sync_stall_s": round(self._sync_stall_s, 6),
                "dead": repr(self._dead) if self._dead else None,
            }
            if self.config.spec_tokens > 0:
                out["spec"] = {
                    "drafted": self._spec_drafted,
                    "accepted": self._spec_accepted,
                    "acceptance_rate": (
                        round(self._spec_accepted / self._spec_drafted,
                              4) if self._spec_drafted else None),
                    "disables": self._spec_disables,
                }
            return out

    def pool_audit(self) -> List[str]:
        """Block-accounting integrity check (leak regression tests):
        empty list = every refcounted block is exactly one of
        free/active/cached and the trie holds no dangling entries."""
        with self._lock:
            return self._pool.audit()

    def shutdown(self) -> None:
        with self._work:
            self._stop = True
            self._work.notify_all()
        self._thread.join(timeout=10)
        self._poll_pool.shutdown(wait=False)

    # -------------------------------------------------------- step loop
    def _run(self) -> None:
        try:
            while True:
                with self._work:
                    while not self._stop and not self._has_work_locked():
                        self._work.wait(timeout=0.5)
                    if self._stop:
                        break
                self._step()
        except BaseException as e:  # noqa: BLE001 — fail typed, never hang
            self._on_dead(e)

    def _has_work_locked(self) -> bool:
        return bool(self._pending) or bool(self._prefilling) \
            or bool(self._ops) \
            or self._staged_weights is not None \
            or any(r is not None for r in self._slots)

    def _on_dead(self, e: BaseException) -> None:
        with self._work:
            self._dead = e
            reqs = [r for r in self._slots if r is not None]
            reqs += list(self._prefilling) + list(self._pending)
            self._pending.clear()
            self._prefilling.clear()
            ops = list(self._ops)
            self._ops.clear()
        err = EngineDeadError(f"engine step loop died: {e!r}")
        err.__cause__ = e
        for r in set(reqs):
            self._close_trace(r, err)
            r.out.put(err)
        for op in ops:                 # never strand an op waiter
            op["box"]["e"] = err
            op["done"].set()

    def _run_on_step_thread(self, fn, timeout_s: float = 30.0):
        """Run ``fn`` on the step thread (the donated caches' only
        owner) at the next step boundary and return its result. The
        warm-prefix migration paths use this so their gathers/scatters
        can never interleave with an in-flight donated-cache update."""
        op = {"fn": fn, "done": threading.Event(), "box": {}}
        with self._work:
            if self._dead is not None:
                raise EngineDeadError(
                    f"engine step loop died: {self._dead!r}")
            self._ops.append(op)
            self._work.notify_all()
        if not op["done"].wait(timeout_s):
            raise TimeoutError("engine step-thread op timed out")
        if "e" in op["box"]:
            raise op["box"]["e"]
        return op["box"].get("r")

    def _drain_ops(self) -> None:
        while True:
            with self._lock:
                op = self._ops.popleft() if self._ops else None
            if op is None:
                return
            try:
                op["box"]["r"] = op["fn"]()
            except BaseException as e:  # noqa: BLE001 — typed to waiter
                op["box"]["e"] = e
            finally:
                op["done"].set()

    # one engine step: drain posted ops -> swap staged weights -> reap
    # -> admit -> one prefill chunk -> one decode
    def _step(self) -> None:
        self._drain_ops()
        self._maybe_swap_weights()
        self._reap_cancelled()
        self._admit()
        self._prefill_one_chunk()
        self._decode_once()
        self._emit_stats()

    def _maybe_swap_weights(self) -> None:
        """Apply a staged weight refresh between decode steps: a pure
        pointer swap on the step thread (the only device-state owner),
        so in-flight decode slots are never drained and no request
        waits. The swap wall is the full cost of the refresh as seen by
        decode — booked separately from _sync_stall_s, which stays 0
        because no slot ever blocks on it."""
        with self._lock:
            staged = self._staged_weights
            if staged is None:
                return
            self._staged_weights = None
            active_reqs = [r for r in self._slots if r is not None]
            active = len(active_reqs)
        t0w = time.time()
        t0 = time.monotonic()
        params, version = staged
        self._params = params
        swap_s = time.monotonic() - t0
        now_w = time.time()
        for r in active_reqs:
            # the swap overlapped these requests' decode: annotate each
            # waterfall with the version boundary it decoded across
            if r.trace is not None:
                r.trace.span(RT.WEIGHT_SWAP, t0w, now_w,
                             version=version)
        with self._lock:
            self._weight_version = version
            self._weight_swaps += 1
            self._weight_swap_wall_s += swap_s
        if self._recorder is not None:
            try:
                self._recorder.record(
                    "RLHF_SYNC", replica=self.replica_tag,
                    version=version, swap_s=round(swap_s, 6),
                    active_slots=active)
            except Exception:
                pass

    def _reap_cancelled(self) -> None:
        with self._lock:
            for req in list(self._prefilling):
                if req.cancelled:
                    self._prefilling.remove(req)
                    self._release_locked(req)
            for req in list(self._pending):
                if req.cancelled:
                    self._pending.remove(req)
                    self._close_trace(req)
                    req.out.put(_DONE)
            for req in self._slots:
                if req is not None and req.cancelled:
                    self._release_locked(req)

    def _admit(self) -> None:
        ec = self.config
        bs = ec.kv_block_size
        while True:
            with self._lock:
                if not self._pending or not self._free_slots:
                    return
                head_adopt = self._pending[0].adopt is not None
            if head_adopt:
                # disagg adoption: shipped KV blocks, no prefill
                if not self._admit_adopt(self._pending[0]):
                    return          # pool pressure: wait for blocks
                continue
            with self._lock:
                if not self._pending or not self._free_slots:
                    return
                req = self._pending[0]
                plen = len(req.prompt)
                need = -(-min(plen + req.max_new_tokens,
                              ec.max_seq_len) // bs)
                # -- radix prefix match: matched full blocks are shared
                # (incref'd) and skip prefill entirely; a fully-matched
                # block-aligned prompt keeps its LAST matched block as a
                # copy-on-write source so the final token still runs
                # through prefill for its logits
                matched: List[int] = []
                mtok = 0
                cow_src = None
                if ec.enable_prefix_sharing:
                    matched, mtok, req.trie_node = \
                        self._pool.match_prefix(req.prompt)
                    if mtok == plen and matched:
                        cow_src = matched.pop()
                        mtok -= bs
                n_priv = need - len(matched) - (1 if cow_src is not None
                                                else 0)
                priv = self._pool.allocate(n_priv)
                if priv is None:
                    # full occupancy: release the match and WAIT for
                    # blocks (shapes are fixed; admission pressure
                    # never grows the compiled batch)
                    self._pool.release(matched)
                    if cow_src is not None:
                        self._pool.release([cow_src])
                    req.trie_node = None
                    return
                cow_dst = None
                if cow_src is not None:
                    cow_dst = priv[0]
                    priv = priv[1:]
                    self._cow_copies += 1
                req.blocks = matched + \
                    ([cow_dst] if cow_dst is not None else []) + priv
                req.hit_blocks = len(matched) + \
                    (1 if cow_src is not None else 0)
                self._pool.count_hits(req.hit_blocks)
                req.trie_cursor = req.hit_blocks
                req.prefill_pos = (plen - 1) if cow_src is not None \
                    else mtok
                self._prompt_blocks_total += -(-plen // bs)
                self._pending.popleft()
                req.slot = self._free_slots.pop()
                self._block_tables[req.slot, :] = 0
                self._block_tables[req.slot, :len(req.blocks)] = \
                    req.blocks
                self._seq_lens[req.slot] = 0
                req.state = _PREFILL
                self._slots[req.slot] = req
                self._prefilling.append(req)
                if req.hit_blocks and self._metrics is not None:
                    try:
                        self._metrics.serve_prefix_hits.inc(
                            req.hit_blocks)
                    except Exception:
                        pass
                if req.trace is not None:
                    now = time.time()
                    req.queue_wait_s = max(
                        0.0, now - req.t_enqueue_wall)
                    req.trace.span(RT.QUEUED, req.t_enqueue_wall, now)
                    req.trace.span(RT.ADMITTED, now, None,
                                   slot=req.slot,
                                   hit_blocks=req.hit_blocks,
                                   prefix_tokens=mtok,
                                   cow=cow_src is not None)
                    self._slo.observe_queue(req.trace,
                                            req.queue_wait_s)
            # device-side CoW copy OUTSIDE the lock (the step thread is
            # the only device user; submit/cancel stay responsive)
            if cow_src is not None:
                self._cache = self._jit_copy(
                    self._cache, self._np.int32(cow_src),
                    self._np.int32(cow_dst))
                with self._lock:
                    self._pool.release([cow_src])

    # --------------------------------------------- disagg adopt / export
    def _admit_adopt(self, req: _Request) -> bool:
        """Admit a disagg hand-off: slot + blocks like a normal request,
        but the prompt's KV arrives in the shipped slab instead of via
        prefill. Blocks the local radix trie already holds are reused
        (their slab copy is skipped — the bytes were shipped but the
        scatter isn't repeated); the rest are scattered into the pool,
        then every full prompt chunk is trie-indexed so the shipped
        prefix is warm here from now on. Never copy-on-write: the first
        token came with the payload, so a fully block-aligned matched
        prompt just starts decode in a fresh private block. Returns
        False — with nothing taken — on pool pressure (admission wait).
        """
        np = self._np
        ec = self.config
        bs = ec.kv_block_size
        payload = req.adopt
        t0w = time.time()
        with self._lock:
            if not self._free_slots:
                return False
            plen = len(req.prompt)
            n_ship = min(int(payload["n_blocks"]), -(-plen // bs))
            need = -(-min(plen + req.max_new_tokens,
                          ec.max_seq_len) // bs)
            matched: List[int] = []
            mtok = 0
            if ec.enable_prefix_sharing:
                matched, mtok, req.trie_node = \
                    self._pool.match_prefix(req.prompt)
            priv = self._pool.allocate(need - len(matched))
            if priv is None:
                self._pool.release(matched)
                req.trie_node = None
                return False
            req.blocks = matched + priv
            req.hit_blocks = len(matched)
            self._pool.count_hits(req.hit_blocks)
            req.trie_cursor = req.hit_blocks
            req.prefill_pos = plen
            self._prompt_blocks_total += -(-plen // bs)
            self._pending.popleft()
            req.slot = self._free_slots.pop()
            self._block_tables[req.slot, :] = 0
            self._block_tables[req.slot, :len(req.blocks)] = req.blocks
            self._seq_lens[req.slot] = 0
            req.state = _PREFILL
            self._slots[req.slot] = req
            if req.hit_blocks and self._metrics is not None:
                try:
                    self._metrics.serve_prefix_hits.inc(req.hit_blocks)
                except Exception:
                    pass
            if req.trace is not None:
                now = time.time()
                req.queue_wait_s = max(0.0, now - req.t_enqueue_wall)
                req.trace.span(RT.QUEUED, req.t_enqueue_wall, now)
                req.trace.span(RT.ADMITTED, now, None, slot=req.slot,
                               hit_blocks=req.hit_blocks,
                               prefix_tokens=mtok, adopt=True)
                self._slo.observe_queue(req.trace, req.queue_wait_s)
            # physical destinations for the slab blocks the local trie
            # did NOT already hold
            dst = req.blocks[req.hit_blocks:n_ship]
        # scatter OUTSIDE the lock (step thread owns the device)
        if dst:
            from ray_tpu.serve.disagg import unpack_kv_blocks
            k_slab, v_slab = unpack_kv_blocks(
                payload["kv"], dtype=self._cache["k"].dtype)
            T = ec.blocks_per_seq
            ids = np.zeros((T,), np.int32)
            ids[:len(dst)] = dst
            shp = self._cache["k"].shape
            k_pad = np.zeros((shp[0], T) + shp[2:], k_slab.dtype)
            v_pad = np.zeros_like(k_pad)
            k_pad[:, :len(dst)] = k_slab[:, req.hit_blocks:n_ship]
            v_pad[:, :len(dst)] = v_slab[:, req.hit_blocks:n_ship]
            jnp = self._jnp
            self._cache = self._jit_scatter(
                self._cache, jnp.asarray(ids), jnp.asarray(k_pad),
                jnp.asarray(v_pad))
            self._jax.block_until_ready(self._cache["k"])
        t1w = time.time()
        first = int(payload["first"])
        req.seq_len = plen
        req.t_first_token = time.monotonic()
        ship_ts = min(float(payload.get("ship_ts") or t0w), t0w)
        wire = payload.get("wire", ec.kv_wire)
        if req.trace is not None:
            req.trace.span(RT.KV_SHIP, ship_ts, t0w,
                           bytes=payload.get("wire_bytes"), wire=wire,
                           src=payload.get("src"))
            req.trace.span(RT.KV_ADOPT, t0w, t1w,
                           blocks=len(dst), reused=req.hit_blocks,
                           bytes=payload.get("wire_bytes"), wire=wire)
        self._kv_adopts += 1
        self._kv_adopt_bytes += int(payload.get("wire_bytes") or 0)
        self._kv_adopt_blocks += len(dst)
        self._kv_ship_wall_s += max(0.0, t1w - ship_ts)
        if self._metrics is not None:
            try:
                self._metrics.serve_kv_ship_seconds.observe(
                    max(0.0, t1w - ship_ts))
            except Exception:
                pass
        if self._recorder is not None:
            try:
                self._recorder.record(
                    "KV_ADOPT", replica=self.replica_tag,
                    blocks=len(dst), reused=req.hit_blocks,
                    dur_s=round(t1w - t0w, 6))
            except Exception:
                pass
        self._record_ttft(req)
        with self._lock:
            # trie-index every full prompt chunk: the shipped prefix
            # is warm on THIS replica for later requests
            if req.trie_node is not None:
                while req.trie_node is not None and \
                        req.trie_cursor < plen // bs:
                    i = req.trie_cursor
                    node, _ = self._pool.insert_child(
                        req.trie_node, req.prompt[i * bs:(i + 1) * bs],
                        req.blocks[i])
                    req.trie_node = node
                    req.trie_cursor += 1
            if req.cancelled:
                self._release_locked(req)
                return True
            if req.eos_token_id is not None \
                    and first == req.eos_token_id:
                self._release_locked(req)
                return True
            req.generated = 1
            req.out.put(self._item(req, first,
                                   payload.get("first_lp")))
            req.history.append(first)
            self._tokens_total += 1
            if req.generated >= req.max_new_tokens:
                self._release_locked(req)
                return True
            req.state = _DECODE
            self._last_tok[req.slot] = first
            self._seq_lens[req.slot] = req.seq_len
        return True

    def _finish_export(self, req: _Request, first: int, lp) -> None:
        """Terminal step of a prefill-export request: gather the
        prompt's finished KV blocks into one contiguous slab, pack it
        for the wire, hand the payload to the waiting ``prefill_export``
        call, and free the slot — this engine never decodes it."""
        np = self._np
        ec = self.config
        bs = ec.kv_block_size
        plen = len(req.prompt)
        n_ship = -(-plen // bs)
        t0w = time.time()
        with self._lock:
            self._prefilling.popleft()
            if req.cancelled:
                self._release_locked(req)
                return
            ids = np.zeros((ec.blocks_per_seq,), np.int32)
            ids[:n_ship] = req.blocks[:n_ship]
        k_slab, v_slab = self._jit_gather(self._cache,
                                          self._jnp.asarray(ids))
        k_np = np.asarray(k_slab)[:, :n_ship]
        v_np = np.asarray(v_slab)[:, :n_ship]
        from ray_tpu.serve.disagg import pack_kv_blocks
        kv = pack_kv_blocks(k_np, v_np, ec.kv_wire)
        payload = {
            "prompt": list(req.prompt),
            "first": int(first),
            "first_lp": None if lp is None else float(lp[0]),
            "kv": kv,
            "n_blocks": n_ship,
            "block_size": bs,
            "wire": ec.kv_wire,
            "wire_bytes": kv["wire_bytes"],
            "ship_ts": time.time(),
            "src": self.replica_tag,
        }
        t1w = time.time()
        self._kv_exports += 1
        self._kv_export_bytes += int(kv["wire_bytes"])
        if req.trace is not None:
            req.trace.span(RT.KV_SHIP, t0w, t1w,
                           bytes=kv["wire_bytes"], wire=ec.kv_wire,
                           blocks=n_ship, dir="export")
        if self._metrics is not None:
            try:
                self._metrics.serve_kv_ship_bytes.inc(
                    kv["wire_bytes"], tags={"wire": ec.kv_wire})
            except Exception:
                pass
        if self._recorder is not None:
            try:
                self._recorder.record(
                    "KV_SHIP", replica=self.replica_tag,
                    blocks=n_ship, bytes=kv["wire_bytes"],
                    wire=ec.kv_wire)
            except Exception:
                pass
        with self._lock:
            req.out.put(payload)
            self._release_locked(req)

    def _prefill_one_chunk(self) -> None:
        with self._lock:
            req = self._prefilling[0] if self._prefilling else None
        if req is None:
            return
        np, jnp = self._np, self._jnp
        ec = self.config
        C = ec.prefill_chunk
        start = req.prefill_pos
        n = min(C, len(req.prompt) - start)
        chunk = np.zeros((1, C), np.int32)
        chunk[0, :n] = req.prompt[start:start + n]
        t0w = time.time()
        t0 = time.monotonic()
        out = self._jit_prefill(
            self._params, jnp.asarray(chunk), self._cache,
            jnp.asarray(self._block_tables[req.slot:req.slot + 1]),
            jnp.full((1,), start, jnp.int32),
            jnp.full((1,), n, jnp.int32))
        if self.config.capture_logprobs:
            tok, lp, self._cache = out
        else:
            tok, self._cache = out
            lp = None
        self._jax.block_until_ready(tok)
        self._prefill_wall_s += time.monotonic() - t0
        req.prefill_pos += n
        self._prefill_chunks += 1
        if req.trace is not None:
            req.trace.span(RT.PREFILL, t0w, time.time(),
                           pos=start, tokens=n)
        # index newly-completed FULL prompt blocks in the radix trie so
        # concurrent/later requests with the same prefix share them; a
        # lost insert race (same chunk path already indexed) keeps our
        # block private and just deepens along the existing path
        if req.trie_node is not None:
            with self._lock:
                while req.trie_node is not None and \
                        (req.trie_cursor + 1) * ec.kv_block_size \
                        <= req.prefill_pos:
                    i = req.trie_cursor
                    chunk = req.prompt[i * ec.kv_block_size:
                                       (i + 1) * ec.kv_block_size]
                    node, _ = self._pool.insert_child(
                        req.trie_node, chunk, req.blocks[i])
                    req.trie_node = node   # None = parent evicted: stop
                    req.trie_cursor += 1
        if req.prefill_pos < len(req.prompt):
            return
        # prompt fully cached: the final chunk's last logits give the
        # first generated token — TTFT stops here
        first = int(tok[0])
        if req.export:
            # disagg prefill replica: ship the finished blocks instead
            # of decoding (user-facing TTFT is the decode side's)
            self._finish_export(req, first, lp)
            return
        req.seq_len = len(req.prompt)
        req.t_first_token = time.monotonic()
        self._record_ttft(req)
        with self._lock:
            self._prefilling.popleft()
            if req.cancelled:
                self._release_locked(req)
                return
            if req.eos_token_id is not None and first == req.eos_token_id:
                self._release_locked(req)
                return
            req.generated = 1
            req.out.put(self._item(req, first,
                                   None if lp is None
                                   else float(lp[0])))
            req.history.append(first)
            self._tokens_total += 1
            if req.generated >= req.max_new_tokens:
                self._release_locked(req)
                return
            req.state = _DECODE
            self._last_tok[req.slot] = first
            self._seq_lens[req.slot] = req.seq_len

    def _account_decode_pages(self, live_lens) -> None:
        """Book one decode step's length-aware work: pages the paged
        kernel touches (``max(ceil(live/bs), 1)`` per slot — idle slots
        run their one trash page) vs the full table window the XLA
        reference gathers. Host-side numpy over the slot arrays the
        step already copied — no device work."""
        from ray_tpu.ops.paged_flash import paged_work_pages
        ec = self.config
        pages = paged_work_pages(
            self._np.asarray(live_lens, self._np.int64),
            ec.kv_block_size)
        self._decode_pages_live += int(pages.sum())
        self._decode_pages_window += ec.decode_slots * ec.blocks_per_seq

    def _decode_once(self) -> None:
        if self.config.spec_tokens > 0:
            self._decode_speculative()
            return
        with self._lock:
            active = [r for r in self._slots
                      if r is not None and r.state == _DECODE]
            if not active:
                return
            self._decode_steps += 1
            self._occupancy[len(active)] += 1
            if self._metrics is not None:
                try:
                    self._metrics.serve_batch_occupancy.observe(
                        len(active))
                except Exception:
                    pass
            toks = self._last_tok.copy()
            lens = self._seq_lens.copy()
            bt = self._block_tables.copy()
        self._account_decode_pages(lens + 1)
        jnp = self._jnp
        t0 = time.monotonic()
        res = self._jit_decode(
            self._params, jnp.asarray(toks), self._cache,
            jnp.asarray(bt), jnp.asarray(lens))
        if self.config.capture_logprobs:
            out, lps, self._cache = res
            lps = self._np.asarray(lps)
        else:
            out, self._cache = res
            lps = None
        out = self._np.asarray(out)
        self._decode_wall_s += time.monotonic() - t0
        produced = 0
        now_w = time.time()
        with self._lock:
            for req in active:
                if req.cancelled or self._slots[req.slot] is not req:
                    continue
                tok = int(out[req.slot])
                req.seq_len += 1           # the token we just wrote
                self._seq_lens[req.slot] = req.seq_len
                if req.eos_token_id is not None \
                        and tok == req.eos_token_id:
                    self._release_locked(req)
                    continue
                req.generated += 1
                req.out.put(self._item(req, tok,
                                       None if lps is None
                                       else float(lps[req.slot])))
                req.history.append(tok)
                self._tokens_total += 1
                produced += 1
                self._trace_token(req, now_w)
                if req.generated >= req.max_new_tokens \
                        or req.seq_len + 1 >= self.config.max_seq_len:
                    self._release_locked(req)
                else:
                    self._last_tok[req.slot] = tok
        # decode tokens into the fleet counter (the first token per
        # request is counted by _record_ttft), so the plane's
        # rate(serve_engine_tokens_total) IS engine tokens/s
        if produced and self._metrics is not None:
            try:
                self._metrics.serve_tokens.inc(produced)
            except Exception:
                pass

    # ---------------------------------------------- speculative decode
    def _draft(self, req: _Request, n_draft: int) -> List[int]:
        """Prompt-lookup drafting: continuation of the most recent
        earlier occurrence of the sequence's own trailing n-gram
        (longest n first). No draft model, no device work — misses just
        return fewer (or no) drafts."""
        if n_draft <= 0:
            return []
        h = req.history
        for g in range(min(self.config.spec_ngram, len(h) - 1), 0, -1):
            pat = h[-g:]
            for i in range(len(h) - g - 1, -1, -1):
                if h[i:i + g] == pat:
                    return h[i + g:i + g + n_draft]
        return []

    def _decode_speculative(self) -> None:
        """One verify step over the slot array: each active slot
        processes [last_tok, draft_1..draft_d] at its next positions in
        ONE fixed-shape (S, k+1) call, then accepts the longest draft
        prefix matching the model's own argmax chain plus one bonus
        token. d=0 degenerates to exactly the classic decode step, so
        per-token output is bit-identical with speculation on or off.
        Rejected drafts leave stale writes only at positions beyond the
        accepted seq_len — never read (causal masking) and overwritten
        when real tokens reach them."""
        np = self._np
        ec = self.config
        L = ec.spec_tokens + 1
        S = ec.decode_slots
        bs = ec.kv_block_size
        with self._lock:
            active = [r for r in self._slots
                      if r is not None and r.state == _DECODE]
            if not active:
                return
            self._decode_steps += 1
            self._occupancy[len(active)] += 1
            if self._metrics is not None:
                try:
                    self._metrics.serve_batch_occupancy.observe(
                        len(active))
                except Exception:
                    pass
            toks = np.zeros((S, L), np.int32)
            lens = np.zeros((S,), np.int32)
            starts = np.zeros((S,), np.int32)
            drafts: Dict[int, List[int]] = {}
            for req in active:
                s = req.slot
                # cap drafts to the sequence's allocated block span so
                # speculative writes NEVER spill into the shared trash
                # block (concurrent slots' junk could corrupt verify)
                span = len(req.blocks) * bs
                budget = min(L, span - req.seq_len,
                             req.max_new_tokens - req.generated + 1)
                d = [] if req.spec_disabled else \
                    self._draft(req, max(0, budget - 1))
                toks[s, 0] = self._last_tok[s]
                if d:
                    toks[s, 1:1 + len(d)] = d
                lens[s] = 1 + len(d)
                starts[s] = req.seq_len
                drafts[s] = d
            bt = self._block_tables.copy()
        self._account_decode_pages(starts + lens)
        jnp = self._jnp
        t0w = time.time()
        t0 = time.monotonic()
        preds, self._cache = self._jit_verify(
            self._params, jnp.asarray(toks), self._cache,
            jnp.asarray(bt), jnp.asarray(starts), jnp.asarray(lens))
        preds = np.asarray(preds)
        self._decode_wall_s += time.monotonic() - t0
        produced = 0
        now_w = time.time()
        with self._lock:
            for req in active:
                if req.cancelled or self._slots[req.slot] is not req:
                    continue
                s = req.slot
                d = drafts[s]
                emitted = 0
                for j in range(len(d) + 1):
                    tok = int(preds[s, j])
                    req.seq_len += 1       # position j's token is real
                    self._seq_lens[s] = req.seq_len
                    if req.eos_token_id is not None \
                            and tok == req.eos_token_id:
                        self._release_locked(req)
                        break
                    req.generated += 1
                    req.out.put(self._item(req, tok, None))
                    req.history.append(tok)
                    self._tokens_total += 1
                    produced += 1
                    emitted += 1
                    self._trace_token(req, now_w)
                    if req.generated >= req.max_new_tokens \
                            or req.seq_len + 1 >= ec.max_seq_len:
                        self._release_locked(req)
                        break
                    self._last_tok[s] = tok
                    # continue into draft j+1 only if draft j was what
                    # the model itself predicted (cache entry correct)
                    if j >= len(d) or d[j] != tok:
                        break
                if d:
                    accepted = max(0, emitted - 1)
                    self._spec_drafted += len(d)
                    self._spec_accepted += accepted
                    if req.trace is not None:
                        req.trace.span(RT.SPEC_VERIFY, t0w, now_w,
                                       drafted=len(d),
                                       accepted=accepted)
                    ratio = accepted / len(d)
                    req.spec_ewma = ratio if req.spec_ewma is None \
                        else 0.8 * req.spec_ewma + 0.2 * ratio
                    if req.spec_ewma < ec.spec_min_acceptance \
                            and not req.spec_disabled:
                        req.spec_disabled = True
                        self._spec_disables += 1
                    if self._metrics is not None:
                        try:
                            self._metrics.serve_spec_accept.observe(
                                ratio)
                        except Exception:
                            pass
        if produced and self._metrics is not None:
            try:
                self._metrics.serve_tokens.inc(produced)
            except Exception:
                pass

    def _trace_token(self, req: _Request, now_w: float) -> None:
        """Book one emitted decode token into the request's trace:
        inter-token gap to the SLO watchdog, and a DECODE span every
        ``trace_decode_tick`` tokens (bounding span count for long
        generations). Speculative bursts emit several tokens at one
        wall instant — the intra-burst gaps are genuinely ~0, which is
        exactly what the user-perceived stream looks like."""
        tr = req.trace
        if tr is None:
            return
        last = req.last_tok_wall
        req.last_tok_wall = now_w
        if last is not None:
            self._slo.observe_gap(tr, max(0.0, now_w - last))
        if req.tick_t0 is None:
            req.tick_t0 = last if last is not None else now_w
        req.tick_toks += 1
        if req.tick_toks >= self.config.trace_decode_tick:
            tr.span(RT.DECODE, req.tick_t0, now_w,
                    tokens=req.tick_toks)
            req.tick_t0, req.tick_toks = None, 0

    def _item(self, req: _Request, tok: int, logprob):
        """Shape one stream item: plain int for serving consumers,
        ``(token, policy_version, logprob)`` for ``detailed`` RLHF
        streams. Called from the step thread, where _weight_version is
        constant for the whole step — the stamp is exactly the policy
        that computed this token's logits."""
        if not req.detailed:
            return tok
        return (tok, self._weight_version, logprob)

    def _release_locked(self, req: _Request,
                        err: Optional[BaseException] = None) -> None:
        """Return a request's slot + blocks to the free lists and close
        its stream (call with self._lock held)."""
        if req.slot is not None and self._slots[req.slot] is req:
            self._slots[req.slot] = None
            self._block_tables[req.slot, :] = 0
            self._seq_lens[req.slot] = 0
            self._last_tok[req.slot] = 0
            self._free_slots.append(req.slot)
            # decref, not free: trie-indexed blocks stay warm for the
            # next request sharing this prefix (evicted LRU only under
            # pool pressure)
            self._pool.release(req.blocks)
            req.blocks = []
            req.slot = None
            req.trie_node = None
        req.state = _FINISHED
        self._close_trace(req, err)
        req.out.put(err if err is not None else _DONE)
        self._work.notify_all()

    def _close_trace(self, req: _Request,
                     err: Optional[BaseException] = None) -> None:
        """Terminal span + ship decision for one request's trace
        (exactly once — the trace is detached first). FAILED names the
        typed error; DONE carries the token count. Shipping is an
        out-queue put, so holding the engine lock here is fine."""
        tr = req.trace
        if tr is None:
            return
        req.trace = None
        now = time.time()
        if req.tick_toks and req.tick_t0 is not None:
            tr.span(RT.DECODE, req.tick_t0, now, tokens=req.tick_toks)
            req.tick_t0, req.tick_toks = None, 0
        if err is not None:
            tr.span(RT.FAILED, now, None,
                    error=type(err).__name__, detail=str(err)[:200])
        else:
            tr.span(RT.DONE, now, None, tokens=req.generated,
                    cancelled=bool(req.cancelled))
        if self._tracer is not None:
            self._tracer.finish(tr)

    # ------------------------------------------------ metrics / events
    def _record_ttft(self, req: _Request) -> None:
        if getattr(req, "warmup", False):
            # compile-only traffic: its TTFT is the jit wall, noise for
            # both the router's EWMA and the flight recorder
            return
        ttft = req.t_first_token - req.t_submit
        # full TTFT = router-enqueue -> first token: queue_wait_s is
        # the router-stamped component the engine never used to see.
        # The fleet histogram observes the FULL number so its quantiles
        # agree with the request waterfalls on what TTFT means; the
        # EWMA stays engine-scoped (it is the router's own-capacity
        # gauge — charging it the router's queueing would feed back).
        qw = getattr(req, "queue_wait_s", 0.0)
        t_enq = getattr(req, "t_enqueue_wall", 0.0)
        full = max(ttft, time.time() - t_enq) if t_enq else ttft
        self._ttft_ewma = ttft if self._ttft_ewma is None \
            else 0.8 * self._ttft_ewma + 0.2 * ttft
        qw_ewma = getattr(self, "_queue_wait_ewma", None)
        self._queue_wait_ewma = qw if qw_ewma is None \
            else 0.8 * qw_ewma + 0.2 * qw
        if self._metrics is not None:
            try:
                self._metrics.serve_ttft.observe(full)
                self._metrics.serve_tokens.inc()
            except Exception:
                pass
        trace = getattr(req, "trace", None)
        if self._recorder is not None:
            try:
                self._recorder.record(
                    "ENGINE_TTFT", replica=self.replica_tag,
                    rid=req.rid, ttft_s=round(ttft, 6),
                    queue_wait_s=round(qw, 6),
                    prompt_len=len(req.prompt),
                    request_id=(trace.request_id
                                if trace is not None else None))
            except Exception:
                pass
        if trace is not None:
            now = time.time()
            req.last_tok_wall = now     # inter-token gap baseline
            trace.event(RT.FIRST_TOKEN, now,
                        ttft_s=round(full, 6),
                        engine_ttft_s=round(ttft, 6),
                        queue_wait_s=round(qw, 6))
            self._slo.observe_ttft(trace, full)

    def _emit_stats(self, interval_s: float = 0.5) -> None:
        now = time.monotonic()
        if now - self._last_stats_emit < interval_s:
            return
        self._last_stats_emit = now
        s = self.stats()
        if self._metrics is not None:
            try:
                self._metrics.serve_queue_depth.set(s["queue_depth"])
                self._metrics.serve_tokens_per_s.set(s["tokens_per_s"])
                self._metrics.serve_blocks_shared.set(
                    s["blocks_shared"])
            except Exception:
                pass
        if self._recorder is not None:
            try:
                self._recorder.record(
                    "ENGINE_STATS", replica=self.replica_tag,
                    queue_depth=s["queue_depth"],
                    active=s["active_slots"],
                    tokens_per_s=s["tokens_per_s"],
                    free_blocks=s["free_blocks"])
                self._recorder.maybe_flush()
            except Exception:
                pass
        # a replica decoding flat-out may never hit the worker idle
        # loop: the stats cadence doubles as the fleet-report heartbeat
        try:
            from ray_tpu.core.global_state import try_global_worker
            w = try_global_worker()
            if w is not None and getattr(w, "metrics_reporter",
                                         None) is not None:
                w.metrics_reporter.maybe_report()
        except Exception:
            pass


def _resolve_dtype(name):
    import jax.numpy as jnp
    if not isinstance(name, str):
        return name
    return {"float32": jnp.float32, "f32": jnp.float32,
            "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
            "float16": jnp.float16}[name]


class LLMServer:
    """Deployment-facing engine wrapper. Construct with plain dicts so
    the deployment graph ships cheaply to the replica actor::

        app = serve.deployment(LLMServer).bind(
            model={"d_model": 256, "n_layers": 4, ...},
            engine={"decode_slots": 8, "kv_block_size": 16})
        h = serve.run(app)
        for tok in h.options(stream=True).generate.remote([1, 2, 3]):
            ...

    ``generate`` is an async generator, so each token rides the core
    streaming-generator machinery (per-item objects, backpressure,
    typed failure on replica death).
    """

    def __init__(self, model: Optional[Dict[str, Any]] = None,
                 engine: Optional[Dict[str, Any]] = None,
                 seed: int = 0, warmup: bool = True):
        from ray_tpu.models import TransformerConfig
        model = dict(model or {})
        if "dtype" in model:
            model["dtype"] = _resolve_dtype(model["dtype"])
        model.setdefault("dtype", _resolve_dtype("float32"))
        self.model_config = TransformerConfig(**model)
        self.engine_config = EngineConfig(**(engine or {}))
        self.engine = LLMEngine(self.model_config, self.engine_config,
                                seed=seed,
                                replica_tag=f"pid:{os.getpid()}")
        if warmup:
            # compile prefill + decode BEFORE the replica enters
            # rotation: actor calls queue behind __init__, so a
            # replica the autoscaler adds mid-load serves its first
            # request hot instead of charging users the jit wall
            try:
                self.engine.warmup()
            except Exception:
                pass

    @staticmethod
    def _trace_ctx() -> Optional[Dict[str, Any]]:
        """Flatten the router's trace stamp out of the replica call
        context (request_id + sampling verdict + routing annotations),
        so the engine opens the request's trace under the id the
        client/proxy already knows."""
        try:
            from ray_tpu.serve._private.replica import \
                get_request_context
            ctx = get_request_context()
        except Exception:
            return None
        rid = ctx.get("request_id")
        if not rid:
            return None
        return dict(ctx.get("trace") or {}, request_id=rid)

    async def generate(self, prompt_ids: Sequence[int],
                       max_new_tokens: Optional[int] = None,
                       eos_token_id: Optional[int] = None):
        async for tok in self.engine.generate(
                prompt_ids, max_new_tokens, eos_token_id,
                trace_ctx=self._trace_ctx()):
            yield tok

    async def __call__(self, prompt_ids: Sequence[int],
                       max_new_tokens: Optional[int] = None):
        async for tok in self.engine.generate(
                prompt_ids, max_new_tokens,
                trace_ctx=self._trace_ctx()):
            yield tok

    # ------------------------------------------ disagg replica surface
    async def prefill_export(self, prompt_ids: Sequence[int]
                             ) -> Dict[str, Any]:
        """Prefill-fleet actor method: chunked-prefill the prompt and
        return the KV hand-off payload. The payload's device slabs ride
        the out-of-band zero-copy serializer; the decode replica pulls
        them peer-to-peer when the router chains this call's ObjectRef
        into ``adopt_generate``."""
        eng = self.engine
        req = eng.submit(prompt_ids, max_new_tokens=1,
                         trace_ctx=self._trace_ctx(), _export=True)
        loop = asyncio.get_running_loop()
        get = functools.partial(req.out.get, timeout=0.2)
        try:
            while True:
                try:
                    item = await loop.run_in_executor(
                        eng._poll_pool, get)
                except queue.Empty:
                    if eng._dead is not None:
                        raise EngineDeadError(
                            f"engine step loop died: {eng._dead!r}")
                    continue
                if isinstance(item, BaseException):
                    raise item
                if isinstance(item, dict):
                    return item
                if item is _DONE:
                    raise EngineDeadError(
                        "prefill_export ended without a payload")
        finally:
            eng.cancel(req)

    async def adopt_generate(self, payload: Dict[str, Any],
                             max_new_tokens: Optional[int] = None,
                             eos_token_id: Optional[int] = None):
        """Decode-fleet actor method: adopt a shipped prefill payload
        and stream tokens — the first token (computed by the prefill
        replica) included, so the stream is exactly what a colocated
        ``generate`` would produce."""
        eng = self.engine
        req = eng.submit_adopt(payload, max_new_tokens, eos_token_id,
                               trace_ctx=self._trace_ctx())
        loop = asyncio.get_running_loop()
        get = functools.partial(req.out.get, timeout=0.2)
        try:
            while True:
                try:
                    item = await loop.run_in_executor(
                        eng._poll_pool, get)
                except queue.Empty:
                    if eng._dead is not None:
                        raise EngineDeadError(
                            f"engine step loop died: {eng._dead!r}")
                    continue
                if item is _DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            eng.cancel(req)

    async def export_warm_prefixes(self, min_hits: int = 1,
                                   max_blocks: int = 0
                                   ) -> Optional[Dict[str, Any]]:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self.engine._poll_pool,
            functools.partial(self.engine.export_warm_prefixes,
                              min_hits, max_blocks))

    async def import_warm_prefixes(self,
                                   payload: Optional[Dict[str, Any]]
                                   ) -> int:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self.engine._poll_pool,
            functools.partial(self.engine.import_warm_prefixes,
                              payload))

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def sync_weights(self, packed: Dict[str, Any]) -> int:
        """Apply an int8-packed weight refresh (the
        :mod:`ray_tpu.rlhf.weight_sync` wire format) in-flight:
        dequantize on this actor-call thread, stage for the step
        thread's between-steps pointer swap. Returns the staged
        version. Decode never drains."""
        from ray_tpu.rlhf.weight_sync import unpack_weights
        params, version = unpack_weights(packed)
        self.engine.stage_weights(params, version)
        return version

    def pool_audit(self) -> List[str]:
        return self.engine.pool_audit()

    def kv_block_bytes(self) -> int:
        ec, mc = self.engine_config, self.model_config
        return ec.kv_block_size * ec.kv_bytes_per_token(mc)

    def check_health(self) -> None:
        if self.engine._dead is not None:
            raise EngineDeadError(repr(self.engine._dead))
