"""Continuous-batching LLM inference engine (the "millions of users"
serving path, ROADMAP item 1).

vLLM-style serving on the repo's own model stack: a paged KV cache in
device memory (``models.transformer.init_kv_cache``), a fixed array of
**decode slots** stepped as ONE batched ``decode_step`` call, and
**chunked prefill** interleaved between decode steps so a new arrival's
time-to-first-token never stalls in-flight streams for more than one
``prefill_chunk``'s worth of compute. New requests are admitted into the
in-flight batch between steps — continuous batching, not static batching:
a finishing stream frees its slot and blocks for the next queued prompt
immediately, so the MXU stays at high occupancy under ragged request
lengths.

Shapes are FIXED at engine construction (``decode_slots`` sequences per
decode call, ``prefill_chunk`` tokens per prefill call, one block table
of ``blocks_per_seq`` entries per slot) and both model functions are
jitted once with donated caches — admission, EOS, and cancellation are
pure host-side bookkeeping and never recompile.

Memory accounting: one KV block holds ``block_size`` tokens ×
``2 (k+v) × n_layers × kv_heads × head_dim × dtype_bytes`` bytes; the
pool is ``num_kv_blocks`` blocks (default: full occupancy — every slot
can hold ``max_seq_len`` tokens — plus one reserved trash block that
idle slots' writes land in). Blocks are recycled through a free list on
EOS/cancel/error.

Integration: :class:`LLMServer` is the deployment-facing wrapper —
``generate`` is an async generator, so a Serve replica streams tokens
through the core ``num_returns="streaming"`` machinery and
``handle.options(stream=True)`` / the HTTP proxy work unchanged;
consumer ``close()`` lands in :meth:`LLMEngine.cancel`, which frees the
slot and blocks at the next step boundary.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import functools
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu.exceptions import RayTpuError


class EngineDeadError(RayTpuError):
    """The engine's step loop died; every queued/in-flight request is
    failed with this (typed — consumers never hang on a dead engine)."""


class RequestTooLargeError(RayTpuError):
    """prompt_len + 1 exceeds the engine's per-request window
    (``max_seq_len``) — the request can never be admitted."""


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Knobs of the serving engine (see README "Serving").

    - ``decode_slots``: sequences decoded per batched step — the
      continuous-batching width and the unit of batch occupancy.
    - ``kv_block_size``: tokens per KV-cache block (paging granularity;
      smaller = less internal fragmentation, more gather indices).
    - ``max_seq_len``: per-request window (prompt + generated tokens);
      sets ``blocks_per_seq`` and the attention gather width.
    - ``prefill_chunk``: prompt tokens processed per engine step — the
      TTFT-vs-inter-token-latency tradeoff knob.
    - ``num_kv_blocks``: KV pool size; 0 = auto (full occupancy + the
      reserved trash block idle slots write into).
    """
    decode_slots: int = 8
    kv_block_size: int = 16
    max_seq_len: int = 256
    prefill_chunk: int = 32
    num_kv_blocks: int = 0
    max_new_tokens: int = 64          # default per-request cap
    eos_token_id: Optional[int] = None

    @property
    def blocks_per_seq(self) -> int:
        return -(-self.max_seq_len // self.kv_block_size)

    @property
    def resolved_num_blocks(self) -> int:
        if self.num_kv_blocks:
            return self.num_kv_blocks
        return 1 + self.decode_slots * self.blocks_per_seq

    def kv_bytes_per_token(self, model_config) -> int:
        """KV bytes/token — the HBM-budget side of the block math."""
        import jax.numpy as jnp
        c = model_config
        itemsize = jnp.dtype(c.dtype).itemsize
        return 2 * c.n_layers * c.kv_heads * c.head_dim * itemsize


_DONE = object()          # stream-end sentinel on the request queue

# request lifecycle states
_QUEUED, _PREFILL, _DECODE, _FINISHED = range(4)


class _Request:
    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "out", "state", "slot", "blocks", "prefill_pos",
                 "seq_len", "generated", "cancelled", "t_submit",
                 "t_first_token")

    def __init__(self, rid: int, prompt: List[int], max_new_tokens: int,
                 eos_token_id: Optional[int]):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.out: "queue.Queue" = queue.Queue()
        self.state = _QUEUED
        self.slot: Optional[int] = None
        self.blocks: List[int] = []
        self.prefill_pos = 0          # prompt tokens already in cache
        self.seq_len = 0              # cache positions written
        self.generated = 0            # tokens emitted
        self.cancelled = False
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None


class LLMEngine:
    """Continuous-batching scheduler over the paged decode path.

    Thread model: one background step thread owns the device state
    (caches + slot arrays); ``submit``/``cancel`` only touch the queue
    under a lock and are safe from any thread or event loop. Consumers
    read per-request ``queue.Queue``s fed by the step thread.
    """

    def __init__(self, model_config, engine_config: Optional[EngineConfig]
                 = None, params=None, seed: int = 0,
                 replica_tag: str = ""):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from ray_tpu.models import (decode_step, init_kv_cache,
                                    init_params, prefill)

        self.model_config = model_config
        self.config = engine_config or EngineConfig()
        self.replica_tag = replica_tag
        ec = self.config
        if ec.prefill_chunk < 1 or ec.decode_slots < 1:
            raise ValueError("prefill_chunk and decode_slots must be >= 1")

        self._params = params if params is not None \
            else init_params(model_config, jax.random.PRNGKey(seed))
        self._cache = init_kv_cache(model_config, ec.resolved_num_blocks,
                                    ec.kv_block_size)

        S, T = ec.decode_slots, ec.blocks_per_seq
        self._np = np
        self._jnp = jnp
        # Host-side slot arrays. Block-table row 0s point idle slots at
        # the reserved trash block, so their (masked-garbage) decode
        # writes never touch a live sequence's blocks.
        self._block_tables = np.zeros((S, T), np.int32)
        self._seq_lens = np.zeros((S,), np.int32)
        self._last_tok = np.zeros((S,), np.int32)
        self._slots: List[Optional[_Request]] = [None] * S
        self._free_slots = list(range(S))
        self._free_blocks = collections.deque(
            range(1, ec.resolved_num_blocks))    # block 0 = trash

        # jit once at the fixed shapes; caches are donated so XLA
        # updates them in place step over step.
        def _prefill_fn(params, tokens, cache, bt, start, lens):
            logits, cache = prefill(model_config, params, tokens, cache,
                                    bt, start, lens)
            last = jnp.take_along_axis(
                logits, (lens - 1)[:, None, None], axis=1)[:, 0]
            return jnp.argmax(last, axis=-1).astype(jnp.int32), cache

        def _decode_fn(params, toks, cache, bt, seq_lens):
            logits, cache = decode_step(model_config, params, toks,
                                        cache, bt, seq_lens)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        self._jit_prefill = jax.jit(_prefill_fn, donate_argnums=(2,))
        self._jit_decode = jax.jit(_decode_fn, donate_argnums=(2,))

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._pending: "collections.deque[_Request]" = collections.deque()
        self._prefilling: "collections.deque[_Request]" = \
            collections.deque()
        self._rid = 0
        self._stop = False
        self._dead: Optional[BaseException] = None

        # -- stats / metrics -------------------------------------------
        self._tokens_total = 0
        self._decode_steps = 0
        self._prefill_chunks = 0
        self._occupancy: Dict[int, int] = collections.defaultdict(int)
        self._t_start = time.monotonic()
        self._last_stats_emit = 0.0
        # EWMA of recent TTFTs: the autoscaler's latency signal (a
        # histogram is right for dashboards, wrong for a scale-up
        # decision that wants "what are users seeing RIGHT NOW")
        self._ttft_ewma: Optional[float] = None
        self._metrics = self._recorder = None
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            self._metrics = runtime_metrics()
        except Exception:
            pass
        try:
            from ray_tpu.core.global_state import try_global_worker
            w = try_global_worker()
            self._recorder = getattr(w, "recorder", None)
        except Exception:
            pass

        # Engine-owned executor for consumer-side queue polls: sharing
        # the actor event loop's default executor would let stream
        # polls and whole actor calls starve each other under load.
        from concurrent.futures import ThreadPoolExecutor
        self._poll_pool = ThreadPoolExecutor(
            2 * ec.decode_slots + 4, thread_name_prefix="llm-engine-poll")

        self._thread = threading.Thread(
            target=self._run, name="llm-engine-step", daemon=True)
        self._thread.start()

    # ------------------------------------------------------- public API
    def submit(self, prompt_ids: Sequence[int],
               max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None) -> _Request:
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt")
        ec = self.config
        if len(prompt) + 1 > ec.max_seq_len:
            raise RequestTooLargeError(
                f"prompt of {len(prompt)} tokens + 1 exceeds the engine "
                f"window max_seq_len={ec.max_seq_len}")
        mnt = max_new_tokens if max_new_tokens is not None \
            else ec.max_new_tokens
        eos = eos_token_id if eos_token_id is not None else ec.eos_token_id
        with self._work:
            if self._dead is not None:
                raise EngineDeadError(
                    f"engine step loop died: {self._dead!r}")
            self._rid += 1
            req = _Request(self._rid, prompt, max(1, int(mnt)), eos)
            self._pending.append(req)
            self._work.notify_all()
        return req

    def cancel(self, req: _Request) -> None:
        """Mark a request cancelled; the step thread frees its slot and
        blocks at the next step boundary (the generator ``close()``
        path lands here)."""
        with self._work:
            req.cancelled = True
            self._work.notify_all()

    async def generate(self, prompt_ids: Sequence[int],
                       max_new_tokens: Optional[int] = None,
                       eos_token_id: Optional[int] = None):
        """Async token stream for one request. Raises typed errors
        (``EngineDeadError`` / ``RequestTooLargeError``) instead of
        hanging; early ``aclose()`` cancels the request and frees its
        slot + blocks."""
        req = self.submit(prompt_ids, max_new_tokens, eos_token_id)
        loop = asyncio.get_running_loop()
        get = functools.partial(req.out.get, timeout=0.2)
        try:
            while True:
                try:
                    item = await loop.run_in_executor(self._poll_pool, get)
                except queue.Empty:
                    if self._dead is not None:
                        raise EngineDeadError(
                            f"engine step loop died: {self._dead!r}")
                    continue
                if item is _DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            self.cancel(req)

    def generate_sync(self, prompt_ids: Sequence[int],
                      max_new_tokens: Optional[int] = None,
                      eos_token_id: Optional[int] = None,
                      timeout_s: float = 120.0):
        """Blocking token stream (tests / direct embedding)."""
        req = self.submit(prompt_ids, max_new_tokens, eos_token_id)
        deadline = time.monotonic() + timeout_s
        try:
            while True:
                try:
                    item = req.out.get(timeout=0.2)
                except queue.Empty:
                    if self._dead is not None:
                        raise EngineDeadError(
                            f"engine step loop died: {self._dead!r}")
                    if time.monotonic() > deadline:
                        raise TimeoutError("generate_sync timed out")
                    continue
                if item is _DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            self.cancel(req)

    def stats(self) -> Dict[str, Any]:
        """Scheduler counters (the autoscaling signal surface): queue
        depth, batch occupancy histogram, tokens/s, leak-check views of
        the slot/block free lists."""
        with self._lock:
            elapsed = max(time.monotonic() - self._t_start, 1e-9)
            return {
                "queue_depth": len(self._pending),
                "prefilling": len(self._prefilling),
                "active_slots": sum(1 for r in self._slots
                                    if r is not None),
                "free_slots": len(self._free_slots),
                "free_blocks": len(self._free_blocks),
                "total_blocks": self.config.resolved_num_blocks - 1,
                "tokens_total": self._tokens_total,
                "tokens_per_s": round(self._tokens_total / elapsed, 2),
                "decode_steps": self._decode_steps,
                "prefill_chunks": self._prefill_chunks,
                "occupancy_hist": dict(self._occupancy),
                "ttft_ewma_s": (round(self._ttft_ewma, 6)
                                if self._ttft_ewma is not None else None),
                "dead": repr(self._dead) if self._dead else None,
            }

    def shutdown(self) -> None:
        with self._work:
            self._stop = True
            self._work.notify_all()
        self._thread.join(timeout=10)
        self._poll_pool.shutdown(wait=False)

    # -------------------------------------------------------- step loop
    def _run(self) -> None:
        try:
            while True:
                with self._work:
                    while not self._stop and not self._has_work_locked():
                        self._work.wait(timeout=0.5)
                    if self._stop:
                        break
                self._step()
        except BaseException as e:  # noqa: BLE001 — fail typed, never hang
            self._on_dead(e)

    def _has_work_locked(self) -> bool:
        return bool(self._pending) or bool(self._prefilling) \
            or any(r is not None for r in self._slots)

    def _on_dead(self, e: BaseException) -> None:
        with self._work:
            self._dead = e
            reqs = [r for r in self._slots if r is not None]
            reqs += list(self._prefilling) + list(self._pending)
            self._pending.clear()
            self._prefilling.clear()
        err = EngineDeadError(f"engine step loop died: {e!r}")
        err.__cause__ = e
        for r in set(reqs):
            r.out.put(err)

    # one engine step: reap -> admit -> one prefill chunk -> one decode
    def _step(self) -> None:
        self._reap_cancelled()
        self._admit()
        self._prefill_one_chunk()
        self._decode_once()
        self._emit_stats()

    def _reap_cancelled(self) -> None:
        with self._lock:
            for req in list(self._prefilling):
                if req.cancelled:
                    self._prefilling.remove(req)
                    self._release_locked(req)
            for req in list(self._pending):
                if req.cancelled:
                    self._pending.remove(req)
                    req.out.put(_DONE)
            for req in self._slots:
                if req is not None and req.cancelled:
                    self._release_locked(req)

    def _admit(self) -> None:
        ec = self.config
        while True:
            with self._lock:
                if not self._pending or not self._free_slots:
                    return
                req = self._pending[0]
                need = -(-min(len(req.prompt) + req.max_new_tokens,
                              ec.max_seq_len) // ec.kv_block_size)
                if need > len(self._free_blocks):
                    # full occupancy: WAIT for blocks (shapes are fixed;
                    # admission pressure never grows the compiled batch)
                    return
                self._pending.popleft()
                req.slot = self._free_slots.pop()
                req.blocks = [self._free_blocks.popleft()
                              for _ in range(need)]
                self._block_tables[req.slot, :] = 0
                self._block_tables[req.slot, :need] = req.blocks
                self._seq_lens[req.slot] = 0
                req.state = _PREFILL
                self._slots[req.slot] = req
                self._prefilling.append(req)

    def _prefill_one_chunk(self) -> None:
        with self._lock:
            req = self._prefilling[0] if self._prefilling else None
        if req is None:
            return
        np, jnp = self._np, self._jnp
        ec = self.config
        C = ec.prefill_chunk
        start = req.prefill_pos
        n = min(C, len(req.prompt) - start)
        chunk = np.zeros((1, C), np.int32)
        chunk[0, :n] = req.prompt[start:start + n]
        tok, self._cache = self._jit_prefill(
            self._params, jnp.asarray(chunk), self._cache,
            jnp.asarray(self._block_tables[req.slot:req.slot + 1]),
            jnp.full((1,), start, jnp.int32),
            jnp.full((1,), n, jnp.int32))
        req.prefill_pos += n
        self._prefill_chunks += 1
        if req.prefill_pos < len(req.prompt):
            return
        # prompt fully cached: the final chunk's last logits give the
        # first generated token — TTFT stops here
        first = int(tok[0])
        req.seq_len = len(req.prompt)
        req.t_first_token = time.monotonic()
        self._record_ttft(req)
        with self._lock:
            self._prefilling.popleft()
            if req.cancelled:
                self._release_locked(req)
                return
            if req.eos_token_id is not None and first == req.eos_token_id:
                self._release_locked(req)
                return
            req.generated = 1
            req.out.put(first)
            self._tokens_total += 1
            if req.generated >= req.max_new_tokens:
                self._release_locked(req)
                return
            req.state = _DECODE
            self._last_tok[req.slot] = first
            self._seq_lens[req.slot] = req.seq_len

    def _decode_once(self) -> None:
        with self._lock:
            active = [r for r in self._slots
                      if r is not None and r.state == _DECODE]
            if not active:
                return
            self._decode_steps += 1
            self._occupancy[len(active)] += 1
            if self._metrics is not None:
                try:
                    self._metrics.serve_batch_occupancy.observe(
                        len(active))
                except Exception:
                    pass
            toks = self._last_tok.copy()
            lens = self._seq_lens.copy()
            bt = self._block_tables.copy()
        jnp = self._jnp
        out, self._cache = self._jit_decode(
            self._params, jnp.asarray(toks), self._cache,
            jnp.asarray(bt), jnp.asarray(lens))
        out = self._np.asarray(out)
        produced = 0
        with self._lock:
            for req in active:
                if req.cancelled or self._slots[req.slot] is not req:
                    continue
                tok = int(out[req.slot])
                req.seq_len += 1           # the token we just wrote
                self._seq_lens[req.slot] = req.seq_len
                if req.eos_token_id is not None \
                        and tok == req.eos_token_id:
                    self._release_locked(req)
                    continue
                req.generated += 1
                req.out.put(tok)
                self._tokens_total += 1
                produced += 1
                if req.generated >= req.max_new_tokens \
                        or req.seq_len + 1 >= self.config.max_seq_len:
                    self._release_locked(req)
                else:
                    self._last_tok[req.slot] = tok
        # decode tokens into the fleet counter (the first token per
        # request is counted by _record_ttft), so the plane's
        # rate(serve_engine_tokens_total) IS engine tokens/s
        if produced and self._metrics is not None:
            try:
                self._metrics.serve_tokens.inc(produced)
            except Exception:
                pass

    def _release_locked(self, req: _Request,
                        err: Optional[BaseException] = None) -> None:
        """Return a request's slot + blocks to the free lists and close
        its stream (call with self._lock held)."""
        if req.slot is not None and self._slots[req.slot] is req:
            self._slots[req.slot] = None
            self._block_tables[req.slot, :] = 0
            self._seq_lens[req.slot] = 0
            self._last_tok[req.slot] = 0
            self._free_slots.append(req.slot)
            self._free_blocks.extend(req.blocks)
            req.blocks = []
            req.slot = None
        req.state = _FINISHED
        req.out.put(err if err is not None else _DONE)
        self._work.notify_all()

    # ------------------------------------------------ metrics / events
    def _record_ttft(self, req: _Request) -> None:
        ttft = req.t_first_token - req.t_submit
        self._ttft_ewma = ttft if self._ttft_ewma is None \
            else 0.8 * self._ttft_ewma + 0.2 * ttft
        if self._metrics is not None:
            try:
                self._metrics.serve_ttft.observe(ttft)
                self._metrics.serve_tokens.inc()
            except Exception:
                pass
        if self._recorder is not None:
            try:
                self._recorder.record(
                    "ENGINE_TTFT", replica=self.replica_tag,
                    rid=req.rid, ttft_s=round(ttft, 6),
                    prompt_len=len(req.prompt))
            except Exception:
                pass

    def _emit_stats(self, interval_s: float = 0.5) -> None:
        now = time.monotonic()
        if now - self._last_stats_emit < interval_s:
            return
        self._last_stats_emit = now
        s = self.stats()
        if self._metrics is not None:
            try:
                self._metrics.serve_queue_depth.set(s["queue_depth"])
                self._metrics.serve_tokens_per_s.set(s["tokens_per_s"])
            except Exception:
                pass
        if self._recorder is not None:
            try:
                self._recorder.record(
                    "ENGINE_STATS", replica=self.replica_tag,
                    queue_depth=s["queue_depth"],
                    active=s["active_slots"],
                    tokens_per_s=s["tokens_per_s"],
                    free_blocks=s["free_blocks"])
                self._recorder.maybe_flush()
            except Exception:
                pass
        # a replica decoding flat-out may never hit the worker idle
        # loop: the stats cadence doubles as the fleet-report heartbeat
        try:
            from ray_tpu.core.global_state import try_global_worker
            w = try_global_worker()
            if w is not None and getattr(w, "metrics_reporter",
                                         None) is not None:
                w.metrics_reporter.maybe_report()
        except Exception:
            pass


def _resolve_dtype(name):
    import jax.numpy as jnp
    if not isinstance(name, str):
        return name
    return {"float32": jnp.float32, "f32": jnp.float32,
            "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
            "float16": jnp.float16}[name]


class LLMServer:
    """Deployment-facing engine wrapper. Construct with plain dicts so
    the deployment graph ships cheaply to the replica actor::

        app = serve.deployment(LLMServer).bind(
            model={"d_model": 256, "n_layers": 4, ...},
            engine={"decode_slots": 8, "kv_block_size": 16})
        h = serve.run(app)
        for tok in h.options(stream=True).generate.remote([1, 2, 3]):
            ...

    ``generate`` is an async generator, so each token rides the core
    streaming-generator machinery (per-item objects, backpressure,
    typed failure on replica death).
    """

    def __init__(self, model: Optional[Dict[str, Any]] = None,
                 engine: Optional[Dict[str, Any]] = None,
                 seed: int = 0):
        from ray_tpu.models import TransformerConfig
        model = dict(model or {})
        if "dtype" in model:
            model["dtype"] = _resolve_dtype(model["dtype"])
        model.setdefault("dtype", _resolve_dtype("float32"))
        self.model_config = TransformerConfig(**model)
        self.engine_config = EngineConfig(**(engine or {}))
        self.engine = LLMEngine(self.model_config, self.engine_config,
                                seed=seed,
                                replica_tag=f"pid:{os.getpid()}")

    async def generate(self, prompt_ids: Sequence[int],
                       max_new_tokens: Optional[int] = None,
                       eos_token_id: Optional[int] = None):
        async for tok in self.engine.generate(
                prompt_ids, max_new_tokens, eos_token_id):
            yield tok

    async def __call__(self, prompt_ids: Sequence[int],
                       max_new_tokens: Optional[int] = None):
        async for tok in self.engine.generate(prompt_ids,
                                              max_new_tokens):
            yield tok

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def kv_block_bytes(self) -> int:
        ec, mc = self.engine_config, self.model_config
        return ec.kv_block_size * ec.kv_bytes_per_token(mc)

    def check_health(self) -> None:
        if self.engine._dead is not None:
            raise EngineDeadError(repr(self.engine._dead))
