"""Refcounted KV-block pool with radix-trie prefix sharing.

The serving engine's paged block tables already indirect every cache
read through per-sequence block ids, so two sequences whose prompts
share a prefix can point their leading table entries at the SAME
physical blocks (vLLM's prefix caching / SGLang's radix attention).
This module owns the bookkeeping:

- every managed block carries a **refcount** (requests using it); the
  free list only holds blocks with no references and no trie entry;
- **full** ``block_size``-token prompt chunks are indexed in a radix
  trie keyed on the chunk's token tuple — matching a new prompt walks
  the trie chunk by chunk and hands back the shared blocks (incref'd),
  so prefill skips them entirely;
- a request finishing (EOS / cancel / error) **decrefs** instead of
  freeing: a block whose refcount hits zero but that is still indexed
  in the trie stays resident as reusable cache, and is evicted
  **LRU, leaves first**, only when an allocation actually needs the
  space (pool pressure) — an idle pool keeps every prefix warm.

Only full prompt chunks are ever inserted, which makes shared blocks
immutable by construction: a sequence's own writes (later prompt
chunks, generated tokens, speculative drafts) always land at positions
``>= matched_tokens``, i.e. in blocks the trie has never seen. The
partial tail of a fully-matched prompt is handled by the engine with a
copy-on-write block copy (see ``LLMEngine._admit``).

Thread model: the pool is NOT internally locked — the engine calls it
with its scheduler lock held (all mutations happen on the step
thread).
"""

from __future__ import annotations

import collections
import zlib
from typing import Dict, List, Optional, Sequence, Tuple


def prefix_fingerprint(tokens: Sequence[int],
                       block_size: int) -> Optional[int]:
    """Stable fingerprint of a prompt's FIRST full KV-block chunk
    (crc32 of the token bytes — deterministic across processes, unlike
    ``hash``). ``None`` when the prompt has no full block. Routers
    compare this against :meth:`PrefixBlockPool.root_fingerprints` to
    place a COLD session on the replica whose radix trie already holds
    its prefix."""
    if block_size < 1 or len(tokens) < block_size:
        return None
    data = b"".join(int(t).to_bytes(8, "little", signed=True)
                    for t in tokens[:block_size])
    return zlib.crc32(data)


class _TrieNode:
    """One full token chunk in the radix trie. ``key`` is the chunk's
    token tuple (its edge label from ``parent``); ``block`` the
    physical block holding that chunk's KV."""

    __slots__ = ("children", "parent", "key", "block", "touch",
                 "detached", "hits")

    def __init__(self, parent: Optional["_TrieNode"],
                 key: Optional[tuple], block: Optional[int]):
        self.children: Dict[tuple, "_TrieNode"] = {}
        self.parent = parent
        self.key = key
        self.block = block
        self.touch = 0          # LRU clock stamp
        self.detached = False   # evicted — inserts under it must abort
        self.hits = 0           # prefix-match count (migration floor)


class PrefixBlockPool:
    """Refcounted block allocator + radix prefix index over one paged
    KV pool of ``num_blocks`` blocks (``reserved`` ids — the engine's
    trash block — are never handed out)."""

    def __init__(self, num_blocks: int, block_size: int,
                 reserved: Sequence[int] = (0,)):
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.block_size = block_size
        self._reserved = frozenset(reserved)
        managed = [b for b in range(num_blocks)
                   if b not in self._reserved]
        self.total_managed = len(managed)
        self._free: "collections.deque[int]" = collections.deque(managed)
        self._ref: Dict[int, int] = {}          # block -> refcount >= 1
        self._node_of: Dict[int, _TrieNode] = {}  # trie-resident blocks
        self._root = _TrieNode(None, None, None)
        self._clock = 0
        # -- counters (engine surfaces these in stats())
        self.hits_total = 0        # blocks handed out via prefix match
        self.inserts_total = 0
        self.evictions_total = 0

    # ------------------------------------------------------- refcounts
    def incref(self, block: int) -> None:
        if block in self._ref:
            self._ref[block] += 1
        else:
            # resurrecting a cached (ref-0, trie-resident) block
            self._ref[block] = 1

    def decref(self, block: int) -> None:
        n = self._ref[block] - 1
        if n > 0:
            self._ref[block] = n
            return
        del self._ref[block]
        if block not in self._node_of:
            self._free.append(block)
        # else: stays resident in the trie as reusable cache

    def release(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            self.decref(b)

    # ------------------------------------------------------- matching
    def _touch(self, node: _TrieNode) -> None:
        self._clock += 1
        node.touch = self._clock

    def match_prefix(self, tokens: Sequence[int]
                     ) -> Tuple[List[int], int, _TrieNode]:
        """Walk the trie along ``tokens`` in full-chunk steps. Returns
        ``(blocks, matched_tokens, node)`` — matched blocks are
        incref'd (caller owns one reference each; release on abort) and
        ``node`` is the deepest matched trie node (the parent for this
        request's own inserts)."""
        node = self._root
        blocks: List[int] = []
        bs = self.block_size
        for i in range(len(tokens) // bs):
            child = node.children.get(tuple(tokens[i * bs:(i + 1) * bs]))
            if child is None:
                break
            node = child
            blocks.append(node.block)
            self.incref(node.block)
            self._touch(node)
            node.hits += 1
        # hits_total is NOT bumped here: a match may be released when
        # allocation fails (admission wait) and retried — the engine
        # counts hits once, on successful admission (count_hits)
        return blocks, len(blocks) * bs, node

    def count_hits(self, n: int) -> None:
        self.hits_total += n

    # ----------------------------------------------------- allocation
    def allocate(self, n: int) -> Optional[List[int]]:
        """Take ``n`` private blocks (refcount 1 each), evicting LRU
        ref-0 trie leaves under pressure. Returns None — with nothing
        taken — when even eviction can't cover ``n`` (the engine's
        admission-wait signal)."""
        got: List[int] = []
        while len(got) < n:
            if self._free:
                b = self._free.popleft()
                self._ref[b] = 1
                got.append(b)
                continue
            if not self._evict_one():
                for b in got:           # restore, all-or-nothing
                    del self._ref[b]
                    self._free.append(b)
                return None
        return got

    def _evict_one(self) -> bool:
        """Evict the least-recently-touched ref-0 LEAF (a node with
        referenced or cached children is load-bearing for deeper
        matches and never evicted; freeing a leaf may expose its
        parent as the next candidate)."""
        best: Optional[Tuple[int, _TrieNode]] = None
        for block, node in self._node_of.items():
            if block in self._ref or node.children:
                continue
            if best is None or node.touch < best[1].touch:
                best = (block, node)
        if best is None:
            return False
        block, node = best
        node.detached = True
        if node.parent is not None:
            node.parent.children.pop(node.key, None)
        del self._node_of[block]
        self._free.append(block)
        self.evictions_total += 1
        return True

    # ------------------------------------------------------ insertion
    def insert_child(self, parent: Optional[_TrieNode],
                     chunk: Sequence[int], block: int
                     ) -> Tuple[Optional[_TrieNode], bool]:
        """Index ``block`` (full, holding exactly ``chunk``) under
        ``parent``. Returns ``(node, inserted)``:

        - fresh insert → the new node, True;
        - the path already exists (a concurrent request with the same
          prompt won the race) → the existing node, False — the
          caller's block stays private and is freed normally;
        - ``parent`` was evicted meanwhile (or None) → (None, False) —
          the caller stops indexing this request.
        """
        if parent is None or parent.detached:
            return None, False
        key = tuple(chunk)
        existing = parent.children.get(key)
        if existing is not None:
            self._touch(existing)
            return existing, False
        node = _TrieNode(parent, key, block)
        parent.children[key] = node
        self._node_of[block] = node
        self._touch(node)
        self.inserts_total += 1
        return node, True

    # ------------------------------------------------------- migration
    def export_chains(self, min_hits: int = 1,
                      max_blocks: int = 0) -> List[List[Tuple[tuple, int]]]:
        """Warm prefix chains worth migrating off a draining replica.

        A chain is a contiguous root-anchored trie path of ref-0
        (cached) nodes whose ``hits`` meet the floor — exactly the
        blocks that would die with this replica but have proven reuse.
        Chains truncate at the first node that is referenced (a live
        request still writes against it), below the hit floor, or
        detached: an importer re-inserts from its own root, so a gap
        would orphan everything deeper. Returns
        ``[[(chunk_tokens, block_id), ...], ...]`` ordered hottest
        chain first; ``max_blocks > 0`` caps the total block count.
        """
        chains: List[List[Tuple[tuple, int]]] = []

        def walk(node: _TrieNode, path: List[Tuple[tuple, int]]):
            extended = False
            for child in sorted(node.children.values(),
                                key=lambda n: -n.hits):
                if (child.detached or child.block in self._ref
                        or child.hits < min_hits):
                    continue
                walk(child, path + [(child.key, child.block)])
                extended = True
            if not extended and path:
                chains.append(path)

        walk(self._root, [])
        chains.sort(key=lambda c: -len(c))
        if max_blocks > 0:
            out, n = [], 0
            for c in chains:
                if n + len(c) > max_blocks:
                    c = c[:max_blocks - n]
                if not c:
                    break
                out.append(c)
                n += len(c)
            chains = out
        return chains

    # -------------------------------------------------------- introspection
    def root_fingerprints(self, limit: int = 64) -> List[int]:
        """Fingerprints of the trie ROOT's children — the first-block
        chunks this pool holds warm. O(root fan-out), capped at
        ``limit`` (most-recently-touched first): cheap enough for every
        ``Replica.stats()`` probe, rich enough for a router to place a
        cold session where its system prompt already lives."""
        kids = sorted(self._root.children.values(),
                      key=lambda n: -n.touch)[:limit]
        out = []
        for node in kids:
            fp = prefix_fingerprint(node.key, self.block_size)
            if fp is not None:
                out.append(fp)
        return out

    def stats(self) -> Dict[str, int]:
        cached = sum(1 for b in self._node_of if b not in self._ref)
        shared = sum(1 for b, r in self._ref.items() if r > 1)
        return {
            "free": len(self._free),
            "cached": cached,               # ref-0, trie-resident
            "reclaimable": len(self._free) + cached,
            "active": len(self._ref),
            "shared": shared,               # refcount > 1 right now
            "trie_blocks": len(self._node_of),
            "hits_total": self.hits_total,
            "inserts_total": self.inserts_total,
            "evictions_total": self.evictions_total,
        }

    def audit(self) -> List[str]:
        """Integrity check (leak regression tests): every managed block
        is in EXACTLY one of {free, referenced, cached-in-trie}; every
        trie node is reachable, attached, and consistent with
        ``_node_of``. Returns a list of problems (empty = clean)."""
        problems: List[str] = []
        free = set(self._free)
        if len(free) != len(self._free):
            problems.append("duplicate blocks on the free list")
        ref = set(self._ref)
        trie = set(self._node_of)
        if free & ref:
            problems.append(f"blocks both free and referenced: "
                            f"{sorted(free & ref)}")
        if free & trie:
            problems.append(f"blocks both free and trie-resident: "
                            f"{sorted(free & trie)}")
        accounted = free | ref | trie
        managed = {b for b in range(
            self.total_managed + len(self._reserved))
            if b not in self._reserved}
        missing = managed - accounted
        if missing:
            problems.append(f"leaked blocks (nowhere): {sorted(missing)}")
        extra = accounted - managed
        if extra:
            problems.append(f"unmanaged blocks tracked: {sorted(extra)}")
        # trie reachability + pointer consistency
        reachable = set()
        stack = [self._root]
        while stack:
            node = stack.pop()
            for key, child in node.children.items():
                if child.parent is not node or child.key != key:
                    problems.append(f"trie pointer mismatch at {key}")
                if child.detached:
                    problems.append(f"detached node still linked: {key}")
                if child.block is None:
                    problems.append(f"trie node without block: {key}")
                elif self._node_of.get(child.block) is not child:
                    problems.append(
                        f"_node_of mismatch for block {child.block}")
                else:
                    reachable.add(child.block)
                stack.append(child)
        dangling = trie - reachable
        if dangling:
            problems.append(f"unreachable trie blocks: {sorted(dangling)}")
        return problems
