"""Deployment + Application: the Serve authoring API.

Reference: ``python/ray/serve/deployment.py`` (``@serve.deployment``)
and ``serve/_private/deployment_graph_build.py`` — a Deployment wraps a
class/function with replica/autoscaling config; ``.bind(*args)``
produces an Application node whose arguments may themselves be bound
deployments (model composition: inner deployments become
DeploymentHandles at init time).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple, Union


@dataclasses.dataclass
class AutoscalingConfig:
    """Queue-depth autoscaling (reference ``serve/config.py``).

    Beyond the reference's ongoing-request target, engine-aware
    deployments (those whose instance exposes ``stats()`` — e.g.
    ``LLMServer``) can scale up on the per-replica engine gauges: a
    mean engine queue depth above ``target_queue_depth``, or a mean
    time-to-first-token above ``target_ttft_s``, triggers the same
    scale-up path as ongoing-request pressure. Both default to None
    (off) so plain deployments behave exactly as before; engine
    pressure also vetoes a downscale (an idle handle count can
    coexist with a deep engine backlog — continuous batching hides
    queued work from the ongoing-request signal).
    """
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 10.0
    #: scale up when mean engine queue depth exceeds this (None = off)
    target_queue_depth: Optional[float] = None
    #: scale up when mean engine TTFT (EWMA) exceeds this (None = off)
    target_ttft_s: Optional[float] = None


class Deployment:
    def __init__(self, func_or_class, name: str,
                 num_replicas: Optional[Union[int, str]] = None,
                 autoscaling_config: Optional[dict] = None,
                 ray_actor_options: Optional[dict] = None,
                 max_ongoing_requests: int = 100,
                 user_config: Optional[Any] = None,
                 health_check_period_s: float = 10.0,
                 version: Optional[str] = None,
                 migrate_prefixes: bool = False):
        self.func_or_class = func_or_class
        self.name = name
        if isinstance(autoscaling_config, dict):
            autoscaling_config = AutoscalingConfig(**autoscaling_config)
        if num_replicas == "auto" and autoscaling_config is None:
            autoscaling_config = AutoscalingConfig()
        self.autoscaling_config = autoscaling_config
        self.num_replicas = (autoscaling_config.min_replicas
                             if autoscaling_config else
                             (num_replicas if isinstance(num_replicas, int)
                              else 1))
        self.ray_actor_options = ray_actor_options or {}
        self.max_ongoing_requests = max_ongoing_requests
        self.user_config = user_config
        self.health_check_period_s = health_check_period_s
        self.version = version
        #: drain-time warm-prefix migration: before the controller
        #: kills a replica on downscale, its warm radix-trie KV chains
        #: are exported (``Replica.prepare_drain``) and adopted by a
        #: surviving replica, so the fleet's prefix hit rate survives
        #: the drain (serve/disagg.py::migrate_warm_prefixes)
        self.migrate_prefixes = migrate_prefixes

    def options(self, **kwargs) -> "Deployment":
        merged = dict(
            func_or_class=self.func_or_class, name=self.name,
            num_replicas=self.num_replicas,
            autoscaling_config=self.autoscaling_config,
            ray_actor_options=self.ray_actor_options,
            max_ongoing_requests=self.max_ongoing_requests,
            user_config=self.user_config,
            health_check_period_s=self.health_check_period_s,
            version=self.version,
            migrate_prefixes=self.migrate_prefixes)
        merged.update(kwargs)
        return Deployment(**merged)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment({self.name})"


class Application:
    """A bound deployment DAG node (reference ``Application``)."""

    def __init__(self, deployment: Deployment, args: Tuple, kwargs: Dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs

    def _collect(self, seen: Dict[str, "Application"]) -> None:
        """Topologically collect all deployments in this app DAG."""
        for arg in list(self.init_args) + list(self.init_kwargs.values()):
            if isinstance(arg, Application):
                arg._collect(seen)
        seen[self.deployment.name] = self


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: Optional[Union[int, str]] = None,
               autoscaling_config: Optional[dict] = None,
               ray_actor_options: Optional[dict] = None,
               max_ongoing_requests: int = 100,
               user_config: Optional[Any] = None,
               health_check_period_s: float = 10.0,
               version: Optional[str] = None,
               migrate_prefixes: bool = False):
    """``@serve.deployment`` (reference ``api.py``)."""
    def wrap(fc):
        return Deployment(
            fc, name or fc.__name__, num_replicas=num_replicas,
            autoscaling_config=autoscaling_config,
            ray_actor_options=ray_actor_options,
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config,
            health_check_period_s=health_check_period_s,
            version=version,
            migrate_prefixes=migrate_prefixes)
    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
