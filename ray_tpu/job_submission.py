"""Job submission SDK (reference: ``python/ray/dashboard/modules/job/sdk.py``
``JobSubmissionClient`` — same method surface over the same REST routes,
using stdlib urllib instead of requests)."""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from ray_tpu.dashboard.job_manager import JobStatus

__all__ = ["JobSubmissionClient", "JobStatus"]


def _find_dashboard_address(address: Optional[str]) -> str:
    if address:
        return address.rstrip("/")
    env = os.environ.get("RAY_TPU_DASHBOARD_ADDRESS")
    if env:
        return env.rstrip("/")
    # resolve from a session dir (RAY_TPU_ADDRESS or the newest session)
    session = os.environ.get("RAY_TPU_SESSION_DIR") \
        or os.environ.get("RAY_TPU_ADDRESS")
    if session:
        path = os.path.join(session, "dashboard.json")
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)["address"].rstrip("/")
    raise RuntimeError(
        "cannot locate the dashboard: pass address= (http://host:port) or "
        "set RAY_TPU_DASHBOARD_ADDRESS / RAY_TPU_ADDRESS")


class JobSubmissionClient:
    def __init__(self, address: Optional[str] = None):
        self.address = _find_dashboard_address(address)

    # ------------------------------------------------------------- http
    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> Any:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.address + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", "")
            except Exception:
                detail = ""
            raise RuntimeError(
                f"{method} {path} failed ({e.code}): {detail}") from None

    # -------------------------------------------------------------- api
    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   priority: str = "normal",
                   elastic: bool = False) -> str:
        """``priority`` (low/normal/high) orders this job in slice
        arbitration — under sustained serve pressure the SliceArbiter
        preempts the LOWEST-priority training job's slice first;
        ``elastic=True`` declares the driver survives that (it wraps
        training in ElasticTrainer and resumes on the shrunken mesh)."""
        out = self._request("POST", "/api/jobs/", {
            "entrypoint": entrypoint, "submission_id": submission_id,
            "metadata": metadata, "runtime_env": runtime_env,
            "priority": priority, "elastic": elastic})
        return out["submission_id"]

    def get_arbiter_status(self) -> Dict[str, Any]:
        """Live slice-arbitration table: who owns which slice and why
        (borrowed-by-serve rows carry the preemption reason)."""
        return self._request("GET", "/api/v0/arbiter")

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._request("GET", "/api/jobs/")

    def get_job_info(self, submission_id: str) -> Dict[str, Any]:
        return self._request("GET", f"/api/jobs/{submission_id}")

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id)["status"]

    def get_job_logs(self, submission_id: str) -> str:
        return self._request(
            "GET", f"/api/jobs/{submission_id}/logs")["logs"]

    def stop_job(self, submission_id: str) -> bool:
        return self._request(
            "POST", f"/api/jobs/{submission_id}/stop")["stopped"]

    def wait_until_status(self, submission_id: str,
                          statuses=JobStatus.TERMINAL,
                          timeout_s: float = 300.0) -> str:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in statuses:
                return status
            time.sleep(0.5)
        raise TimeoutError(
            f"job {submission_id} not in {statuses} after {timeout_s}s")
