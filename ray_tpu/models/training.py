"""GSPMD train-step assembly: model + mesh + rules + optimizer -> one
jitted SPMD program.

This is the TPU-native replacement for the reference's whole
DDP/DeepSpeed integration surface (``train/torch/config.py``,
``examples/deepspeed/deepspeed_torch_trainer.py``): instead of wrapping
the model in a distributed module and an engine, the parallelism is a
(mesh, rule-table) pair; ``jax.jit`` with explicit in/out shardings
compiles the collectives (psum for grads on dp, all-gather/reduce-scatter
for fsdp params) into the step itself.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models.transformer import (
    TransformerConfig, init_params, logical_axes, lm_loss)
from ray_tpu.parallel.sharding import (
    ShardingRules, FSDP_RULES, shard_params, batch_sharding, replicated)


@dataclasses.dataclass
class TrainStepBundle:
    """Everything a worker needs to run sharded training steps."""
    config: TransformerConfig
    mesh: Any
    rules: ShardingRules
    init_fn: Callable[[jax.Array], Dict]       # key -> sharded state
    step_fn: Callable[[Dict, Dict], Tuple[Dict, Dict]]  # (state, batch)
    state_shardings: Dict
    batch_spec: Any

    def init(self, seed: int = 0) -> Dict:
        return self.init_fn(jax.random.PRNGKey(seed))

    def step(self, state: Dict, batch: Dict) -> Tuple[Dict, Dict]:
        if "loss_mask" not in batch:
            batch = dict(batch, loss_mask=jnp.ones_like(
                batch["input_ids"], dtype=jnp.float32))
        return self.step_fn(state, batch)


def _default_optimizer(learning_rate: float, weight_decay: float):
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(learning_rate, b1=0.9, b2=0.95, eps=1e-8,
                    weight_decay=weight_decay),
    )


def make_train_step(config: TransformerConfig, mesh,
                    rules: Optional[ShardingRules] = None,
                    optimizer=None,
                    learning_rate: float = 1e-5,
                    weight_decay: float = 0.0,
                    donate_state: bool = True,
                    remat_policy: Optional[str] = None,
                    ce_chunk_size: Optional[int] = None) -> TrainStepBundle:
    """Build sharded init + train-step functions over ``mesh``.

    The optimizer state inherits each parameter's sharding (ZeRO-style
    optimizer sharding falls out of FSDP rules for free — Adam moments are
    param-shaped pytree leaves).

    ``remat_policy`` / ``ce_chunk_size`` override the config's
    rematerialization policy and fused-CE chunking for this train step
    without touching the caller's config (the compute-path knobs a
    trainer wants to sweep without redefining the model).
    """
    rules = rules if rules is not None else FSDP_RULES
    if remat_policy is not None:
        config = dataclasses.replace(config, remat=None,
                                     remat_policy=remat_policy)
    if ce_chunk_size is not None:
        config = dataclasses.replace(config, ce_chunk_size=ce_chunk_size)
    if optimizer is None:
        optimizer = _default_optimizer(learning_rate, weight_decay)

    axes_tree = logical_axes(config)
    param_sh = shard_params({}, axes_tree, rules, mesh)
    batch_sh = batch_sharding(mesh, rules, ("batch", "sequence"))
    rep = replicated(mesh)

    def init_raw(key):
        params = init_params(config, key)
        opt_state = optimizer.init(params)
        return {"params": params, "opt_state": opt_state,
                "step": jnp.zeros((), jnp.int32)}

    # Optimizer-state leaves that are param-shaped get the param's
    # sharding; scalars/counters replicate. Resolve via a throwaway
    # eval_shape of the whole state.
    state_shapes = jax.eval_shape(init_raw, jax.random.PRNGKey(0))

    flat_params, params_treedef = jax.tree.flatten(
        state_shapes["params"])
    flat_param_sh = jax.tree.flatten(param_sh)[0]
    param_sh_tree = jax.tree.unflatten(params_treedef, flat_param_sh)

    # Optax state (adam mu/nu, etc.) nests whole param-shaped subtrees;
    # substitute each such subtree with the params' sharding tree and
    # replicate everything else (counters). Matching by treedef — not by
    # leaf shape — keeps same-shaped params with different shardings
    # (e.g. wq/wk/wv/wo when n_heads*head_dim == d_model) distinct.
    def is_param_tree(x):
        try:
            return jax.tree.structure(x) == params_treedef
        except Exception:
            return False

    opt_sh = jax.tree.map(
        lambda sub: param_sh_tree if is_param_tree(sub) else rep,
        state_shapes["opt_state"], is_leaf=is_param_tree)

    state_sh = {
        "params": param_sh_tree,
        "opt_state": opt_sh,
        "step": rep,
    }

    init_fn = jax.jit(init_raw, out_shardings=state_sh)

    def step_raw(state, batch):
        def loss_fn(p):
            return lm_loss(config, p, batch, mesh=mesh, rules=rules)
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"])
        updates, new_opt = optimizer.update(
            grads, state["opt_state"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        new_state = {"params": new_params, "opt_state": new_opt,
                     "step": state["step"] + 1}
        metrics = {"loss": loss, "n_tokens": aux["n_tokens"],
                   "grad_norm": optax.global_norm(grads)}
        return new_state, metrics

    step_fn = jax.jit(
        step_raw,
        in_shardings=(state_sh, {"input_ids": batch_sh,
                                 "loss_mask": batch_sh}),
        out_shardings=(state_sh, rep),
        donate_argnums=(0,) if donate_state else (),
    )

    return TrainStepBundle(config=config, mesh=mesh, rules=rules,
                           init_fn=init_fn, step_fn=step_fn,
                           state_shardings=state_sh, batch_spec=batch_sh)


def make_eval_step(config: TransformerConfig, mesh,
                   rules: Optional[ShardingRules] = None,
                   state_shardings=None):
    """Jitted forward-only loss, honoring the train step's layouts."""
    rules = rules if rules is not None else FSDP_RULES
    batch_sh = batch_sharding(mesh, rules, ("batch", "sequence"))
    if state_shardings is not None:
        param_sh = state_shardings["params"]
    else:
        param_sh = shard_params({}, logical_axes(config), rules, mesh)

    @functools.partial(
        jax.jit,
        in_shardings=(param_sh, {"input_ids": batch_sh,
                                 "loss_mask": batch_sh}),
        out_shardings=replicated(mesh))
    def eval_step(params, batch):
        loss, aux = lm_loss(config, params, batch, mesh=mesh, rules=rules)
        return {"loss": loss, "n_tokens": aux["n_tokens"]}
    return eval_step
