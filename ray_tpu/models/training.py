"""GSPMD train-step assembly: model + mesh + rules + optimizer -> one
jitted SPMD program.

This is the TPU-native replacement for the reference's whole
DDP/DeepSpeed integration surface (``train/torch/config.py``,
``examples/deepspeed/deepspeed_torch_trainer.py``): instead of wrapping
the model in a distributed module and an engine, the parallelism is a
(mesh, rule-table) pair; ``jax.jit`` with explicit in/out shardings
compiles the collectives (psum for grads on dp, all-gather/reduce-scatter
for fsdp params) into the step itself.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models.transformer import (
    TransformerConfig, init_params, logical_axes, lm_loss)
from ray_tpu.parallel.quantization import DEFAULT_BLOCK_SIZE, fake_quant
from ray_tpu.parallel.sharding import (
    ShardingRules, FSDP_RULES, shard_params, batch_sharding, replicated,
    flatten_tree, unflatten_like)

GRAD_TRANSPORTS = ("fp32", "int8")


@dataclasses.dataclass
class TrainStepBundle:
    """Everything a worker needs to run sharded training steps."""
    config: TransformerConfig
    mesh: Any
    rules: ShardingRules
    init_fn: Callable[[jax.Array], Dict]       # key -> sharded state
    step_fn: Callable[[Dict, Dict], Tuple[Dict, Dict]]  # (state, batch)
    state_shardings: Dict
    batch_spec: Any
    grad_transport: str = "fp32"
    shard_weight_update: bool = False
    #: live-telemetry cadence (see :meth:`_telemetry`); <= 0 disables
    telemetry_interval_s: float = 0.5
    _tel_last: float = dataclasses.field(default=0.0, repr=False)
    _tel_tokens: float = dataclasses.field(default=0.0, repr=False)
    _tel_steps: int = dataclasses.field(default=0, repr=False)

    def init(self, seed: int = 0) -> Dict:
        return self.init_fn(jax.random.PRNGKey(seed))

    def step(self, state: Dict, batch: Dict) -> Tuple[Dict, Dict]:
        if "loss_mask" not in batch:
            batch = dict(batch, loss_mask=jnp.ones_like(
                batch["input_ids"], dtype=jnp.float32))
        out = self.step_fn(state, batch)
        self._telemetry(batch, out[1])
        return out

    def _telemetry(self, batch: Dict, metrics: Dict) -> None:
        """Per-step training telemetry into the fleet metrics plane —
        the live version of what bench.py records offline. Steps are
        only *counted* on the hot path; every ``telemetry_interval_s``
        the accumulated window is closed: block on the (already
        dispatched) step metrics, then set tokens/s, an MFU gauge from
        the bench FLOP model (flops_per_token x tokens/s over the
        chip's bf16 peak across the mesh), loss and grad norm, and
        observe the mean step wall. Never raises; the interval gate
        keeps device syncs off the steady-state step path."""
        if self.telemetry_interval_s <= 0:
            return
        import time
        now = time.monotonic()
        if not self._tel_last:
            self._tel_last = now
        ids = batch["input_ids"]
        self._tel_tokens += float(ids.size)
        self._tel_steps += 1
        elapsed = now - self._tel_last
        if elapsed < self.telemetry_interval_s:
            return
        tokens, steps = self._tel_tokens, self._tel_steps
        self._tel_last = now
        self._tel_tokens = 0.0
        self._tel_steps = 0
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            m = runtime_metrics()
            jax.block_until_ready(metrics)
            tokens_per_s = tokens / elapsed
            m.train_tokens_per_s.set(tokens_per_s)
            m.train_step_wall.observe(elapsed / steps)
            m.train_loss.set(float(metrics["loss"]))
            m.train_grad_norm.set(float(metrics["grad_norm"]))
            try:
                from ray_tpu.parallel.mesh import chip_spec
                achieved = tokens_per_s * \
                    self.config.flops_per_token(ids.shape[-1])
                peak = chip_spec().bf16_flops * max(1, self.mesh.size)
                m.train_mfu.set(100.0 * achieved / peak)
            except Exception:
                pass
            from ray_tpu.core.global_state import try_global_worker
            w = try_global_worker()
            if w is not None and getattr(w, "metrics_reporter",
                                         None) is not None:
                w.metrics_reporter.maybe_report()
        except Exception:
            pass


def default_optimizer(learning_rate: float, weight_decay: float = 0.0,
                      clip_norm: Optional[float] = 1.0):
    """The standard training optimizer: global-norm clip (when
    ``clip_norm`` is set) chained onto AdamW. ``parallel.plan`` builds
    the SAME optimizer for every lowering so checkpoints round-trip
    between the SPMD step and the MPMD pipeline with identical
    treedefs (the pipeline applies the clip leg manually with the
    cross-stage norm — arithmetically the same update)."""
    adamw = optax.adamw(learning_rate, b1=0.9, b2=0.95, eps=1e-8,
                        weight_decay=weight_decay)
    if clip_norm is None:
        return adamw
    return optax.chain(optax.clip_by_global_norm(clip_norm), adamw)


def _default_optimizer(learning_rate: float, weight_decay: float):
    return default_optimizer(learning_rate, weight_decay, 1.0)


def make_train_step(config: TransformerConfig, mesh,
                    rules: Optional[ShardingRules] = None,
                    optimizer=None,
                    learning_rate: float = 1e-5,
                    weight_decay: float = 0.0,
                    donate_state: bool = True,
                    remat_policy: Optional[str] = None,
                    ce_chunk_size: Optional[int] = None,
                    grad_transport: str = "fp32",
                    shard_weight_update: bool = False,
                    quant_block_size: int = DEFAULT_BLOCK_SIZE,
                    quant_stochastic: bool = False,
                    telemetry_interval_s: float = 0.5
                    ) -> TrainStepBundle:
    """Build sharded init + train-step functions over ``mesh``.

    The optimizer state inherits each parameter's sharding (ZeRO-style
    optimizer sharding falls out of FSDP rules for free — Adam moments are
    param-shaped pytree leaves).

    ``remat_policy`` / ``ce_chunk_size`` override the config's
    rematerialization policy and fused-CE chunking for this train step
    without touching the caller's config (the compute-path knobs a
    trainer wants to sweep without redefining the model).

    Communication-path knobs (the gradient byte path from loss to
    weight):

    - ``grad_transport``: ``"fp32"`` (exact) or ``"int8"`` — gradients
      cross the reduction wire int8 blockwise-quantized (per-block f32
      scales, f32 accumulators; EQuARX, arXiv:2506.17615). Inside one
      SPMD program the reduction itself is compiled by XLA, so the knob
      injects the transport's quantization error via
      ``quantization.fake_quant`` on each gradient leaf — numerically
      the requantize leg of the quantized all-reduce; the eager
      ``collective.quantized_allreduce`` carries real int8 payloads.
      ``quant_block_size`` / ``quant_stochastic`` tune the wire format
      (stochastic rounding makes the quantizer unbiased, keyed per step
      and leaf).
    - ``shard_weight_update``: reduce-scatter gradients over the data
      axes (dp×fsdp), have each replica update only its 1/N flat
      optimizer shard, then all-gather fresh params
      (arXiv:2004.13336). Optimizer state lives in the flat sharded
      layout (1/N per replica even for leaves the rule table
      replicates); ``state["params"]`` keeps its normal layout, so
      eval/checkpoint paths are unchanged. Flat shards are padded to
      whole quant blocks so both transports share one state treedef.
    """
    if grad_transport not in GRAD_TRANSPORTS:
        raise ValueError(f"grad_transport must be one of "
                         f"{GRAD_TRANSPORTS}, got {grad_transport!r}")
    rules = rules if rules is not None else FSDP_RULES
    if remat_policy is not None:
        config = dataclasses.replace(config, remat=None,
                                     remat_policy=remat_policy)
    if ce_chunk_size is not None:
        config = dataclasses.replace(config, ce_chunk_size=ce_chunk_size)
    if optimizer is None:
        optimizer = _default_optimizer(learning_rate, weight_decay)

    axes_tree = logical_axes(config)
    param_sh = shard_params({}, axes_tree, rules, mesh)
    batch_sh = batch_sharding(mesh, rules, ("batch", "sequence"))
    rep = replicated(mesh)

    # Cross-replica sharded weight update: gradients and master-param
    # working copies are flattened to 1-D, padded to n_shards * k quant
    # blocks, and sharded over the data axes. A sharding constraint to
    # ``flat_sh`` on a freshly reduced gradient compiles to the
    # reduce-scatter; the constraint back to the parameter's compute
    # sharding on the updated leaf compiles to the all-gather.
    from jax.sharding import NamedSharding, PartitionSpec as P
    update_axes = tuple(a for a in ("dp", "fsdp") if mesh.shape[a] > 1)
    n_shards = 1
    for a in update_axes:
        n_shards *= mesh.shape[a]
    flat_sh = NamedSharding(mesh, P(update_axes) if update_axes else P())

    def _flatten_tree(tree, constrain_to=None):
        return flatten_tree(tree, n_shards, quant_block_size,
                            constrain_to=constrain_to)

    def init_raw(key):
        params = init_params(config, key)
        if shard_weight_update:
            opt_state = optimizer.init(_flatten_tree(params))
        else:
            opt_state = optimizer.init(params)
        return {"params": params, "opt_state": opt_state,
                "step": jnp.zeros((), jnp.int32)}

    # Optimizer-state leaves that are param-shaped get the param's
    # sharding; scalars/counters replicate. Resolve via a throwaway
    # eval_shape of the whole state.
    state_shapes = jax.eval_shape(init_raw, jax.random.PRNGKey(0))

    flat_params, params_treedef = jax.tree.flatten(
        state_shapes["params"])
    flat_param_sh = jax.tree.flatten(param_sh)[0]
    param_sh_tree = jax.tree.unflatten(params_treedef, flat_param_sh)

    # Optax state (adam mu/nu, etc.) nests whole param-shaped subtrees;
    # substitute each such subtree with the params' sharding tree and
    # replicate everything else (counters). Matching by treedef — not by
    # leaf shape — keeps same-shaped params with different shardings
    # (e.g. wq/wk/wv/wo when n_heads*head_dim == d_model) distinct.
    def is_param_tree(x):
        try:
            return jax.tree.structure(x) == params_treedef
        except Exception:
            return False

    opt_leaf_sh_tree = param_sh_tree
    if shard_weight_update:
        # Flat layout: every optimizer leaf (moments etc.) is a 1-D
        # shard over the data axes, 1/N resident per replica.
        opt_leaf_sh_tree = jax.tree.unflatten(
            params_treedef, [flat_sh] * len(flat_params))
    opt_sh = jax.tree.map(
        lambda sub: opt_leaf_sh_tree if is_param_tree(sub) else rep,
        state_shapes["opt_state"], is_leaf=is_param_tree)

    state_sh = {
        "params": param_sh_tree,
        "opt_state": opt_sh,
        "step": rep,
    }

    init_fn = jax.jit(init_raw, out_shardings=state_sh)

    def _quantize_grads(grads, step):
        """int8 transport: each gradient leaf picks up one wire leg's
        blockwise quantization error (per-step, per-leaf keys when
        stochastic rounding is on)."""
        base = jax.random.fold_in(jax.random.PRNGKey(0x5eed), step) \
            if quant_stochastic else None
        leaves, treedef = jax.tree.flatten(grads)
        out = []
        for i, g in enumerate(leaves):
            key = jax.random.fold_in(base, i) if quant_stochastic else None
            out.append(fake_quant(g, quant_block_size,
                                  quant_stochastic, key))
        return jax.tree.unflatten(treedef, out)

    def step_raw(state, batch):
        def loss_fn(p):
            return lm_loss(config, p, batch, mesh=mesh, rules=rules)
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"])
        if grad_transport == "int8":
            grads = _quantize_grads(grads, state["step"])
        if shard_weight_update:
            # Reduce-scatter grads to flat 1/N shards, update only the
            # local optimizer shard, all-gather fresh params (the
            # constraint back to the param sharding via out_shardings).
            gflat = _flatten_tree(grads, constrain_to=flat_sh)
            pflat = _flatten_tree(state["params"], constrain_to=flat_sh)
            updates, new_opt = optimizer.update(
                gflat, state["opt_state"], pflat)
            new_pflat = optax.apply_updates(pflat, updates)
            new_params = unflatten_like(state["params"], new_pflat)
        else:
            updates, new_opt = optimizer.update(
                grads, state["opt_state"], state["params"])
            new_params = optax.apply_updates(state["params"], updates)
        new_state = {"params": new_params, "opt_state": new_opt,
                     "step": state["step"] + 1}
        metrics = {"loss": loss, "n_tokens": aux["n_tokens"],
                   "grad_norm": optax.global_norm(grads)}
        return new_state, metrics

    step_fn = jax.jit(
        step_raw,
        in_shardings=(state_sh, {"input_ids": batch_sh,
                                 "loss_mask": batch_sh}),
        out_shardings=(state_sh, rep),
        donate_argnums=(0,) if donate_state else (),
    )

    return TrainStepBundle(config=config, mesh=mesh, rules=rules,
                           init_fn=init_fn, step_fn=step_fn,
                           state_shardings=state_sh, batch_spec=batch_sh,
                           grad_transport=grad_transport,
                           shard_weight_update=shard_weight_update,
                           telemetry_interval_s=telemetry_interval_s)


def make_eval_step(config: TransformerConfig, mesh,
                   rules: Optional[ShardingRules] = None,
                   state_shardings=None):
    """Jitted forward-only loss, honoring the train step's layouts."""
    rules = rules if rules is not None else FSDP_RULES
    batch_sh = batch_sharding(mesh, rules, ("batch", "sequence"))
    if state_shardings is not None:
        param_sh = state_shardings["params"]
    else:
        param_sh = shard_params({}, logical_axes(config), rules, mesh)

    @functools.partial(
        jax.jit,
        in_shardings=(param_sh, {"input_ids": batch_sh,
                                 "loss_mask": batch_sh}),
        out_shardings=replicated(mesh))
    def eval_step(params, batch):
        loss, aux = lm_loss(config, params, batch, mesh=mesh, rules=rules)
        return {"loss": loss, "n_tokens": aux["n_tokens"]}
    return eval_step
