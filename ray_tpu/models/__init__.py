"""Model family: TPU-first transformer LMs.

The reference ships no in-tree model implementations (its models arrive
through torch user code and HF integrations, e.g. the GPT-J recipe
``release/air_examples/gptj_deepspeed_finetuning/``). This framework makes
the flagship models first-class so trainers/serving/benchmarks share one
GSPMD-ready implementation:

- functional param-pytree models (no framework object graph): ``init`` /
  ``apply`` plus a parallel pytree of logical sharding axes consumed by
  ``ray_tpu.parallel.sharding.shard_params``;
- ``lax.scan`` over stacked layer params (O(1) compile time in depth) with
  ``jax.checkpoint`` rematerialization per block;
- attention via ``ray_tpu.ops`` (Pallas flash on TPU, ring attention when
  the mesh has a nontrivial ``sp`` axis).
"""

from ray_tpu.models.transformer import (
    TransformerConfig,
    Transformer,
    lm_loss,
    hidden_states,
    init_params,
    init_kv_cache,
    prefill,
    decode_step,
    logical_axes,
    REMAT_POLICIES,
    remat_policy_fn,
)
from ray_tpu.models.registry import get_config, register_config, MODEL_CONFIGS
from ray_tpu.models.training import (
    make_train_step,
    make_eval_step,
    TrainStepBundle,
)

__all__ = [
    "TransformerConfig",
    "Transformer",
    "lm_loss",
    "hidden_states",
    "init_params",
    "init_kv_cache",
    "prefill",
    "decode_step",
    "logical_axes",
    "REMAT_POLICIES",
    "remat_policy_fn",
    "get_config",
    "register_config",
    "MODEL_CONFIGS",
    "make_train_step",
    "make_eval_step",
    "TrainStepBundle",
]
