"""Mixture-of-experts MLP with capacity-based top-1 (Switch) routing.

TPU-first dispatch: token->expert movement is expressed as einsums over a
dispatch one-hot ``[tokens, experts, capacity]`` (the flaxformer/Switch
formulation). With expert weights sharded on the ``ep`` mesh axis and
tokens on ``dp``/``fsdp``, XLA lowers the two boundary einsums to
all-to-alls over ICI — no hand-written NCCL alltoall like torch MoE
stacks (reference has no in-tree MoE; SURVEY.md §2.5 commits the ``ep``
axis here).

Static shapes throughout (capacity fixes the per-expert token count, the
overflow is dropped and carried by the residual), so the whole layer
jits into the one GSPMD program like everything else.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def moe_mlp(c, lp, h):
    """h: [batch, seq, d_model] (compute dtype). Returns (out, aux_loss).

    lp carries ``moe_wg [D,E]``, ``moe_wi [E,D,F]``, ``moe_wo [E,F,D]``.
    aux_loss is the Switch load-balancing term (encourages uniform
    routing; weight it into the training loss).
    """
    dt = c.dtype
    B, S, D = h.shape
    E = c.n_experts
    N = B * S
    capacity = max(1, int(c.capacity_factor * N / E))
    x = h.reshape(N, D)

    logits = jnp.dot(x, lp["moe_wg"].astype(dt)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)              # [N, E]
    gate = jnp.max(probs, axis=-1)                       # top-1 weight
    expert = jnp.argmax(probs, axis=-1)                  # [N]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)

    # position of each token within its expert's buffer; tokens past
    # capacity are dropped (their residual passes through unchanged)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0      # [N, E]
    keep = ((pos >= 0.0) & (pos < capacity)).astype(jnp.float32)
    slot = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    # [N, E, capacity] dispatch one-hot
    dispatch = jax.nn.one_hot(slot, capacity, dtype=jnp.float32) \
        * (onehot * keep)[..., None]
    combine = dispatch * gate[:, None, None]

    # boundary einsums: tokens-sharded <-> expert-sharded (all-to-all)
    xin = jnp.einsum("nec,nd->ecd", dispatch.astype(dt), x)
    hmid = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xin,
                                  lp["moe_wi"].astype(dt)))
    xout = jnp.einsum("ecf,efd->ecd", hmid, lp["moe_wo"].astype(dt))
    y = jnp.einsum("nec,ecd->nd", combine.astype(dt), xout)

    # Switch aux loss: E * sum_e mean(frac routed to e) * mean(prob e)
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    return y.reshape(B, S, D), aux


def moe_param_shapes(c):
    """(name -> shape) for one layer's MoE parameters."""
    return {
        "moe_wg": (c.d_model, c.n_experts),
        "moe_wi": (c.n_experts, c.d_model, c.d_ff),
        "moe_wo": (c.n_experts, c.d_ff, c.d_model),
    }


def moe_logical_axes():
    return {
        "moe_wg": ("layers", "embed", None),
        "moe_wi": ("layers", "expert", "embed", "mlp"),
        "moe_wo": ("layers", "expert", "mlp", "embed"),
    }
