"""Decoder-only transformer LM, TPU-first.

Supports two block styles behind one config:

- ``"gptj"`` — parallel attention+MLP residual off a single LayerNorm
  (GPT-J 6B: rotary over the first 64 of 256 head dims, untied lm_head
  with bias). The flagship matches the reference's GPT-J fine-tune recipe
  (``release/air_examples/gptj_deepspeed_finetuning/``) architecturally.
- ``"llama"`` — sequential pre-RMSNorm blocks, SwiGLU MLP, full-dim neox
  rotary, optional GQA (num_kv_heads < num_heads).

Design (TPU-first, not a port):
- params are a plain dict pytree; per-layer weights are STACKED on a
  leading ``layers`` axis and the forward pass is one ``lax.scan`` over
  layers (+ ``jax.checkpoint`` per block) — constant compile time in
  depth, XLA-friendly.
- every weight has an entry in :func:`logical_axes` — the same treedef
  with tuples of logical names ("embed", "mlp", "heads", "vocab", …);
  ``parallel.sharding.ShardingRules`` maps those to mesh axes, so DP /
  FSDP / TP / SP are rule-table changes, not model changes.
- master params live in f32; ``config.dtype`` (bf16 on TPU) is the
  compute dtype, cast at use sites so the MXU sees bf16 while layernorm
  statistics and the softmax stay f32 (ops layer contract).
- rematerialization is a named policy (``remat_policy``), not a bool:
  ``"dots"`` (default) saves projection/MLP matmul outputs and the
  attention output (``checkpoint_name``) while recomputing elementwise
  work and attention internals in the backward; ``"full"``/``"none"``
  are the old all-or-nothing extremes; ``"offload"`` parks block inputs
  in pinned host memory.
- the LM loss never materializes the full ``[b, s, vocab]`` logits
  tensor: ``ops.fused_lm_head_loss`` projects + reduces in sequence
  chunks of ``ce_chunk_size`` tokens (``ce_chunk_size=0`` restores the
  materialized-logits reference path).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ray_tpu.ops import (
    apply_rotary,
    layer_norm,
    multihead_attention,
    paged_attention,
    ring_attention,
    rms_norm,
    rotary_table,
    cross_entropy_loss,
    fused_lm_head_loss,
)

REMAT_POLICIES = ("full", "none", "dots", "dots_all", "offload")


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50400
    d_model: int = 4096
    n_layers: int = 28
    n_heads: int = 16
    head_dim: int = 256
    n_kv_heads: Optional[int] = None        # GQA; None = n_heads
    d_ff: int = 16384
    max_seq_len: int = 2048
    rotary_dim: int = 64                     # gptj rotates a prefix
    rope_base: float = 10000.0
    block_style: str = "gptj"               # "gptj" | "llama"
    dtype: Any = jnp.bfloat16                # compute dtype
    # Legacy bool (True -> "full", False -> "none"); None defers to
    # remat_policy. Kept so existing configs keep their exact behavior.
    remat: Optional[bool] = None
    remat_policy: str = "dots"               # see REMAT_POLICIES
    # Fused LM-head loss: tokens per CE chunk (0 = materialized logits).
    ce_chunk_size: int = 512
    attn_impl: str = "auto"                  # ops.multihead_attention impl
    attn_block_q: int = 0                    # 0 = chip-aware default
    attn_block_k: int = 0
    # Paged decode path (serving): "auto" dispatches the Pallas paged
    # kernel on TPU (interpret mode off-TPU when forced to "kernel");
    # "reference" pins the pure-XLA gather. paged_block_r = 0 picks the
    # chip-aware query-row block (ops.paged_flash.default_paged_block_r).
    paged_impl: str = "auto"
    paged_block_r: int = 0
    # Chunked prefill runs the same paged kernel at chunk*(heads/kv)
    # query rows — far more than decode's heads/kv — so a larger row
    # block can win there. 0 = use paged_block_r; the engine autotunes
    # this at long windows (allowing > 128) and records the winner.
    paged_block_r_prefill: int = 0
    # MoE (0 = dense): every layer's MLP becomes n_experts experts with
    # Switch top-1 routing, weights sharded on the ep mesh axis
    n_experts: int = 0
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def resolved_remat_policy(self) -> str:
        """Effective remat policy, honoring the legacy ``remat`` bool."""
        if self.remat is not None:
            return "full" if self.remat else "none"
        return self.remat_policy

    @property
    def num_params(self) -> int:
        """Parameter count (for MFU accounting)."""
        e, v, h = self.d_model, self.vocab_size, self.n_heads * self.head_dim
        kvh = self.kv_heads * self.head_dim
        per_layer = e * h + 2 * e * kvh + h * e          # q, k, v, o
        if self.n_experts:
            per_layer += e * self.n_experts \
                + self.n_experts * 2 * e * self.d_ff     # router + experts
            per_layer += 2 * e                           # norms
        elif self.block_style == "llama":
            per_layer += 3 * e * self.d_ff + 2 * e       # swiglu + 2 rmsnorm
        else:
            per_layer += 2 * e * self.d_ff + self.d_ff + e  # fc biases
            per_layer += 2 * e                           # ln scale+bias
        total = v * e + self.n_layers * per_layer
        total += e if self.block_style == "llama" else 2 * e  # final norm
        total += e * v + (v if self.block_style == "gptj" else 0)  # lm head
        return total

    @property
    def num_active_params(self) -> int:
        """Params touched per token: with Switch top-1 routing only ONE
        expert's MLP runs per token — FLOPs must not count the rest."""
        if not self.n_experts:
            return self.num_params
        inactive = self.n_layers * (self.n_experts - 1) \
            * 2 * self.d_model * self.d_ff
        return self.num_params - inactive

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Approximate train FLOPs/token (6·N active params + attention)."""
        s = seq_len or self.max_seq_len
        attn = 12 * self.n_layers * self.n_heads * self.head_dim * s
        return 6.0 * self.num_active_params + attn


# ------------------------------------------------------------------ init
def _dense_init(key, shape, scale=0.02):
    return scale * jax.random.normal(key, shape, jnp.float32)


def init_params(config: TransformerConfig, key) -> Dict:
    c = config
    keys = jax.random.split(key, 9)
    h = c.n_heads * c.head_dim
    kvh = c.kv_heads * c.head_dim
    L = c.n_layers

    def stack(k, shape, scale=0.02):
        return _dense_init(k, (L,) + shape, scale)

    out_scale = 0.02 / (2 * L) ** 0.5    # scaled residual-out init
    layers: Dict[str, jnp.ndarray] = {
        "wq": stack(keys[0], (c.d_model, h)),
        "wk": stack(keys[1], (c.d_model, kvh)),
        "wv": stack(keys[2], (c.d_model, kvh)),
        "wo": stack(keys[3], (h, c.d_model), out_scale),
    }
    if c.n_experts:
        from ray_tpu.models.moe import moe_param_shapes
        mk = jax.random.split(keys[6], 3)
        layers.update({
            name: stack(mk[i], shape,
                        out_scale if name == "moe_wo" else 0.02)
            for i, (name, shape) in
            enumerate(sorted(moe_param_shapes(c).items()))})
        if c.block_style == "llama":
            layers.update({
                "attn_norm": jnp.ones((L, c.d_model), jnp.float32),
                "mlp_norm": jnp.ones((L, c.d_model), jnp.float32)})
            final = {"scale": jnp.ones((c.d_model,), jnp.float32)}
            head = {"w": _dense_init(keys[8], (c.d_model, c.vocab_size))}
        else:
            layers.update({
                "ln_scale": jnp.ones((L, c.d_model), jnp.float32),
                "ln_bias": jnp.zeros((L, c.d_model), jnp.float32)})
            final = {"scale": jnp.ones((c.d_model,), jnp.float32),
                     "bias": jnp.zeros((c.d_model,), jnp.float32)}
            head = {"w": _dense_init(keys[8], (c.d_model, c.vocab_size)),
                    "b": jnp.zeros((c.vocab_size,), jnp.float32)}
    elif c.block_style == "llama":
        layers.update({
            "w_gate": stack(keys[4], (c.d_model, c.d_ff)),
            "w_up": stack(keys[5], (c.d_model, c.d_ff)),
            "w_down": stack(keys[6], (c.d_ff, c.d_model), out_scale),
            "attn_norm": jnp.ones((L, c.d_model), jnp.float32),
            "mlp_norm": jnp.ones((L, c.d_model), jnp.float32),
        })
        final = {"scale": jnp.ones((c.d_model,), jnp.float32)}
        head = {"w": _dense_init(keys[8], (c.d_model, c.vocab_size))}
    else:
        layers.update({
            "fc_in": stack(keys[4], (c.d_model, c.d_ff)),
            "fc_in_b": jnp.zeros((L, c.d_ff), jnp.float32),
            "fc_out": stack(keys[5], (c.d_ff, c.d_model), out_scale),
            "fc_out_b": jnp.zeros((L, c.d_model), jnp.float32),
            "ln_scale": jnp.ones((L, c.d_model), jnp.float32),
            "ln_bias": jnp.zeros((L, c.d_model), jnp.float32),
        })
        final = {"scale": jnp.ones((c.d_model,), jnp.float32),
                 "bias": jnp.zeros((c.d_model,), jnp.float32)}
        head = {"w": _dense_init(keys[8], (c.d_model, c.vocab_size)),
                "b": jnp.zeros((c.vocab_size,), jnp.float32)}

    return {
        "embed": _dense_init(keys[7], (c.vocab_size, c.d_model)),
        "layers": layers,
        "final_norm": final,
        "lm_head": head,
    }


def logical_axes(config: TransformerConfig) -> Dict:
    """Pytree (same treedef as params) of logical-axis tuples."""
    c = config
    common = {
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv"),
        "wv": ("layers", "embed", "kv"),
        "wo": ("layers", "heads", "embed"),
    }
    if c.n_experts:
        from ray_tpu.models.moe import moe_logical_axes
        layers = {**common, **moe_logical_axes()}
        if c.block_style == "llama":
            layers.update({"attn_norm": ("layers", "embed"),
                           "mlp_norm": ("layers", "embed")})
            final = {"scale": ("embed",)}
            head = {"w": ("embed", "vocab")}
        else:
            layers.update({"ln_scale": ("layers", "embed"),
                           "ln_bias": ("layers", "embed")})
            final = {"scale": ("embed",), "bias": ("embed",)}
            head = {"w": ("embed", "vocab"), "b": ("vocab",)}
    elif c.block_style == "llama":
        layers = {**common,
                  "w_gate": ("layers", "embed", "mlp"),
                  "w_up": ("layers", "embed", "mlp"),
                  "w_down": ("layers", "mlp", "embed"),
                  "attn_norm": ("layers", "embed"),
                  "mlp_norm": ("layers", "embed")}
        final = {"scale": ("embed",)}
        head = {"w": ("embed", "vocab")}
    else:
        layers = {**common,
                  "fc_in": ("layers", "embed", "mlp"),
                  "fc_in_b": ("layers", "mlp"),
                  "fc_out": ("layers", "mlp", "embed"),
                  "fc_out_b": ("layers", "embed"),
                  "ln_scale": ("layers", "embed"),
                  "ln_bias": ("layers", "embed")}
        final = {"scale": ("embed",), "bias": ("embed",)}
        head = {"w": ("embed", "vocab"), "b": ("vocab",)}
    return {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": final,
        "lm_head": head,
    }


# ---------------------------------------------------------------- remat
def remat_policy_fn(name: str):
    """Map a policy name to a ``jax.checkpoint`` saveable policy.

    Returns ``None`` for "full" (save nothing — recompute everything);
    "none" (don't checkpoint at all) is the caller's branch. "dots" saves
    matmul outputs WITHOUT batch dims (qkv/out projections, MLP matmuls —
    weight-stationary dots worth keeping) plus the named attention output,
    so neither the flash kernel nor the O(s²) reference attention is
    re-run in the backward; the quadratic score matrices (dots WITH batch
    dims) are still recomputed. "dots_all" additionally saves those.
    "offload" parks block inputs in pinned host memory and saves the
    attention output on device.
    """
    cp = jax.checkpoint_policies
    save_attn = cp.save_only_these_names("attn_out")
    if name == "full":
        return None
    if name == "dots":
        return cp.save_from_both_policies(
            cp.dots_with_no_batch_dims_saveable, save_attn)
    if name == "dots_all":
        return cp.save_from_both_policies(cp.dots_saveable, save_attn)
    if name == "offload":
        return cp.save_and_offload_only_these_names(
            names_which_can_be_saved=["attn_out"],
            names_which_can_be_offloaded=["block_in"],
            offload_src="device", offload_dst="pinned_host")
    raise ValueError(
        f"unknown remat policy {name!r}; have {REMAT_POLICIES}")


# --------------------------------------------------------------- forward
def _attention(c: TransformerConfig, q, k, v, mesh, rules):
    """Dispatch attention: ring over the sp axis when it's nontrivial,
    otherwise the flash/reference dispatcher (ops layer)."""
    sp_axis = rules.get("sequence") if rules else None
    if mesh is not None and sp_axis is not None and sp_axis in mesh.shape \
            and mesh.shape[sp_axis] > 1:
        from jax.sharding import PartitionSpec as P
        from ray_tpu.util.jax_compat import shard_map
        batch_axes = rules.get("batch")
        spec = P(batch_axes, sp_axis, None, None)
        fn = shard_map(
            functools.partial(ring_attention, axis_name=sp_axis,
                              causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return fn(q, k, v)
    return multihead_attention(
        q, k, v, causal=True, impl=c.attn_impl,
        block_q=c.attn_block_q, block_k=c.attn_block_k)


def _attn_sublayer(c, h, lp, sin, cos, layout, mesh, rules):
    """qkv projection → rotary → GQA repeat → attention → output proj.
    Shared by both block styles (only the rotary layout differs)."""
    e = h.shape[-1]
    dt = c.dtype

    def proj(w, n):
        return jnp.einsum("bse,ehd->bshd", h.astype(dt),
                          w.reshape(e, n, -1).astype(dt))
    q = proj(lp["wq"], c.n_heads)
    k = proj(lp["wk"], c.kv_heads)
    v = proj(lp["wv"], c.kv_heads)
    q = apply_rotary(q, sin, cos, layout=layout)
    k = apply_rotary(k, sin, cos, layout=layout)
    if c.kv_heads != c.n_heads:
        rep = c.n_heads // c.kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    att = _attention(c, q, k, v, mesh, rules)
    att = checkpoint_name(att, "attn_out")
    return jnp.einsum("bshd,hde->bse", att,
                      lp["wo"].reshape(c.n_heads, c.head_dim, e).astype(dt))


def _mlp_sublayer(c, h, lp):
    """Dense or MoE MLP on normed input h; returns (out, moe_aux)."""
    dt = c.dtype
    if c.n_experts:
        from ray_tpu.models.moe import moe_mlp
        return moe_mlp(c, lp, h.astype(dt))
    if c.block_style == "llama":
        gate = jax.nn.silu(jnp.dot(h, lp["w_gate"].astype(dt)))
        up = jnp.dot(h, lp["w_up"].astype(dt))
        return jnp.dot(gate * up, lp["w_down"].astype(dt)), 0.0
    mlp = jnp.dot(h.astype(dt), lp["fc_in"].astype(dt)) \
        + lp["fc_in_b"].astype(dt)
    mlp = jax.nn.gelu(mlp)
    return jnp.dot(mlp, lp["fc_out"].astype(dt)) \
        + lp["fc_out_b"].astype(dt), 0.0


def _gptj_block(c, x, lp, sin, cos, mesh, rules):
    x = checkpoint_name(x, "block_in")
    h = layer_norm(x, lp["ln_scale"], lp["ln_bias"])
    att = _attn_sublayer(c, h, lp, sin, cos, "gptj", mesh, rules)
    mlp, aux = _mlp_sublayer(c, h, lp)
    return x + (att + mlp).astype(x.dtype), aux


def _llama_block(c, x, lp, sin, cos, mesh, rules):
    dt = c.dtype
    x = checkpoint_name(x, "block_in")
    h = rms_norm(x, lp["attn_norm"])
    att = _attn_sublayer(c, h, lp, sin, cos, "neox", mesh, rules)
    x = x + att.astype(x.dtype)
    h2 = rms_norm(x, lp["mlp_norm"]).astype(dt)
    mlp, aux = _mlp_sublayer(c, h2, lp)
    return x + mlp.astype(x.dtype), aux


def run_layers(config: TransformerConfig, layer_params: Dict,
               x: jnp.ndarray, mesh=None, rules=None):
    """Scan the transformer blocks in ``layer_params`` (leaves stacked
    ``[n, ...]``) over hidden states ``x``: (b, s, e) -> ((b, s, e),
    moe_aux). The trunk shared by :func:`hidden_states` and the
    pipeline-stage forward (a stage's trunk is a contiguous slice of
    the stacked layer leaves — same scan, fewer layers)."""
    c = config
    seq = x.shape[1]
    sin, cos = rotary_table(
        seq, c.rotary_dim if c.block_style == "gptj" else c.head_dim,
        c.rope_base)

    block = _gptj_block if c.block_style == "gptj" else _llama_block
    body = functools.partial(block, c, sin=sin, cos=cos,
                             mesh=mesh, rules=rules)
    policy = c.resolved_remat_policy
    if policy != "none":
        body = jax.checkpoint(body, policy=remat_policy_fn(policy))

    def scan_fn(carry, lp):
        out, aux = body(carry, lp)
        if mesh is not None and rules is not None:
            from ray_tpu.parallel.sharding import constrain
            out = constrain(out, mesh, rules, ("batch", "sequence", None))
        return out, aux

    x, layer_aux = jax.lax.scan(scan_fn, x, layer_params)
    return x, (jnp.sum(layer_aux) if c.n_experts else 0.0)


def _final_norm(config: TransformerConfig, params: Dict, x: jnp.ndarray):
    fn = params["final_norm"]
    if config.block_style == "llama":
        return rms_norm(x, fn["scale"])
    return layer_norm(x, fn["scale"], fn["bias"])


def hidden_states(config: TransformerConfig, params: Dict,
                  input_ids: jnp.ndarray, mesh=None, rules=None):
    """Embed -> blocks -> final norm: (b, s) int32 -> ((b, s, e), moe_aux).

    The shared trunk under both :func:`apply` (which adds the LM-head
    projection) and :func:`lm_loss` (which fuses the projection into the
    chunked loss so full logits never materialize).
    """
    c = config
    x = jnp.take(params["embed"], input_ids, axis=0).astype(c.dtype)
    x, moe_aux = run_layers(c, params["layers"], x, mesh=mesh, rules=rules)
    return _final_norm(c, params, x), moe_aux


def apply(config: TransformerConfig, params: Dict, input_ids: jnp.ndarray,
          mesh=None, rules=None, return_moe_aux: bool = False):
    """Forward pass: (batch, seq) int32 -> (batch, seq, vocab) logits.

    Always returns logits; with ``return_moe_aux=True`` returns
    ``(logits, moe_aux_loss)`` (0.0 for dense configs). ``mesh``/``rules``
    enable in-graph sharding constraints and ring attention; both
    optional (single-device path needs neither).
    """
    c = config
    x, moe_aux = hidden_states(c, params, input_ids, mesh=mesh, rules=rules)
    logits = jnp.dot(x.astype(c.dtype),
                     params["lm_head"]["w"].astype(c.dtype))
    if c.block_style != "llama":
        logits = logits + params["lm_head"]["b"].astype(c.dtype)
    if return_moe_aux:
        return logits, moe_aux
    return logits


def lm_loss(config: TransformerConfig, params: Dict, batch: Dict,
            mesh=None, rules=None) -> Tuple[jnp.ndarray, Dict]:
    """Next-token LM loss. batch: {"input_ids": (b,s) int32,
    "loss_mask": optional (b,s)}. Returns (loss, aux).

    With ``config.ce_chunk_size > 0`` (default) the LM-head projection is
    fused into the chunked cross entropy (``ops.fused_lm_head_loss``) —
    the full float32 logits tensor is never resident. ``ce_chunk_size=0``
    restores the materialized-logits reference path.
    """
    c = config
    ids = batch["input_ids"]
    labels = ids[:, 1:]
    mask = batch.get("loss_mask")
    mask = mask[:, 1:] if mask is not None else None
    # Chunking scans over the sequence axis; when that axis is SHARDED
    # (sp > 1, the ring-attention meshes) per-chunk slicing would force
    # the partitioner to regather every chunk — keep materialized logits
    # there, fuse everywhere else.
    sp_axis = rules.get("sequence") if rules else None
    seq_sharded = (mesh is not None and sp_axis is not None
                   and sp_axis in mesh.shape and mesh.shape[sp_axis] > 1)
    if c.ce_chunk_size and not seq_sharded:
        x, moe_aux = hidden_states(c, params, ids, mesh=mesh, rules=rules)
        head = params["lm_head"]
        loss, n = fused_lm_head_loss(
            x.astype(c.dtype)[:, :-1], head["w"], labels,
            head_bias=head.get("b"), mask=mask,
            chunk_size=c.ce_chunk_size)
    else:
        logits, moe_aux = apply(c, params, ids, mesh=mesh, rules=rules,
                                return_moe_aux=True)
        loss, n = cross_entropy_loss(logits[:, :-1], labels, mask=mask)
    aux = {"n_tokens": n}
    if c.n_experts:
        loss = loss + c.moe_aux_weight * moe_aux
        aux["moe_aux"] = moe_aux
    return loss, aux


# --------------------------------------------------- pipeline stages
# MPMD pipeline parallelism (parallel/mpmd_pipeline.py) splits the model
# into S separately-compiled stage programs: stage 0 owns the embedding
# plus the first trunk slice, middle stages own trunk slices, the last
# stage owns its slice plus final norm and LM head (fused into the loss,
# like lm_loss). Because per-layer weights are STACKED on the leading
# ``layers`` axis, a stage's parameters are literally ``leaf[lo:hi]`` —
# no re-initialization, and a stage slice of ``init_params(key)`` is
# bit-identical to the single-program model's weights.

def stage_layer_ranges(n_layers: int, n_stages: int):
    """Near-even contiguous ``[lo, hi)`` layer ranges, earlier stages
    taking the remainder (they also carry the embedding)."""
    if not 1 <= n_stages <= n_layers:
        raise ValueError(
            f"n_stages must be in [1, {n_layers}], got {n_stages}")
    base, rem = divmod(n_layers, n_stages)
    ranges, lo = [], 0
    for s in range(n_stages):
        hi = lo + base + (1 if s < rem else 0)
        ranges.append((lo, hi))
        lo = hi
    return ranges


def stage_slice_params(config: TransformerConfig, params: Dict,
                       stage: int, n_stages: int) -> Dict:
    """Slice a full parameter pytree down to one pipeline stage's
    weights: trunk-range of the stacked layer leaves, plus the
    embedding (stage 0) / final norm + LM head (last stage)."""
    if config.n_experts:
        raise NotImplementedError(
            "pipeline stage splitting does not support MoE configs "
            "(the aux loss would need cross-stage wiring)")
    lo, hi = stage_layer_ranges(config.n_layers, n_stages)[stage]
    out: Dict = {"layers": jax.tree.map(lambda a: a[lo:hi],
                                        params["layers"])}
    if stage == 0:
        out["embed"] = params["embed"]
    if stage == n_stages - 1:
        out["final_norm"] = params["final_norm"]
        out["lm_head"] = params["lm_head"]
    return out


def merge_stage_params(config: TransformerConfig,
                       chunk_params: Dict[int, Dict]) -> Dict:
    """Inverse of :func:`stage_slice_params`: reassemble the canonical
    single-program parameter pytree from per-chunk slices keyed by
    global chunk index ``0..K-1`` (``K = len(chunk_params)``). Works on
    any param-SHAPED tree (Adam moments included), so the pipeline
    checkpoint merge reuses it for optimizer state."""
    if not chunk_params:
        raise ValueError("missing chunks: got an empty chunk set")
    K = max(chunk_params) + 1
    missing = [c for c in range(K) if c not in chunk_params]
    if missing or "final_norm" not in chunk_params[K - 1]:
        raise ValueError(
            f"missing chunks: have {sorted(chunk_params)}, need a "
            f"contiguous 0..K-1 set ending in the final-norm/LM-head "
            f"chunk")
    layer_trees = [chunk_params[c]["layers"] for c in range(K)]
    out: Dict = {"layers": jax.tree.map(
        lambda *xs: jnp.concatenate(xs, axis=0), *layer_trees)}
    out["embed"] = chunk_params[0]["embed"]
    out["final_norm"] = chunk_params[K - 1]["final_norm"]
    out["lm_head"] = chunk_params[K - 1]["lm_head"]
    return out


def stage_forward(config: TransformerConfig, stage: int, n_stages: int,
                  stage_params: Dict, inp: jnp.ndarray,
                  mesh=None, rules=None) -> jnp.ndarray:
    """One stage's forward: stage 0 takes (b, s) int32 token ids and
    embeds them; later stages take the upstream (b, s, e) activation.
    The last stage applies the final norm, so its output feeds
    :func:`stage_loss` (or an LM-head projection) directly."""
    c = config
    if stage == 0:
        x = jnp.take(stage_params["embed"], inp, axis=0).astype(c.dtype)
    else:
        x = inp.astype(c.dtype)
    x, _ = run_layers(c, stage_params["layers"], x, mesh=mesh, rules=rules)
    if stage == n_stages - 1:
        x = _final_norm(c, stage_params, x)
    return x


def stage_loss(config: TransformerConfig, stage_params: Dict,
               h: jnp.ndarray, input_ids: jnp.ndarray,
               loss_mask: Optional[jnp.ndarray] = None):
    """Last-stage LM loss from final-norm'd hidden states ``h``: the
    same fused-projection tail as :func:`lm_loss` (ce_chunk_size > 0)
    or the materialized-logits reference path. Returns (loss, n)."""
    c = config
    labels = input_ids[:, 1:]
    mask = loss_mask[:, 1:] if loss_mask is not None else None
    head = stage_params["lm_head"]
    if c.ce_chunk_size:
        return fused_lm_head_loss(
            h.astype(c.dtype)[:, :-1], head["w"], labels,
            head_bias=head.get("b"), mask=mask,
            chunk_size=c.ce_chunk_size)
    logits = jnp.dot(h.astype(c.dtype), head["w"].astype(c.dtype))
    if c.block_style != "llama":
        logits = logits + head["b"].astype(c.dtype)
    return cross_entropy_loss(logits[:, :-1], labels, mask=mask)


# ------------------------------------------------------- inference (KV)
# The serving decode path: a paged KV cache ([num_blocks, block_size,
# kv_heads, head_dim] per layer, block table per sequence) written by
# chunked prefill and batched single-token decode steps. Both entry
# points are shape-stable — jit them once at the engine's fixed
# (batch, chunk, table) shapes and admission never recompiles.

def init_kv_cache(config: TransformerConfig, num_blocks: int,
                  block_size: int) -> Dict[str, jnp.ndarray]:
    """Allocate the paged KV cache: ``{"k", "v"}`` of shape
    ``[n_layers, num_blocks, block_size, kv_heads, head_dim]`` in the
    compute dtype. Zero-filled; a zero key scores 0 pre-softmax, so
    reserved/trash blocks are numerically harmless."""
    c = config
    shape = (c.n_layers, num_blocks, block_size, c.kv_heads, c.head_dim)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def _paged_attn_sublayer(c, h, lp, sin, cos, layout, kc, vc,
                         block_tables, positions, write_mask, lens):
    """Decode-path attention sublayer: project qkv for the new tokens,
    rotate at their absolute positions, write k/v into the cache blocks,
    then attend against the (now-updated) paged cache. ``lens`` is the
    per-sequence live token count after this call's writes — the Pallas
    kernel skips whole cache blocks past it. Returns
    (attn_out, kc, vc)."""
    e = h.shape[-1]
    dt = c.dtype

    def proj(w, n):
        return jnp.einsum("bse,ehd->bshd", h.astype(dt),
                          w.reshape(e, n, -1).astype(dt))
    q = proj(lp["wq"], c.n_heads)
    k = proj(lp["wk"], c.kv_heads)
    v = proj(lp["wv"], c.kv_heads)
    q = apply_rotary(q, sin, cos, positions=positions, layout=layout)
    k = apply_rotary(k, sin, cos, positions=positions, layout=layout)

    n_blocks, bs = kc.shape[0], kc.shape[1]
    bid = jnp.take_along_axis(block_tables, positions // bs, axis=1)
    slot = positions % bs
    # invalid (padded) chunk positions scatter out of bounds -> dropped
    bid = jnp.where(write_mask, bid, n_blocks)
    kc = kc.at[bid, slot].set(k.astype(kc.dtype), mode="drop")
    vc = vc.at[bid, slot].set(v.astype(vc.dtype), mode="drop")

    # h.shape[1] is static under jit: > 1 means a prefill chunk, whose
    # much larger query-row count can carry a bigger row block than the
    # single-token decode step compiled from this same sublayer
    br = c.paged_block_r_prefill \
        if (h.shape[1] > 1 and c.paged_block_r_prefill) \
        else c.paged_block_r
    att = paged_attention(q, kc, vc, block_tables, positions,
                          lens=lens, impl=c.paged_impl,
                          block_r=br or None)
    out = jnp.einsum("bshd,hde->bse", att,
                     lp["wo"].reshape(c.n_heads, c.head_dim, e).astype(dt))
    return out, kc, vc


def _forward_with_cache(c: TransformerConfig, params: Dict,
                        ids: jnp.ndarray, cache: Dict[str, jnp.ndarray],
                        block_tables: jnp.ndarray,
                        positions: jnp.ndarray,
                        write_mask: jnp.ndarray,
                        lens: jnp.ndarray):
    """Shared trunk of :func:`prefill` and :func:`decode_step`:
    (B, C) token ids at absolute ``positions`` -> (B, C, vocab) logits,
    writing each layer's k/v into the paged cache as it goes. ``lens``
    (B,) is each sequence's live token count including this call's
    writes — the attention kernel's length-skipping bound."""
    if c.n_experts:
        raise NotImplementedError(
            "paged decode does not support MoE configs yet")
    bs = cache["k"].shape[2]
    window = block_tables.shape[1] * bs
    sin, cos = rotary_table(
        window, c.rotary_dim if c.block_style == "gptj" else c.head_dim,
        c.rope_base)
    layout = "gptj" if c.block_style == "gptj" else "neox"
    x = jnp.take(params["embed"], ids, axis=0).astype(c.dtype)

    def gptj_step(x, lp, kc, vc):
        h = layer_norm(x, lp["ln_scale"], lp["ln_bias"])
        att, kc, vc = _paged_attn_sublayer(
            c, h, lp, sin, cos, layout, kc, vc,
            block_tables, positions, write_mask, lens)
        mlp, _ = _mlp_sublayer(c, h, lp)
        return x + (att + mlp).astype(x.dtype), kc, vc

    def llama_step(x, lp, kc, vc):
        h = rms_norm(x, lp["attn_norm"])
        att, kc, vc = _paged_attn_sublayer(
            c, h, lp, sin, cos, layout, kc, vc,
            block_tables, positions, write_mask, lens)
        x = x + att.astype(x.dtype)
        h2 = rms_norm(x, lp["mlp_norm"]).astype(c.dtype)
        mlp, _ = _mlp_sublayer(c, h2, lp)
        return x + mlp.astype(x.dtype), kc, vc

    step = gptj_step if c.block_style == "gptj" else llama_step

    def scan_fn(carry, per_layer):
        lp, kc, vc = per_layer
        out, kc, vc = step(carry, lp, kc, vc)
        return out, (kc, vc)

    x, (new_k, new_v) = jax.lax.scan(
        scan_fn, x, (params["layers"], cache["k"], cache["v"]))

    fn = params["final_norm"]
    if c.block_style == "llama":
        x = rms_norm(x, fn["scale"])
    else:
        x = layer_norm(x, fn["scale"], fn["bias"])
    logits = jnp.dot(x.astype(c.dtype),
                     params["lm_head"]["w"].astype(c.dtype))
    if c.block_style != "llama":
        logits = logits + params["lm_head"]["b"].astype(c.dtype)
    return logits, {"k": new_k, "v": new_v}


def prefill(config: TransformerConfig, params: Dict, tokens: jnp.ndarray,
            cache: Dict[str, jnp.ndarray], block_tables: jnp.ndarray,
            start_pos: jnp.ndarray, lens: jnp.ndarray):
    """Process one prompt chunk per sequence, writing cache blocks.

    ``tokens``: (B, C) int32 — chunk ``start_pos[b] .. start_pos[b]+
    lens[b]-1`` of each prompt, zero-padded past ``lens[b]`` (chunked
    prefill feeds a fixed C per call so the engine never recompiles).
    Chunk token i attends every cached position ``<= start_pos + i`` —
    earlier chunks of the same prompt plus the chunk's own causal
    prefix. Returns ``(logits (B, C, vocab), cache)``; the first
    generated token comes from ``logits[b, lens[b]-1]`` of the FINAL
    chunk.
    """
    b, chunk = tokens.shape
    positions = start_pos[:, None] + jnp.arange(chunk, dtype=jnp.int32)
    write_mask = jnp.arange(chunk, dtype=jnp.int32)[None, :] \
        < lens[:, None]
    # live tokens after this chunk's writes: earlier chunks + this one
    live = (start_pos + lens).astype(jnp.int32)
    return _forward_with_cache(config, params, tokens, cache,
                               block_tables, positions, write_mask,
                               live)


def decode_step(config: TransformerConfig, params: Dict,
                token_ids: jnp.ndarray, cache: Dict[str, jnp.ndarray],
                block_tables: jnp.ndarray, seq_lens: jnp.ndarray):
    """One batched decode step: each sequence's newest token
    (``token_ids``: (B,) int32, sitting at absolute position
    ``seq_lens[b]``) is written to its cache block and attends every
    earlier position — causal by construction. Returns
    ``(logits (B, vocab), cache)``.
    """
    positions = seq_lens[:, None].astype(jnp.int32)
    write_mask = jnp.ones_like(positions, dtype=bool)
    logits, cache = _forward_with_cache(
        config, params, token_ids[:, None], cache,
        block_tables, positions, write_mask,
        seq_lens.astype(jnp.int32) + 1)
    return logits[:, 0], cache


class Transformer:
    """Convenience OO wrapper binding a config: ``init``/``apply``/``loss``
    plus the sharding-annotation tree."""

    def __init__(self, config: TransformerConfig):
        self.config = config

    def init(self, key) -> Dict:
        return init_params(self.config, key)

    def logical_axes(self) -> Dict:
        return logical_axes(self.config)

    def apply(self, params, input_ids, mesh=None, rules=None):
        return apply(self.config, params, input_ids, mesh=mesh, rules=rules)

    def loss(self, params, batch, mesh=None, rules=None):
        return lm_loss(self.config, params, batch, mesh=mesh, rules=rules)
