"""Decoder-only transformer LM, TPU-first.

Supports two block styles behind one config:

- ``"gptj"`` — parallel attention+MLP residual off a single LayerNorm
  (GPT-J 6B: rotary over the first 64 of 256 head dims, untied lm_head
  with bias). The flagship matches the reference's GPT-J fine-tune recipe
  (``release/air_examples/gptj_deepspeed_finetuning/``) architecturally.
- ``"llama"`` — sequential pre-RMSNorm blocks, SwiGLU MLP, full-dim neox
  rotary, optional GQA (num_kv_heads < num_heads).

Design (TPU-first, not a port):
- params are a plain dict pytree; per-layer weights are STACKED on a
  leading ``layers`` axis and the forward pass is one ``lax.scan`` over
  layers (+ ``jax.checkpoint`` per block) — constant compile time in
  depth, XLA-friendly.
- every weight has an entry in :func:`logical_axes` — the same treedef
  with tuples of logical names ("embed", "mlp", "heads", "vocab", …);
  ``parallel.sharding.ShardingRules`` maps those to mesh axes, so DP /
  FSDP / TP / SP are rule-table changes, not model changes.
- master params live in f32; ``config.dtype`` (bf16 on TPU) is the
  compute dtype, cast at use sites so the MXU sees bf16 while layernorm
  statistics and the softmax stay f32 (ops layer contract).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops import (
    apply_rotary,
    layer_norm,
    multihead_attention,
    ring_attention,
    rms_norm,
    rotary_table,
    cross_entropy_loss,
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50400
    d_model: int = 4096
    n_layers: int = 28
    n_heads: int = 16
    head_dim: int = 256
    n_kv_heads: Optional[int] = None        # GQA; None = n_heads
    d_ff: int = 16384
    max_seq_len: int = 2048
    rotary_dim: int = 64                     # gptj rotates a prefix
    rope_base: float = 10000.0
    block_style: str = "gptj"               # "gptj" | "llama"
    dtype: Any = jnp.bfloat16                # compute dtype
    remat: bool = True
    attn_impl: str = "auto"                  # ops.multihead_attention impl
    attn_block_q: int = 512
    attn_block_k: int = 512

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def num_params(self) -> int:
        """Parameter count (for MFU accounting)."""
        e, v, h = self.d_model, self.vocab_size, self.n_heads * self.head_dim
        kvh = self.kv_heads * self.head_dim
        per_layer = e * h + 2 * e * kvh + h * e          # q, k, v, o
        if self.block_style == "llama":
            per_layer += 3 * e * self.d_ff + 2 * e       # swiglu + 2 rmsnorm
        else:
            per_layer += 2 * e * self.d_ff + self.d_ff + e  # fc biases
            per_layer += 2 * e                           # ln scale+bias
        total = v * e + self.n_layers * per_layer
        total += e if self.block_style == "llama" else 2 * e  # final norm
        total += e * v + (v if self.block_style == "gptj" else 0)  # lm head
        return total

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Approximate train FLOPs/token (6·N params + attention term)."""
        s = seq_len or self.max_seq_len
        attn = 12 * self.n_layers * self.n_heads * self.head_dim * s
        return 6.0 * self.num_params + attn


# ------------------------------------------------------------------ init
def _dense_init(key, shape, scale=0.02):
    return scale * jax.random.normal(key, shape, jnp.float32)


def init_params(config: TransformerConfig, key) -> Dict:
    c = config
    keys = jax.random.split(key, 9)
    h = c.n_heads * c.head_dim
    kvh = c.kv_heads * c.head_dim
    L = c.n_layers

    def stack(k, shape, scale=0.02):
        return _dense_init(k, (L,) + shape, scale)

    out_scale = 0.02 / (2 * L) ** 0.5    # scaled residual-out init
    layers: Dict[str, jnp.ndarray] = {
        "wq": stack(keys[0], (c.d_model, h)),
        "wk": stack(keys[1], (c.d_model, kvh)),
        "wv": stack(keys[2], (c.d_model, kvh)),
        "wo": stack(keys[3], (h, c.d_model), out_scale),
    }
    if c.block_style == "llama":
        layers.update({
            "w_gate": stack(keys[4], (c.d_model, c.d_ff)),
            "w_up": stack(keys[5], (c.d_model, c.d_ff)),
            "w_down": stack(keys[6], (c.d_ff, c.d_model), out_scale),
            "attn_norm": jnp.ones((L, c.d_model), jnp.float32),
            "mlp_norm": jnp.ones((L, c.d_model), jnp.float32),
        })
        final = {"scale": jnp.ones((c.d_model,), jnp.float32)}
        head = {"w": _dense_init(keys[8], (c.d_model, c.vocab_size))}
    else:
        layers.update({
            "fc_in": stack(keys[4], (c.d_model, c.d_ff)),
            "fc_in_b": jnp.zeros((L, c.d_ff), jnp.float32),
            "fc_out": stack(keys[5], (c.d_ff, c.d_model), out_scale),
            "fc_out_b": jnp.zeros((L, c.d_model), jnp.float32),
            "ln_scale": jnp.ones((L, c.d_model), jnp.float32),
            "ln_bias": jnp.zeros((L, c.d_model), jnp.float32),
        })
        final = {"scale": jnp.ones((c.d_model,), jnp.float32),
                 "bias": jnp.zeros((c.d_model,), jnp.float32)}
        head = {"w": _dense_init(keys[8], (c.d_model, c.vocab_size)),
                "b": jnp.zeros((c.vocab_size,), jnp.float32)}

    return {
        "embed": _dense_init(keys[7], (c.vocab_size, c.d_model)),
        "layers": layers,
        "final_norm": final,
        "lm_head": head,
    }


def logical_axes(config: TransformerConfig) -> Dict:
    """Pytree (same treedef as params) of logical-axis tuples."""
    c = config
    common = {
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv"),
        "wv": ("layers", "embed", "kv"),
        "wo": ("layers", "heads", "embed"),
    }
    if c.block_style == "llama":
        layers = {**common,
                  "w_gate": ("layers", "embed", "mlp"),
                  "w_up": ("layers", "embed", "mlp"),
                  "w_down": ("layers", "mlp", "embed"),
                  "attn_norm": ("layers", "embed"),
                  "mlp_norm": ("layers", "embed")}
        final = {"scale": ("embed",)}
        head = {"w": ("embed", "vocab")}
    else:
        layers = {**common,
                  "fc_in": ("layers", "embed", "mlp"),
                  "fc_in_b": ("layers", "mlp"),
                  "fc_out": ("layers", "mlp", "embed"),
                  "fc_out_b": ("layers", "embed"),
                  "ln_scale": ("layers", "embed"),
                  "ln_bias": ("layers", "embed")}
        final = {"scale": ("embed",), "bias": ("embed",)}
        head = {"w": ("embed", "vocab"), "b": ("vocab",)}
    return {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": final,
        "lm_head": head,
    }


# --------------------------------------------------------------- forward
def _attention(c: TransformerConfig, q, k, v, mesh, rules):
    """Dispatch attention: ring over the sp axis when it's nontrivial,
    otherwise the flash/reference dispatcher (ops layer)."""
    sp_axis = rules.get("sequence") if rules else None
    if mesh is not None and sp_axis is not None and sp_axis in mesh.shape \
            and mesh.shape[sp_axis] > 1:
        from jax.sharding import PartitionSpec as P
        batch_axes = rules.get("batch")
        spec = P(batch_axes, sp_axis, None, None)
        fn = jax.shard_map(
            functools.partial(ring_attention, axis_name=sp_axis,
                              causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return fn(q, k, v)
    return multihead_attention(
        q, k, v, causal=True, impl=c.attn_impl,
        block_q=c.attn_block_q, block_k=c.attn_block_k)


def _attn_sublayer(c, h, lp, sin, cos, layout, mesh, rules):
    """qkv projection → rotary → GQA repeat → attention → output proj.
    Shared by both block styles (only the rotary layout differs)."""
    e = h.shape[-1]
    dt = c.dtype

    def proj(w, n):
        return jnp.einsum("bse,ehd->bshd", h.astype(dt),
                          w.reshape(e, n, -1).astype(dt))
    q = proj(lp["wq"], c.n_heads)
    k = proj(lp["wk"], c.kv_heads)
    v = proj(lp["wv"], c.kv_heads)
    q = apply_rotary(q, sin, cos, layout=layout)
    k = apply_rotary(k, sin, cos, layout=layout)
    if c.kv_heads != c.n_heads:
        rep = c.n_heads // c.kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    att = _attention(c, q, k, v, mesh, rules)
    return jnp.einsum("bshd,hde->bse", att,
                      lp["wo"].reshape(c.n_heads, c.head_dim, e).astype(dt))


def _gptj_block(c, x, lp, sin, cos, mesh, rules):
    h = layer_norm(x, lp["ln_scale"], lp["ln_bias"])
    dt = c.dtype
    att = _attn_sublayer(c, h, lp, sin, cos, "gptj", mesh, rules)
    mlp = jnp.dot(h.astype(dt), lp["fc_in"].astype(dt)) \
        + lp["fc_in_b"].astype(dt)
    mlp = jax.nn.gelu(mlp)
    mlp = jnp.dot(mlp, lp["fc_out"].astype(dt)) + lp["fc_out_b"].astype(dt)
    return x + (att + mlp).astype(x.dtype)


def _llama_block(c, x, lp, sin, cos, mesh, rules):
    dt = c.dtype
    h = rms_norm(x, lp["attn_norm"])
    att = _attn_sublayer(c, h, lp, sin, cos, "neox", mesh, rules)
    x = x + att.astype(x.dtype)
    h2 = rms_norm(x, lp["mlp_norm"]).astype(dt)
    gate = jax.nn.silu(jnp.dot(h2, lp["w_gate"].astype(dt)))
    up = jnp.dot(h2, lp["w_up"].astype(dt))
    mlp = jnp.dot(gate * up, lp["w_down"].astype(dt))
    return x + mlp.astype(x.dtype)


def apply(config: TransformerConfig, params: Dict, input_ids: jnp.ndarray,
          mesh=None, rules=None) -> jnp.ndarray:
    """Forward pass: (batch, seq) int32 -> (batch, seq, vocab) logits.

    ``mesh``/``rules`` enable in-graph sharding constraints and ring
    attention; both optional (single-device path needs neither).
    """
    c = config
    x = jnp.take(params["embed"], input_ids, axis=0).astype(c.dtype)
    seq = input_ids.shape[1]
    sin, cos = rotary_table(
        seq, c.rotary_dim if c.block_style == "gptj" else c.head_dim,
        c.rope_base)

    block = _gptj_block if c.block_style == "gptj" else _llama_block
    body = functools.partial(block, c, sin=sin, cos=cos,
                             mesh=mesh, rules=rules)
    if c.remat:
        body = jax.checkpoint(body)

    def scan_fn(carry, lp):
        out = body(carry, lp)
        if mesh is not None and rules is not None:
            from ray_tpu.parallel.sharding import constrain
            out = constrain(out, mesh, rules, ("batch", "sequence", None))
        return out, None

    x, _ = jax.lax.scan(scan_fn, x, params["layers"])

    fn = params["final_norm"]
    if c.block_style == "llama":
        x = rms_norm(x, fn["scale"])
        logits = jnp.dot(x.astype(c.dtype),
                         params["lm_head"]["w"].astype(c.dtype))
    else:
        x = layer_norm(x, fn["scale"], fn["bias"])
        logits = jnp.dot(x.astype(c.dtype),
                         params["lm_head"]["w"].astype(c.dtype))
        logits = logits + params["lm_head"]["b"].astype(c.dtype)
    return logits


def lm_loss(config: TransformerConfig, params: Dict, batch: Dict,
            mesh=None, rules=None) -> Tuple[jnp.ndarray, Dict]:
    """Next-token LM loss. batch: {"input_ids": (b,s) int32,
    "loss_mask": optional (b,s)}. Returns (loss, aux)."""
    ids = batch["input_ids"]
    logits = apply(config, params, ids, mesh=mesh, rules=rules)
    labels = ids[:, 1:]
    mask = batch.get("loss_mask")
    mask = mask[:, 1:] if mask is not None else None
    loss, n = cross_entropy_loss(logits[:, :-1], labels, mask=mask)
    return loss, {"n_tokens": n}


class Transformer:
    """Convenience OO wrapper binding a config: ``init``/``apply``/``loss``
    plus the sharding-annotation tree."""

    def __init__(self, config: TransformerConfig):
        self.config = config

    def init(self, key) -> Dict:
        return init_params(self.config, key)

    def logical_axes(self) -> Dict:
        return logical_axes(self.config)

    def apply(self, params, input_ids, mesh=None, rules=None):
        return apply(self.config, params, input_ids, mesh=mesh, rules=rules)

    def loss(self, params, batch, mesh=None, rules=None):
        return lm_loss(self.config, params, batch, mesh=mesh, rules=rules)
