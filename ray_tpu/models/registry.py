"""Named model configurations.

The flagship ``gptj-6b`` mirrors the architecture the reference's GPT-J
fine-tune recipe trains (EleutherAI GPT-J-6B: 28 layers, d_model 4096,
16 heads x 256, rotary_dim 64, vocab 50400 — see
``release/air_examples/gptj_deepspeed_finetuning/`` in the reference);
``llama2-7b`` covers the reference's Llama-2 release tests. ``*-tiny``
variants keep the same block structure at test scale.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax.numpy as jnp

from ray_tpu.models.transformer import TransformerConfig

MODEL_CONFIGS: Dict[str, TransformerConfig] = {
    "gptj-6b": TransformerConfig(
        vocab_size=50400, d_model=4096, n_layers=28, n_heads=16,
        head_dim=256, d_ff=16384, max_seq_len=2048, rotary_dim=64,
        block_style="gptj"),
    "moe-tiny": TransformerConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, head_dim=16,
        d_ff=128, max_seq_len=128, rotary_dim=8, block_style="gptj",
        n_experts=4, dtype=jnp.float32, remat=False),
    "gptj-tiny": TransformerConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, head_dim=16,
        d_ff=256, max_seq_len=128, rotary_dim=8, block_style="gptj",
        dtype=jnp.float32, remat=False),
    "llama2-7b": TransformerConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
        head_dim=128, d_ff=11008, max_seq_len=4096, rotary_dim=128,
        block_style="llama"),
    "llama2-tiny": TransformerConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, head_dim=16,
        n_kv_heads=2, d_ff=128, max_seq_len=128, rotary_dim=16,
        block_style="llama", dtype=jnp.float32, remat=False),
}


def get_config(name: str, **overrides) -> TransformerConfig:
    if name not in MODEL_CONFIGS:
        raise KeyError(
            f"unknown model {name!r}; have {sorted(MODEL_CONFIGS)}")
    cfg = MODEL_CONFIGS[name]
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def register_config(name: str, config: TransformerConfig) -> None:
    MODEL_CONFIGS[name] = config
