"""BaseTrainer: config container + fit() driver loop.

Reference: ``python/ray/train/base_trainer.py:107`` (``fit`` :561). The
reference wraps every trainer in a single-trial Tune run
(``TrainTrainable`` :711); this build does the same when Tune is driving
(``as_trainable()``), and runs the identical loop directly for plain
``.fit()`` so single runs don't pay Tune overhead.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.config import (
    CheckpointConfig, FailureConfig, RunConfig, ScalingConfig)
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.storage import CheckpointManager, StorageContext
from ray_tpu.train.result import Result


class BaseTrainer:
    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 metadata: Optional[Dict[str, Any]] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.metadata = metadata or {}

    # Subclasses implement the actual loop against a BackendExecutor.
    def training_loop(self) -> Result:
        raise NotImplementedError

    def fit(self) -> Result:
        return self.training_loop()

    # -- Tune integration --------------------------------------------
    def as_trainable(self):
        """Wrap as a Tune trainable (reference ``TrainTrainable`` :711)."""
        from ray_tpu.tune.trainable import FunctionTrainable
        trainer = self

        def _train_fn(config: Dict[str, Any]):
            from ray_tpu.tune import trainable as _t
            from ray_tpu.tune._trial_context import get_trial_dir
            import copy
            import os
            t = copy.copy(trainer)
            if config:
                t = t._with_parameters(config)
            # Under Tune each trial gets its own directory; point the
            # inner run's storage there so concurrent trials never share
            # checkpoint paths.
            trial_dir = get_trial_dir()
            if trial_dir:
                t.run_config = copy.copy(t.run_config)
                t.run_config.name = os.path.basename(trial_dir.rstrip("/"))
                t.run_config.storage_path = os.path.dirname(
                    trial_dir.rstrip("/"))
            result = t.fit()
            if result.error:
                raise result.error
            _t.report(result.metrics or {},
                      checkpoint=result.checkpoint)

        _train_fn.__name__ = type(self).__name__
        trainable = FunctionTrainable.wrap(_train_fn)
        trainable.default_resource_request = (
            lambda config: self.scaling_config.as_placement_group_factory())
        return trainable

    # Trainer attributes sweepable from a Tune param_space (reference
    # allows trainer __init__ kwargs as siblings of train_loop_config).
    _SWEEPABLE_ATTRS = ("scaling_config", "run_config", "backend_config",
                        "datasets", "metadata", "dataset_config")

    def _with_parameters(self, config: Dict[str, Any]) -> "BaseTrainer":
        import copy
        t = copy.copy(self)
        overrides = dict(config)
        loop = overrides.pop("train_loop_config", None)
        for attr in self._SWEEPABLE_ATTRS:
            if attr in overrides:
                setattr(t, attr, overrides.pop(attr))
        if loop is None:
            # Flat dict: everything remaining is loop config.
            loop = overrides
            overrides = {}
        if overrides:
            raise ValueError(
                f"Unknown trainer param_space keys: {sorted(overrides)}; "
                f"nest hyperparameters under 'train_loop_config' or use "
                f"one of {self._SWEEPABLE_ATTRS}")
        loop_cfg = dict(getattr(t, "train_loop_config", None) or {})
        loop_cfg.update(loop)
        t.train_loop_config = loop_cfg
        return t

    @classmethod
    def restore(cls, path: str, **kwargs) -> "BaseTrainer":
        """Resume a trainer from a run directory's latest checkpoint
        (reference ``base_trainer.py:577``)."""
        import os
        ckpts = sorted(
            d for d in os.listdir(path) if d.startswith("checkpoint_"))
        if not ckpts:
            raise ValueError(f"No checkpoints under {path}")
        kwargs.setdefault(
            "resume_from_checkpoint",
            Checkpoint(os.path.join(path, ckpts[-1])))
        run_name = os.path.basename(path.rstrip("/"))
        kwargs.setdefault(
            "run_config",
            RunConfig(name=run_name,
                      storage_path=os.path.dirname(path.rstrip("/"))))
        return cls(**kwargs)

    @classmethod
    def can_restore(cls, path: str) -> bool:
        import os
        return os.path.isdir(path) and any(
            d.startswith("checkpoint_") for d in os.listdir(path))

    def _make_storage(self) -> StorageContext:
        name = self.run_config.name or (
            f"{type(self).__name__}_{time.strftime('%Y-%m-%d_%H-%M-%S')}"
            f"_{uuid.uuid4().hex[:6]}")
        self.run_config.name = name
        return StorageContext(self.run_config.storage_path, name)

    def _make_checkpoint_manager(
            self, storage: StorageContext) -> CheckpointManager:
        cc: CheckpointConfig = self.run_config.checkpoint_config
        return CheckpointManager(
            storage, cc.num_to_keep,
            score_attribute=cc.checkpoint_score_attribute,
            score_order=cc.checkpoint_score_order)
