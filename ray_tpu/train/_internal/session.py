"""Per-worker training session: the bridge between user ``train_func``
and the driver loop.

Reference: ``python/ray/train/_internal/session.py`` — ``_TrainSession``
:109 runs the user function on a thread; ``report`` (:402/:662) persists
the checkpoint and enqueues a result that the driver drains; the queue is
bounded so training paces with the driver. Context accessors mirror
``ray.train.get_context()`` (world_rank/world_size/local_rank/node_rank).
"""

from __future__ import annotations

import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.storage import StorageContext

_session: Optional["_TrainSession"] = None


@dataclass
class _TrainingResult:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint] = None
    done: bool = False
    error: Optional[BaseException] = None


class _TrainSession:
    def __init__(self, train_func: Callable[[], Any], world_rank: int,
                 world_size: int, local_rank: int, local_world_size: int,
                 node_rank: int, storage: Optional[StorageContext],
                 checkpoint: Optional[Checkpoint],
                 experiment_name: str = "", trial_name: str = "",
                 trial_id: str = ""):
        self.train_func = train_func
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.storage = storage
        self.loaded_checkpoint = checkpoint
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.trial_id = trial_id
        self.iteration = 0
        # Bounded: report() blocks until the driver consumed the previous
        # result, so workers stay in lockstep with the driver loop.
        self._queue: "queue.Queue[_TrainingResult]" = queue.Queue(maxsize=1)
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------
    def start(self) -> None:
        def runner():
            try:
                self.train_func()
                self._queue.put(_TrainingResult(metrics={}, done=True))
            except BaseException as e:  # surfaced at the driver
                self._queue.put(
                    _TrainingResult(metrics={}, done=True, error=e))

        self._thread = threading.Thread(
            target=runner, name="train_fn", daemon=True)
        self._thread.start()

    def get_next(self) -> _TrainingResult:
        return self._queue.get()

    # -- user API (called from inside train_func) ---------------------
    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        self.iteration += 1
        persisted = None
        if checkpoint is not None:
            if self.storage is not None and self.world_rank == 0:
                persisted = self.storage.persist_current_checkpoint(checkpoint)
            else:
                persisted = checkpoint
        self._queue.put(_TrainingResult(metrics=metrics, checkpoint=persisted))


def init_session(**kwargs) -> _TrainSession:
    global _session
    _session = _TrainSession(**kwargs)
    return _session


def get_session() -> Optional[_TrainSession]:
    return _session


def shutdown_session() -> None:
    global _session
    _session = None


# ---------------------------------------------------------------------
# Public accessors (exported as ray_tpu.train.report / get_context / ...)
# ---------------------------------------------------------------------

class TrainContext:
    """Reference: ``ray.train.get_context()`` context object."""

    def _s(self) -> _TrainSession:
        s = get_session()
        if s is None:
            raise RuntimeError(
                "No train session active: this API must be called inside a "
                "train_func launched by a Trainer.")
        return s

    def get_world_size(self) -> int:
        return self._s().world_size

    def get_world_rank(self) -> int:
        return self._s().world_rank

    def get_local_rank(self) -> int:
        return self._s().local_rank

    def get_local_world_size(self) -> int:
        return self._s().local_world_size

    def get_node_rank(self) -> int:
        return self._s().node_rank

    def get_experiment_name(self) -> str:
        return self._s().experiment_name

    def get_trial_name(self) -> str:
        return self._s().trial_name

    def get_trial_id(self) -> str:
        return self._s().trial_id

    def get_storage(self) -> Optional[StorageContext]:
        return self._s().storage


def get_context() -> TrainContext:
    return TrainContext()


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    s = get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.report() outside a train session")
    s.report(metrics, checkpoint=checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    s = get_session()
    if s is None:
        return None
    return s.loaded_checkpoint


def get_dataset_shard(dataset_name: str = "train"):
    """Reference: ``ray.train.get_dataset_shard``. Returns the per-worker
    shard iterator attached by the trainer's DataConfig."""
    s = get_session()
    if s is None:
        return None
    shards = getattr(s, "dataset_shards", None) or {}
    return shards.get(dataset_name)
