"""WorkerGroup: a gang of TrainWorker actors, one per TPU host.

Reference: ``python/ray/train/_internal/worker_group.py`` —
``RayTrainWorker`` :19 (thin actor wrapping the session) and
``WorkerGroup`` :102 (create/sort/execute/shutdown). TPU-first delta:
workers are sorted by (node ip, TPU chip ids) so world ranks are
contiguous per host, which is what ``jax.distributed`` expects
(process_id = host index in the slice).
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal import session as session_lib
from ray_tpu.train._internal.storage import StorageContext


class RayTrainWorker:
    """Actor running one training process (reference worker_group.py:19)."""

    def __init__(self):
        self._session: Optional[session_lib._TrainSession] = None

    # Generic execution hook used by backends for env/setup fan-out.
    def execute(self, fn: Callable, *args, **kwargs) -> Any:
        return fn(*args, **kwargs)

    def metadata(self) -> Dict[str, Any]:
        ctx = ray_tpu.get_runtime_context()
        return {
            "node_id": ctx.get_node_id(),
            "node_ip": socket.gethostbyname(socket.gethostname()),
            "pid": os.getpid(),
            "tpu_chips": os.environ.get("TPU_VISIBLE_CHIPS", ""),
        }

    def init_session(self, train_func: Callable, world_rank: int,
                      world_size: int, local_rank: int,
                      local_world_size: int, node_rank: int,
                      storage: Optional[StorageContext],
                      checkpoint: Optional[Checkpoint],
                      experiment_name: str, trial_name: str,
                      trial_id: str, dataset_shards: Optional[dict] = None
                      ) -> None:
        s = session_lib.init_session(
            train_func=train_func, world_rank=world_rank,
            world_size=world_size, local_rank=local_rank,
            local_world_size=local_world_size, node_rank=node_rank,
            storage=storage, checkpoint=checkpoint,
            experiment_name=experiment_name, trial_name=trial_name,
            trial_id=trial_id)
        if dataset_shards:
            s.dataset_shards = dataset_shards
        self._session = s

    def start_training(self) -> None:
        assert self._session is not None
        self._session.start()

    def get_next(self) -> session_lib._TrainingResult:
        assert self._session is not None
        return self._session.get_next()

    def shutdown_session(self) -> None:
        session_lib.shutdown_session()
        self._session = None


@dataclass
class WorkerMetadata:
    node_id: str
    node_ip: str
    pid: int
    tpu_chips: str


class WorkerGroup:
    """Reference ``worker_group.py:102``."""

    def __init__(self, num_workers: int,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 placement_group=None, bundle_offset: int = 1,
                 actor_cls_env: Optional[dict] = None):
        self.num_workers = num_workers
        self._pg = placement_group
        opts: Dict[str, Any] = {}
        rpw = dict(resources_per_worker or {"CPU": 1.0})
        opts["num_cpus"] = float(rpw.pop("CPU", 1.0))
        if "TPU" in rpw:
            opts["num_tpus"] = float(rpw.pop("TPU"))
        if rpw:
            opts["resources"] = rpw
        remote_cls = ray_tpu.remote(**opts)(RayTrainWorker)
        self.workers: List[Any] = []
        self.metadata: List[WorkerMetadata] = []
        for i in range(num_workers):
            w_opts = {}
            if placement_group is not None:
                from ray_tpu.util.scheduling_strategies import (
                    PlacementGroupSchedulingStrategy)
                # Worker bundles start after the trainer's head bundle
                # (offset 0 when the head bundle was empty/absent).
                w_opts["scheduling_strategy"] = (
                    PlacementGroupSchedulingStrategy(
                        placement_group,
                        placement_group_bundle_index=i + bundle_offset))
            self.workers.append(remote_cls.options(**w_opts).remote())

    def fetch_metadata(self) -> List[WorkerMetadata]:
        metas = ray_tpu.get(
            [w.metadata.remote() for w in self.workers])
        self.metadata = [WorkerMetadata(**m) for m in metas]
        return self.metadata

    def sort_workers_by_node(self) -> None:
        """Group workers by node ip then chip ids → contiguous host ranks
        (reference ``backend_executor.py:363``)."""
        if not self.metadata:
            self.fetch_metadata()
        order = sorted(
            range(len(self.workers)),
            key=lambda i: (self.metadata[i].node_ip,
                           self.metadata[i].tpu_chips,
                           self.metadata[i].pid))
        self.workers = [self.workers[i] for i in order]
        self.metadata = [self.metadata[i] for i in order]

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(
            [w.execute.remote(fn, *args, **kwargs) for w in self.workers])

    def execute_async(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return [w.execute.remote(fn, *args, **kwargs)
                for w in self.workers]

    def execute_single(self, index: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(
            self.workers[index].execute.remote(fn, *args, **kwargs))

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        self.metadata = []

    def __len__(self) -> int:
        return len(self.workers)
