"""BackendExecutor: drives the worker-group lifecycle for one run.

Reference: ``python/ray/train/_internal/backend_executor.py:65`` —
``start`` :121 (placement group :197 + WorkerGroup + backend.on_start),
``start_training``, result polling, ``_restart`` :690 on worker failure.
TPU delta: restarts are **slice-granular** — a dead host invalidates the
whole SPMD gang, so the entire worker group is torn down and rebuilt from
the latest checkpoint (SURVEY.md §7 hard-part 5).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.config import ScalingConfig
from ray_tpu.exceptions import ActorDiedError, ActorError, RayTpuError
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.session import _TrainingResult
from ray_tpu.train._internal.storage import StorageContext
from ray_tpu.train._internal.worker_group import WorkerGroup


class TrainingWorkerError(RayTpuError):
    """A worker of the gang died mid-training (triggers group restart)."""


class TrainBackendError(RayTpuError):
    pass


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig,
                 storage: Optional[StorageContext] = None,
                 experiment_name: str = "", trial_name: str = "",
                 trial_id: str = ""):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()
        self._scaling_config = scaling_config
        self._storage = storage
        self._experiment_name = experiment_name
        self._trial_name = trial_name
        self._trial_id = trial_id
        self.worker_group: Optional[WorkerGroup] = None
        self._pg = None          # owned placement group (we remove it)
        self._used_pg = None     # group used for (re)starts, owned or not
        self._bundle_offset = 1
        self._finished_ranks: set = set()

    # -- lifecycle ----------------------------------------------------
    def start(self, placement_group=None) -> None:
        sc = self._scaling_config
        factory = sc.as_placement_group_factory()
        # Worker bundles follow the trainer's head bundle unless the head
        # is empty and thus absent from the created group.
        self._bundle_offset = 0 if factory.head_bundle_is_empty else 1
        if placement_group is None:
            self._pg = factory()
            if not self._pg.wait(timeout_seconds=60):
                raise TrainBackendError(
                    f"Timed out reserving resources for {sc.num_workers} "
                    f"workers: {factory.required_resources()}")
            placement_group = self._pg
        # The group used for (re)starts — owned or externally supplied
        # (e.g. the enclosing Tune trial's reservation).
        self._used_pg = placement_group
        self.worker_group = WorkerGroup(
            num_workers=sc.num_workers,
            resources_per_worker=sc.worker_bundle(),
            placement_group=placement_group,
            bundle_offset=self._bundle_offset)
        self._backend.on_start(self.worker_group, self._backend_config)

    def start_training(self, train_func: Callable[[], Any],
                       checkpoint: Optional[Checkpoint] = None,
                       dataset_shards: Optional[List[dict]] = None) -> None:
        wg = self.worker_group
        assert wg is not None, "call start() first"
        self._finished_ranks = set()
        if not wg.metadata:
            wg.fetch_metadata()
        metas = wg.metadata
        node_ips = sorted({m.node_ip for m in metas})
        node_rank_of = {ip: i for i, ip in enumerate(node_ips)}
        local_rank_counter: Dict[str, int] = {}
        init_futs = []
        for rank, (worker, meta) in enumerate(zip(wg.workers, metas)):
            local_rank = local_rank_counter.get(meta.node_ip, 0)
            local_rank_counter[meta.node_ip] = local_rank + 1
            init_futs.append(worker.init_session.remote(
                train_func, rank, len(wg), local_rank,
                sum(1 for m in metas if m.node_ip == meta.node_ip),
                node_rank_of[meta.node_ip], self._storage, checkpoint,
                self._experiment_name, self._trial_name, self._trial_id,
                dataset_shards[rank] if dataset_shards else None))
        ray_tpu.get(init_futs)
        self._backend.on_training_start(wg, self._backend_config)
        ray_tpu.get([w.start_training.remote() for w in wg.workers])

    def get_next_results(self) -> Optional[List[_TrainingResult]]:
        """Fetch one result from every still-running worker (lockstep).
        Returns the results ordered by world rank (lowest live rank
        first), None when all workers finished cleanly; raises the user
        error if any worker's train_func raised; raises
        TrainingWorkerError if a worker process died. Finished workers
        are never polled again (their queue is empty — a second
        get_next would block forever)."""
        wg = self.worker_group
        assert wg is not None
        live = [rank for rank in range(len(wg.workers))
                if rank not in self._finished_ranks]
        if not live:
            return None
        futs = [wg.workers[rank].get_next.remote() for rank in live]
        try:
            results: List[_TrainingResult] = ray_tpu.get(futs)
        except (ActorError, ActorDiedError) as e:
            raise TrainingWorkerError(str(e)) from e
        for r in results:
            if r.error is not None:
                raise r.error
        out = []
        for rank, r in zip(live, results):
            if r.done:
                self._finished_ranks.add(rank)
            else:
                out.append(r)
        if len(self._finished_ranks) == len(wg.workers):
            return None
        # Ragged finish round: drop the done markers, keep live results.
        return out if out else self.get_next_results()

    def shutdown(self) -> None:
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(
                    self.worker_group, self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        if self._pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None

    def restart(self) -> None:
        """Slice-granular restart (reference ``_restart`` :690). Reuses
        the original reservation, whether owned or externally supplied."""
        wg = self.worker_group
        if wg is not None:
            wg.shutdown()
        sc = self._scaling_config
        self.worker_group = WorkerGroup(
            num_workers=sc.num_workers,
            resources_per_worker=sc.worker_bundle(),
            placement_group=self._used_pg,
            bundle_offset=self._bundle_offset)
        self._backend.on_start(self.worker_group, self._backend_config)
