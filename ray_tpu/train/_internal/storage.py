"""StorageContext: where a run's checkpoints and artifacts live.

Reference: ``python/ray/train/_internal/storage.py:349`` —
``StorageContext`` resolves ``RunConfig.storage_path`` into per-experiment
and per-trial directories and persists checkpoints
(``persist_current_checkpoint`` :522). This build keeps the same layout
(``{storage_path}/{experiment_name}/{trial_dir}/checkpoint_NNNNNN``) on a
local or shared filesystem (GCS-fuse mounts on TPU VMs appear as local
paths, so one code path covers both).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.train._checkpoint import Checkpoint


class StorageContext:
    def __init__(self, storage_path: str, experiment_name: str,
                 trial_dir_name: Optional[str] = None):
        self.storage_path = os.path.abspath(os.path.expanduser(storage_path))
        self.experiment_name = experiment_name
        self.trial_dir_name = trial_dir_name
        os.makedirs(self.experiment_dir, exist_ok=True)
        # Resume numbering past any checkpoints already on disk so a
        # restored/restarted run never overwrites earlier directories.
        existing = self.list_checkpoints()
        self.current_checkpoint_index = (
            int(os.path.basename(existing[-1]).split("_")[-1]) + 1
            if existing else 0)

    @property
    def experiment_dir(self) -> str:
        return os.path.join(self.storage_path, self.experiment_name)

    @property
    def trial_dir(self) -> str:
        if self.trial_dir_name is None:
            return self.experiment_dir
        d = os.path.join(self.experiment_dir, self.trial_dir_name)
        os.makedirs(d, exist_ok=True)
        return d

    def checkpoint_dir(self, index: int) -> str:
        return os.path.join(self.trial_dir, f"checkpoint_{index:06d}")

    def persist_current_checkpoint(self, checkpoint: Checkpoint) -> Checkpoint:
        """Copy a (worker-local) checkpoint into run storage.

        Reference ``storage.py:522``. Returns the persisted checkpoint.
        """
        dest = self.checkpoint_dir(self.current_checkpoint_index)
        self.current_checkpoint_index += 1
        if os.path.abspath(checkpoint.path) == dest:
            return checkpoint
        os.makedirs(dest, exist_ok=True)
        shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
        return Checkpoint(dest)

    def list_checkpoints(self) -> List[str]:
        if not os.path.isdir(self.trial_dir):
            return []
        return sorted(
            os.path.join(self.trial_dir, d)
            for d in os.listdir(self.trial_dir)
            if d.startswith("checkpoint_"))


class CheckpointManager:
    """Top-K retention over persisted checkpoints.

    Reference: ``python/ray/train/_internal/checkpoint_manager.py`` driven
    by ``CheckpointConfig`` (``air/config.py:425``).
    """

    def __init__(self, storage: StorageContext, num_to_keep: Optional[int],
                 score_attribute: Optional[str] = None,
                 score_order: str = "max"):
        self.storage = storage
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        # (checkpoint, metrics) newest-last
        self._tracked: List[Tuple[Checkpoint, Dict[str, Any]]] = []

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        return self._tracked[-1][0] if self._tracked else None

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._tracked:
            return None
        if not self.score_attribute:
            return self._tracked[-1][0]
        scored = [t for t in self._tracked
                  if self.score_attribute in (t[1] or {})]
        if not scored:
            return self._tracked[-1][0]
        key = lambda t: t[1][self.score_attribute]  # noqa: E731
        return (max if self.score_order == "max" else min)(scored, key=key)[0]

    @property
    def checkpoints(self) -> List[Tuple[Checkpoint, Dict[str, Any]]]:
        return list(self._tracked)

    def register_checkpoint(self, checkpoint: Checkpoint,
                            metrics: Optional[Dict[str, Any]] = None) -> None:
        self._tracked.append((checkpoint, metrics or {}))
        self._enforce_retention()

    def _enforce_retention(self) -> None:
        if self.num_to_keep is None:
            return
        while len(self._tracked) > self.num_to_keep:
            # Evict the worst-scored (or oldest) checkpoint, never the latest.
            candidates = self._tracked[:-1]
            if self.score_attribute:
                key = lambda t: t[1].get(  # noqa: E731
                    self.score_attribute,
                    float("-inf") if self.score_order == "max"
                    else float("inf"))
                evict = (min if self.score_order == "max" else max)(
                    candidates, key=key)
            else:
                evict = candidates[0]
            self._tracked.remove(evict)
            shutil.rmtree(evict[0].path, ignore_errors=True)
