"""JaxTrainer: the flagship trainer — GSPMD training over TPU slices.

Reference shape: ``python/ray/train/torch/torch_trainer.py`` (a
DataParallelTrainer bound to the framework backend). The BASELINE.json
north star (GPT-J fine-tune ≥35% MFU on v5e-64) runs through this class:
one worker actor per TPU host of a slice, ``jax.distributed`` rendezvous
via ``JaxConfig``, and the user's train_func building a
``jax.sharding.Mesh`` over the global device set (dp/fsdp/tp/sp axes via
``ray_tpu.parallel``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.jax.config import JaxConfig


class JaxTrainer(DataParallelTrainer):
    _backend_config_cls = JaxConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 jax_config: Optional[JaxConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 dataset_config: Optional[Any] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 metadata: Optional[Dict[str, Any]] = None):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=jax_config or JaxConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            dataset_config=dataset_config,
            resume_from_checkpoint=resume_from_checkpoint,
            metadata=metadata)
