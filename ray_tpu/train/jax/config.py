"""JAX backend: multi-host SPMD rendezvous for the worker group.

Reference shape: ``python/ray/train/torch/config.py:146`` —
``_TorchBackend.on_start`` picks a rendezvous address on rank 0 and runs
``dist.init_process_group`` on every worker. TPU-native equivalent: rank
0 publishes a coordinator address; every worker calls
``jax.distributed.initialize(coordinator, num_processes, process_id)``,
which is the JAX runtime's coordination service (barrier + device mesh
discovery over DCN). Inside a host, no process group exists at all —
collectives are XLA ICI ops compiled into the jitted program.

On a single host (tests, one-chip dev) distributed init is skipped:
``jax.devices()`` already sees every local chip and GSPMD handles the
rest, so ``train_func`` code is identical either way.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Type

from ray_tpu.train.backend import Backend, BackendConfig


@dataclass
class JaxConfig(BackendConfig):
    # Force-enable/disable jax.distributed.initialize; None = auto
    # (enabled iff the group spans >1 node).
    distributed: Optional[bool] = None
    #: 0 picks a free port on the coordinator at start time.
    coordinator_port: int = 0
    #: Per-process device count override (CPU testing: N virtual devices
    #: per worker process; real TPU hosts leave this None — the runtime
    #: discovers the host's chips).
    local_device_count: Optional[int] = None

    @property
    def backend_cls(self) -> Type["_JaxBackend"]:
        return _JaxBackend


def _setup_jax_distributed(rendezvous_key: bytes, port: int,
                           num_processes: int, process_id: int,
                           local_device_count: Optional[int] = None) -> None:
    """Runs on each worker before train_func (reference analog:
    ``_setup_torch_process_group`` torch/config.py:64 — rank 0 publishes
    the rendezvous, everyone joins). Rank 0 probes its port (0 = free)
    and publishes ip:port to the cluster KV IN THE SAME PROCESS that
    immediately binds it via jax.distributed.initialize, so there is no
    cross-RPC window for another process to steal the port; followers
    poll the KV. Must run before the worker's first jax backend init:
    XLA_FLAGS and the coordination service only apply to an
    uninitialized runtime."""
    import time

    import ray_tpu
    from ray_tpu.core.global_state import global_worker

    if local_device_count is not None:
        # replace any inherited count (test harnesses export a
        # driver-wide value that is wrong for per-process workers)
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(
            f"--xla_force_host_platform_device_count={local_device_count}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
    # platform pinning already happened at worker startup
    # (ray_tpu.core.worker.main honors RAY_TPU_JAX_PLATFORM)
    import jax
    try:
        from jax._src import xla_bridge
        if getattr(xla_bridge, "_backends", None):
            raise RuntimeError(
                "jax backend already initialized in this worker process; "
                "distributed setup (XLA_FLAGS / coordination service) "
                "cannot apply. Use fresh training workers.")
    except ImportError:
        pass
    w = global_worker()
    if process_id == 0:
        import socket
        ip = socket.gethostbyname(socket.gethostname())
        if port == 0:
            with socket.socket() as s:
                s.bind(("", 0))
                port = s.getsockname()[1]
        address = f"{ip}:{port}"
        w.kv_put(rendezvous_key, address.encode(), ns="__train__")
    else:
        deadline = time.monotonic() + 60.0
        address = None
        while time.monotonic() < deadline:
            raw = w.kv_get(rendezvous_key, ns="__train__")
            if raw:
                address = raw.decode()
                break
            time.sleep(0.05)
        if address is None:
            raise TimeoutError("rank 0 never published the jax "
                               "coordinator address")
    os.environ["RAY_TPU_JAX_COORDINATOR"] = address
    os.environ["RAY_TPU_JAX_NUM_PROCESSES"] = str(num_processes)
    os.environ["RAY_TPU_JAX_PROCESS_ID"] = str(process_id)
    jax.distributed.initialize(
        coordinator_address=address,
        num_processes=num_processes,
        process_id=process_id)


def _shutdown_jax_distributed() -> None:
    import jax
    try:
        jax.distributed.shutdown()
    except Exception:
        pass


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig) -> None:
        worker_group.fetch_metadata()  # refresh even if previously set
        worker_group.sort_workers_by_node()
        n_nodes = len({m.node_ip for m in worker_group.metadata})
        use_distributed = backend_config.distributed
        if use_distributed is None:
            use_distributed = n_nodes > 1
        if not use_distributed:
            return
        import uuid

        import ray_tpu
        key = f"jax-coord-{uuid.uuid4().hex[:12]}".encode()
        futures = []
        for rank, worker in enumerate(worker_group.workers):
            futures.append(worker.execute.remote(
                _setup_jax_distributed, key,
                backend_config.coordinator_port,
                len(worker_group), rank,
                backend_config.local_device_count))
        ray_tpu.get(futures)

    def on_shutdown(self, worker_group, backend_config: JaxConfig) -> None:
        if worker_group.workers:
            try:
                worker_group.execute(_shutdown_jax_distributed)
            except Exception:
                pass
