"""JAX backend: multi-host SPMD rendezvous for the worker group.

Reference shape: ``python/ray/train/torch/config.py:146`` —
``_TorchBackend.on_start`` picks a rendezvous address on rank 0 and runs
``dist.init_process_group`` on every worker. TPU-native equivalent: rank
0 publishes a coordinator address; every worker calls
``jax.distributed.initialize(coordinator, num_processes, process_id)``,
which is the JAX runtime's coordination service (barrier + device mesh
discovery over DCN). Inside a host, no process group exists at all —
collectives are XLA ICI ops compiled into the jitted program.

On a single host (tests, one-chip dev) distributed init is skipped:
``jax.devices()`` already sees every local chip and GSPMD handles the
rest, so ``train_func`` code is identical either way.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Type

from ray_tpu.train.backend import Backend, BackendConfig


@dataclass
class JaxConfig(BackendConfig):
    # Force-enable/disable jax.distributed.initialize; None = auto
    # (enabled iff the group spans >1 node).
    distributed: Optional[bool] = None
    coordinator_port: int = 8476

    @property
    def backend_cls(self) -> Type["_JaxBackend"]:
        return _JaxBackend


def _get_coordinator_ip() -> str:
    import socket
    return socket.gethostbyname(socket.gethostname())


def _setup_jax_distributed(coordinator_address: str, num_processes: int,
                           process_id: int) -> None:
    """Runs on each worker before train_func (reference analog:
    ``_setup_torch_process_group`` torch/config.py:64)."""
    os.environ["RAY_TPU_JAX_COORDINATOR"] = coordinator_address
    os.environ["RAY_TPU_JAX_NUM_PROCESSES"] = str(num_processes)
    os.environ["RAY_TPU_JAX_PROCESS_ID"] = str(process_id)
    import jax
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def _shutdown_jax_distributed() -> None:
    import jax
    try:
        jax.distributed.shutdown()
    except Exception:
        pass


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig) -> None:
        metas = worker_group.fetch_metadata()
        worker_group.sort_workers_by_node()
        metas = worker_group.metadata
        n_nodes = len({m.node_ip for m in metas})
        use_distributed = backend_config.distributed
        if use_distributed is None:
            use_distributed = n_nodes > 1
        if not use_distributed:
            return
        coordinator = worker_group.execute_single(
            0, _get_coordinator_ip)
        address = f"{coordinator}:{backend_config.coordinator_port}"
        futures = []
        for rank, worker in enumerate(worker_group.workers):
            futures.append(worker.execute.remote(
                _setup_jax_distributed, address,
                len(worker_group), rank))
        import ray_tpu
        ray_tpu.get(futures)

    def on_shutdown(self, worker_group, backend_config: JaxConfig) -> None:
        if worker_group.workers:
            try:
                worker_group.execute(_shutdown_jax_distributed)
            except Exception:
                pass
