from ray_tpu.train.jax.config import JaxConfig
from ray_tpu.train.jax.jax_trainer import JaxTrainer

__all__ = ["JaxConfig", "JaxTrainer"]
