"""Backend interface: per-framework worker-group setup.

Reference: ``python/ray/train/backend.py`` — ``BackendConfig`` +
``Backend`` with ``on_start``/``on_training_start``/``on_shutdown`` hooks
(the Torch backend uses these to run ``dist.init_process_group``,
``train/torch/config.py:146``). Here the flagship backend is JAX/TPU:
the hook runs ``jax.distributed`` coordination instead of NCCL.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Type

if TYPE_CHECKING:
    from ray_tpu.train._internal.worker_group import WorkerGroup


@dataclass
class BackendConfig:
    @property
    def backend_cls(self) -> Type["Backend"]:
        return Backend


class Backend:
    """No-op base backend."""

    share_cuda_visible_devices: bool = False  # reference parity; unused

    def on_start(self, worker_group: "WorkerGroup",
                 backend_config: BackendConfig) -> None:
        pass

    def on_training_start(self, worker_group: "WorkerGroup",
                          backend_config: BackendConfig) -> None:
        pass

    def on_shutdown(self, worker_group: "WorkerGroup",
                    backend_config: BackendConfig) -> None:
        pass
