"""DataParallelTrainer: run one train_func per worker in SPMD.

Reference: ``python/ray/train/data_parallel_trainer.py:22``
(``training_loop`` :419): BackendExecutor start → start_training →
drain results → finish, with ``FailureConfig.max_failures`` gang
restarts from the latest checkpoint (``backend_executor.py:690``).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.base_trainer import BaseTrainer
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.backend_executor import (
    BackendExecutor, TrainingWorkerError)
from ray_tpu.train.result import Result


def _wrap_train_func(train_func: Callable,
                     config: Optional[Dict[str, Any]]) -> Callable[[], Any]:
    sig = inspect.signature(train_func)
    if len(sig.parameters) == 0:
        return train_func
    cfg = dict(config or {})
    return lambda: train_func(cfg)


class DataParallelTrainer(BaseTrainer):
    _backend_config_cls = BackendConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 dataset_config: Optional[Any] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 metadata: Optional[Dict[str, Any]] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint,
                         metadata=metadata)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.backend_config = backend_config or self._backend_config_cls()
        self.datasets = datasets or {}
        self.dataset_config = dataset_config

    def _dataset_shards(self, num_workers: int):
        """Split each dataset into per-worker shards
        (reference ``DataConfig.configure``,
        ``train/_internal/data_config.py``)."""
        if not self.datasets:
            return None
        shards = [dict() for _ in range(num_workers)]
        for name, ds in self.datasets.items():
            split = getattr(ds, "streaming_split", None)
            if split is not None:
                for i, shard in enumerate(split(num_workers)):
                    shards[i][name] = shard
            else:
                for i in range(num_workers):
                    shards[i][name] = ds
        return shards

    def training_loop(self) -> Result:
        storage = self._make_storage()
        manager = self._make_checkpoint_manager(storage)
        failure_config = self.run_config.failure_config
        train_func = _wrap_train_func(
            self.train_loop_per_worker, self.train_loop_config)

        executor = BackendExecutor(
            backend_config=self.backend_config,
            scaling_config=self.scaling_config,
            storage=storage,
            experiment_name=self.run_config.name or "",
            trial_name=self.run_config.name or "",
            trial_id=self.run_config.name or "")

        latest_metrics: Dict[str, Any] = {}
        checkpoint = self.resume_from_checkpoint
        failures = 0
        error: Optional[BaseException] = None
        # Inside a Tune trial the trial's placement group already reserves
        # the worker bundles — reuse it instead of reserving twice.
        from ray_tpu.tune._trial_context import get_trial_placement_group
        trial_pg = get_trial_placement_group()
        try:
            executor.start(placement_group=trial_pg)
            executor.start_training(
                train_func, checkpoint=checkpoint,
                dataset_shards=self._dataset_shards(
                    self.scaling_config.num_workers))
            while True:
                try:
                    results = executor.get_next_results()
                except TrainingWorkerError as e:
                    max_failures = failure_config.max_failures
                    if failure_config.fail_fast or (
                            max_failures >= 0 and failures >= max_failures):
                        error = e
                        break
                    failures += 1
                    # Gang restart from the last persisted checkpoint.
                    checkpoint = manager.latest_checkpoint or checkpoint
                    executor.restart()
                    executor.start_training(
                        train_func, checkpoint=checkpoint,
                        dataset_shards=self._dataset_shards(
                            self.scaling_config.num_workers))
                    continue
                except BaseException as e:
                    error = e
                    break
                if results is None:
                    break
                # Rank 0's metrics are the run's metrics (reference
                # convention); rank 0's checkpoint is registered.
                latest_metrics = dict(results[0].metrics)
                ckpt = results[0].checkpoint
                if ckpt is not None:
                    manager.register_checkpoint(ckpt, latest_metrics)
                    # Advance the driver-side index so a gang restart
                    # hands workers a StorageContext that numbers past
                    # already-persisted checkpoints.
                    import os as _os
                    base = _os.path.basename(ckpt.path.rstrip("/"))
                    if base.startswith("checkpoint_"):
                        storage.current_checkpoint_index = max(
                            storage.current_checkpoint_index,
                            int(base.split("_")[-1]) + 1)
        finally:
            executor.shutdown()

        return Result(
            metrics=latest_metrics or None,
            checkpoint=manager.latest_checkpoint,
            path=storage.trial_dir,
            error=error,
            best_checkpoints=manager.checkpoints)
