"""ray_tpu.train: distributed training (reference: ``python/ray/train/``).

Public surface mirrors ``ray.train``: configs, Checkpoint, Result,
``report``/``get_checkpoint``/``get_context``/``get_dataset_shard``, the
generic DataParallelTrainer, and the flagship JaxTrainer (TPU-native
replacement for the reference's TorchTrainer)."""

from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train.result import Result
from ray_tpu.train._internal.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.base_trainer import BaseTrainer
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.jax import JaxConfig, JaxTrainer

__all__ = [
    "Backend",
    "BackendConfig",
    "BaseTrainer",
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainContext",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "report",
]
