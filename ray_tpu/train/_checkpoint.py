"""Checkpoint: a directory snapshot addressed by URI.

Reference: ``python/ray/train/_checkpoint.py:56`` — a Checkpoint is a
directory of files at a (possibly remote) filesystem path, created from /
materialized to local directories. TPU-first delta: ``from_jax`` /
``to_jax`` store pytrees via numpy ``.npz`` flattening so a checkpoint
written under ``jit`` donation survives process death without orbax being
required (orbax can still be layered on by the user).
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional

_METADATA_FILE = ".metadata.json"
_JAX_PYTREE_FILE = "_pytree.npz"
_JAX_TREEDEF_FILE = "_treedef.pkl"


class Checkpoint:
    """A directory snapshot. ``path`` is the canonical location."""

    def __init__(self, path: str):
        self.path = os.path.abspath(os.path.expanduser(path))

    # -- construction -------------------------------------------------
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        with open(os.path.join(d, "dict_checkpoint.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    @classmethod
    def from_jax(cls, pytree: Any, **extra: Any) -> "Checkpoint":
        """Save a JAX pytree (params/opt state) as npz + treedef."""
        import jax
        import numpy as np
        d = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        leaves, treedef = jax.tree_util.tree_flatten(pytree)
        arrays = {f"leaf_{i}": np.asarray(leaf)
                  for i, leaf in enumerate(leaves)}
        np.savez(os.path.join(d, _JAX_PYTREE_FILE), **arrays)
        with open(os.path.join(d, _JAX_TREEDEF_FILE), "wb") as f:
            pickle.dump(treedef, f)
        if extra:
            with open(os.path.join(d, "dict_checkpoint.pkl"), "wb") as f:
                pickle.dump(extra, f)
        return cls(d)

    # -- materialization ----------------------------------------------
    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        if os.path.abspath(path) != self.path:
            shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        # Local checkpoints are served in place, zero-copy.
        yield self.path

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "dict_checkpoint.pkl"), "rb") as f:
            return pickle.load(f)

    def to_jax(self) -> Any:
        import jax
        import numpy as np
        data = np.load(os.path.join(self.path, _JAX_PYTREE_FILE))
        leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
        with open(os.path.join(self.path, _JAX_TREEDEF_FILE), "rb") as f:
            treedef = pickle.load(f)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- metadata -----------------------------------------------------
    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, _METADATA_FILE)
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, _METADATA_FILE), "w") as f:
            json.dump(metadata, f)

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        m = self.get_metadata()
        m.update(metadata)
        self.set_metadata(m)

    def __repr__(self) -> str:
        return f"Checkpoint(path={self.path!r})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))
