"""Result: the outcome of one training/tuning run.

Reference: ``python/ray/air/result.py`` (re-exported as
``ray.train.Result``) — final metrics, best/latest checkpoint, error,
and the run's storage path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.train._checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional[Checkpoint]
    path: Optional[str] = None
    error: Optional[BaseException] = None
    metrics_dataframe: Optional[Any] = None
    best_checkpoints: List[Tuple[Checkpoint, Dict[str, Any]]] = field(
        default_factory=list)

    @property
    def config(self) -> Optional[Dict[str, Any]]:
        return (self.metrics or {}).get("config")
