"""ray_tpu.air: shared ML plumbing (reference: ``python/ray/air/``)."""

from ray_tpu.air.config import (
    ScalingConfig,
    RunConfig,
    FailureConfig,
    CheckpointConfig,
)

__all__ = [
    "ScalingConfig",
    "RunConfig",
    "FailureConfig",
    "CheckpointConfig",
]
