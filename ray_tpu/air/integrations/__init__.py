"""Experiment-tracking integrations (reference:
``python/ray/air/integrations/`` — wandb/mlflow/comet/keras Tune
callback adapters). Each adapter import-gates on its tracking library;
the hermetic TPU image does not bake them, so construction raises a
clear error telling the operator to add the package to the image."""
