"""MLflow integration (reference:
``python/ray/air/integrations/mlflow.py`` — ``MLflowLoggerCallback``
logs one MLflow run per trial; ``setup_mlflow`` configures the client
inside a worker)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.callback import Callback, _scrub


def _require_mlflow():
    try:
        import mlflow
        return mlflow
    except ImportError as e:
        raise ImportError(
            "MLflowLoggerCallback needs the `mlflow` package, which is "
            "not baked into the hermetic TPU image — add it to the image "
            "to enable MLflow tracking") from e


class MLflowLoggerCallback(Callback):
    def __init__(self, tracking_uri: Optional[str] = None,
                 experiment_name: Optional[str] = None,
                 tags: Optional[Dict[str, Any]] = None,
                 save_artifact: bool = False):
        self._mlflow = _require_mlflow()
        if tracking_uri:
            self._mlflow.set_tracking_uri(tracking_uri)
        self.experiment_name = experiment_name
        self.tags = tags or {}
        self.save_artifact = save_artifact
        self._runs: Dict[str, Any] = {}
        self._client = None

    def setup(self, **info):
        self._client = self._mlflow.tracking.MlflowClient()
        exp = self._client.get_experiment_by_name(
            self.experiment_name) if self.experiment_name else None
        if exp is None and self.experiment_name:
            self._exp_id = self._client.create_experiment(
                self.experiment_name)
        elif exp is not None:
            self._exp_id = exp.experiment_id
        else:
            self._exp_id = "0"

    def on_trial_start(self, iteration, trials, trial, **info):
        run = self._client.create_run(
            experiment_id=self._exp_id,
            tags={**self.tags, "trial_name": trial.trial_name})
        self._runs[trial.trial_id] = run.info.run_id
        for k, v in trial.config.items():
            try:
                self._client.log_param(run.info.run_id, k, v)
            except Exception:
                pass

    def on_trial_result(self, iteration, trials, trial, result, **info):
        run_id = self._runs.get(trial.trial_id)
        if run_id is None:
            return
        step = int(result.get("training_iteration", iteration))
        for k, v in _scrub(result).items():
            if isinstance(v, (int, float)):
                self._client.log_metric(run_id, k.replace("/", "."),
                                        float(v), step=step)

    def on_trial_complete(self, iteration, trials, trial, **info):
        run_id = self._runs.pop(trial.trial_id, None)
        if run_id is not None:
            if self.save_artifact and getattr(trial, "checkpoint", None):
                try:
                    self._client.log_artifacts(
                        run_id, trial.checkpoint.path)
                except Exception:
                    pass
            self._client.set_terminated(run_id)

    def on_trial_error(self, iteration, trials, trial, **info):
        run_id = self._runs.pop(trial.trial_id, None)
        if run_id is not None:
            self._client.set_terminated(run_id, status="FAILED")


def setup_mlflow(config: Optional[Dict] = None,
                 tracking_uri: Optional[str] = None,
                 experiment_name: Optional[str] = None, **kwargs: Any):
    """Worker-side MLflow setup (reference ``setup_mlflow``)."""
    mlflow = _require_mlflow()
    if tracking_uri:
        mlflow.set_tracking_uri(tracking_uri)
    if experiment_name:
        mlflow.set_experiment(experiment_name)
    return mlflow
