"""Weights & Biases integration (reference:
``python/ray/air/integrations/wandb.py`` — ``WandbLoggerCallback``
creates one wandb run per trial and streams scrubbed results;
``setup_wandb`` initializes a run inside a Train/Tune worker)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.tune.callback import Callback, _scrub


def _require_wandb():
    try:
        import wandb
        return wandb
    except ImportError as e:
        raise ImportError(
            "WandbLoggerCallback needs the `wandb` package, which is not "
            "baked into the hermetic TPU image — add it to the image to "
            "enable W&B tracking") from e


class WandbLoggerCallback(Callback):
    """One wandb run per trial; results stream as wandb.log rows."""

    def __init__(self, project: Optional[str] = None,
                 group: Optional[str] = None,
                 api_key: Optional[str] = None,
                 excludes: Optional[List[str]] = None,
                 log_config: bool = False, **kwargs: Any):
        self._wandb = _require_wandb()
        if api_key:
            self._wandb.login(key=api_key)
        self.project = project
        self.group = group
        self.excludes = set(excludes or ())
        self.log_config = log_config
        self.kwargs = kwargs
        self._runs: Dict[str, Any] = {}

    def on_trial_start(self, iteration, trials, trial, **info):
        self._runs[trial.trial_id] = self._wandb.init(
            project=self.project, group=self.group,
            name=trial.trial_name, reinit=True,
            config=trial.config if self.log_config else None,
            **self.kwargs)

    def on_trial_result(self, iteration, trials, trial, result, **info):
        run = self._runs.get(trial.trial_id)
        if run is None:
            return
        flat = {k: v for k, v in _scrub(result).items()
                if k not in self.excludes
                and isinstance(v, (int, float))}
        run.log(flat)

    def on_trial_complete(self, iteration, trials, trial, **info):
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.finish()

    on_trial_error = on_trial_complete

    def on_experiment_end(self, trials, **info):
        for run in self._runs.values():
            try:
                run.finish()
            except Exception:
                pass
        self._runs.clear()


def setup_wandb(config: Optional[Dict] = None, **kwargs: Any):
    """Worker-side init (reference ``setup_wandb``): call from inside a
    train loop to get a wandb run bound to this trial."""
    wandb = _require_wandb()
    from ray_tpu.train._internal.session import get_session
    session = get_session()
    trial_name = getattr(session, "trial_name", None) if session else None
    return wandb.init(name=trial_name, config=config, **kwargs)
