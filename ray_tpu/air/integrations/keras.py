"""Keras integration (reference:
``python/ray/air/integrations/keras.py`` — ``ReportCheckpointCallback``
reports metrics + checkpoints to the Train session at epoch end)."""

from __future__ import annotations

import os
import tempfile
from typing import Dict, List, Optional, Union


def _keras_base():
    try:
        import keras
        return keras.callbacks.Callback
    except ImportError:
        try:
            from tensorflow import keras  # type: ignore
            return keras.callbacks.Callback
        except ImportError as e:
            raise ImportError(
                "ReportCheckpointCallback needs `keras` (or tensorflow), "
                "which is not baked into the hermetic TPU image") from e


def ReportCheckpointCallback(
        metrics: Optional[Union[str, List[str], Dict[str, str]]] = None,
        checkpoint_on: str = "epoch_end"):
    """Factory (class is built lazily so importing this module does not
    require keras)."""
    Base = _keras_base()

    class _ReportCheckpointCallback(Base):  # type: ignore[misc]
        def __init__(self):
            super().__init__()
            self._metrics = metrics

        def on_epoch_end(self, epoch, logs=None):
            from ray_tpu.train import report
            from ray_tpu.train._checkpoint import Checkpoint
            logs = logs or {}
            if isinstance(self._metrics, str):
                out = {self._metrics: logs.get(self._metrics)}
            elif isinstance(self._metrics, list):
                out = {m: logs.get(m) for m in self._metrics}
            elif isinstance(self._metrics, dict):
                out = {k: logs.get(v) for k, v in self._metrics.items()}
            else:
                out = dict(logs)
            ckpt = None
            if checkpoint_on == "epoch_end":
                d = tempfile.mkdtemp(prefix="keras_ckpt_")
                self.model.save(os.path.join(d, "model.keras"))
                ckpt = Checkpoint.from_directory(d)
            report(out, checkpoint=ckpt)

    return _ReportCheckpointCallback()
