"""Comet ML integration (reference:
``python/ray/air/integrations/comet.py`` — ``CometLoggerCallback``:
one Comet experiment per trial)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.tune.callback import Callback, _scrub


def _require_comet():
    try:
        import comet_ml
        return comet_ml
    except ImportError as e:
        raise ImportError(
            "CometLoggerCallback needs the `comet_ml` package, which is "
            "not baked into the hermetic TPU image — add it to the image "
            "to enable Comet tracking") from e


class CometLoggerCallback(Callback):
    def __init__(self, online: bool = True,
                 tags: Optional[List[str]] = None, **experiment_kwargs):
        self._comet = _require_comet()
        self.online = online
        self.tags = tags or []
        self.experiment_kwargs = experiment_kwargs
        self._experiments: Dict[str, Any] = {}

    def on_trial_start(self, iteration, trials, trial, **info):
        cls = (self._comet.Experiment if self.online
               else self._comet.OfflineExperiment)
        exp = cls(**self.experiment_kwargs)
        exp.set_name(trial.trial_name)
        exp.add_tags(self.tags)
        exp.log_parameters(trial.config)
        self._experiments[trial.trial_id] = exp

    def on_trial_result(self, iteration, trials, trial, result, **info):
        exp = self._experiments.get(trial.trial_id)
        if exp is None:
            return
        step = int(result.get("training_iteration", iteration))
        exp.log_metrics(
            {k: v for k, v in _scrub(result).items()
             if isinstance(v, (int, float))}, step=step)

    def on_trial_complete(self, iteration, trials, trial, **info):
        exp = self._experiments.pop(trial.trial_id, None)
        if exp is not None:
            exp.end()

    on_trial_error = on_trial_complete

    def on_experiment_end(self, trials, **info):
        for exp in self._experiments.values():
            try:
                exp.end()
            except Exception:
                pass
        self._experiments.clear()
