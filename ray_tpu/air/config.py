"""Shared ML-plumbing configs (reference: ``python/ray/air/config.py``:
``ScalingConfig`` :101, ``FailureConfig`` :375, ``CheckpointConfig`` :425,
``RunConfig`` :574).

TPU-first deltas: ``ScalingConfig`` speaks TPU chips (``use_tpu``/
``tpus_per_worker``) and a ``topology`` string (e.g. ``"v5e-64"``) whose
gang resource (``TPU-{topology}-head``) pins one trainer actor per host of
a pod slice, mirroring the reference accelerator hook
(``python/ray/_private/accelerators/tpu.py:379``).
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ScalingConfig:
    """How much compute a trainer gets (reference ``air/config.py:101``)."""

    num_workers: int = 1
    use_tpu: bool = False
    use_gpu: bool = False  # accepted for API parity; maps onto chips
    trainer_resources: Optional[Dict[str, float]] = None
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None  # e.g. "v5e-64": gang-schedule a slice

    def __post_init__(self):
        if self.use_gpu and not self.use_tpu:
            # This framework is TPU-native; treat GPU requests as chips.
            self.use_tpu = True

    @property
    def _chips_per_worker(self) -> float:
        rpw = self.resources_per_worker or {}
        if "TPU" in rpw:
            return float(rpw["TPU"])
        return 1.0 if self.use_tpu else 0.0

    def worker_bundle(self) -> Dict[str, float]:
        rpw = dict(self.resources_per_worker or {})
        bundle: Dict[str, float] = {}
        bundle["CPU"] = float(rpw.pop("CPU", 0.0 if self.use_tpu else 1.0))
        chips = rpw.pop("TPU", self._chips_per_worker)
        if chips:
            bundle["TPU"] = float(chips)
        bundle.update({k: float(v) for k, v in rpw.items()})
        return bundle

    def trainer_bundle(self) -> Dict[str, float]:
        tr = dict(self.trainer_resources or {"CPU": 1.0})
        return {k: float(v) for k, v in tr.items()}

    def as_placement_group_factory(self):
        from ray_tpu.tune.placement_groups import PlacementGroupFactory
        bundles = [self.trainer_bundle()] + [
            self.worker_bundle() for _ in range(self.num_workers)]
        if self.topology:
            # Reserve the slice's gang resource on the first worker bundle,
            # like the reference's TPU-{pod_type}-head custom resource.
            bundles[1] = dict(bundles[1])
            bundles[1][f"TPU-{self.topology}-head"] = 1.0
        return PlacementGroupFactory(
            bundles, strategy=self.placement_strategy)

    @property
    def total_resources(self) -> Dict[str, float]:
        total: Dict[str, float] = dict(self.trainer_bundle())
        wb = self.worker_bundle()
        for k, v in wb.items():
            total[k] = total.get(k, 0.0) + v * self.num_workers
        return total


@dataclass
class FailureConfig:
    """Restart-from-checkpoint policy (reference ``air/config.py:375``)."""

    max_failures: int = 0
    fail_fast: bool = False


@dataclass
class CheckpointConfig:
    """Top-K checkpoint retention (reference ``air/config.py:425``)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: Optional[bool] = None

    def __post_init__(self):
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be None or > 0")
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    """Run-level config (reference ``air/config.py:574``)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
    log_to_file: bool = False
    #: tune.Callback instances (loggers, experiment trackers)
    callbacks: Optional[list] = None

    def __post_init__(self):
        if self.storage_path is None:
            self.storage_path = os.path.expanduser(
                os.environ.get("RAY_TPU_STORAGE_PATH", "~/ray_tpu_results"))
