"""Head-node daemon entry: ``python -m ray_tpu.scripts.head``.

Runs controller + node manager and blocks until signaled. Started by
``ray-tpu start --head`` (reference analog:
``python/ray/_private/services.py`` daemon spawning).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
import uuid


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--session-dir", default=None)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default="{}")
    p.add_argument("--initial-workers", type=int, default=2)
    args = p.parse_args()

    import ray_tpu
    # A head daemon must not inherit a driver's RAY_TPU_ADDRESS: it IS
    # the cluster. --session-dir pins the session path if given.
    os.environ.pop("RAY_TPU_ADDRESS", None)
    info = ray_tpu.init(
        num_cpus=args.num_cpus, num_tpus=args.num_tpus,
        resources=json.loads(args.resources),
        _num_initial_workers=args.initial_workers,
        _session_dir=args.session_dir)
    # Publish the default address for `ray-tpu` subcommands and drivers.
    os.makedirs("/tmp/ray_tpu", exist_ok=True)
    with open("/tmp/ray_tpu/latest_session", "w") as f:
        f.write(info["session_dir"])
    print(f"ray_tpu head running; session_dir={info['session_dir']}")
    sys.stdout.flush()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
