"""Head-node daemon entry: ``python -m ray_tpu.scripts.head``.

Runs controller + node manager and blocks until signaled. Started by
``ray-tpu start --head`` (reference analog:
``python/ray/_private/services.py`` daemon spawning).

With ``--cluster-config <yaml>`` the head also owns the slice layer:
when the config has a ``slices:`` section it constructs the
SliceManager (``autoscaler/launcher.py::build_slice_manager`` — slices
the launcher already created are adopted, not re-acquired) and polls it
under an ``AutoscalerMonitor``, so pending SLICE_PACK/SLICE_SPREAD
gangs acquire slices and maintenance drains run WITHOUT any driver or
test building the manager by hand (ROADMAP item 1).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
import uuid


def _start_slice_monitor(config_path: str, interval_s: float):
    """Build the SliceManager from the cluster config and start its
    monitor loop. When the config also has an ``arbiter:`` section the
    monitor drives the SliceArbiter instead — it reconciles the
    manager first each tick, then arbitrates slices between the serve
    fleet and training off the metrics plane's fleet gauges. Returns
    (monitor, manager) or (None, None) when the config has no slices
    section."""
    import ray_tpu.api as api
    from ray_tpu.autoscaler.autoscaler import AutoscalerMonitor
    from ray_tpu.autoscaler.launcher import (
        build_slice_arbiter, build_slice_manager, load_cluster_config)

    cfg = load_cluster_config(config_path)
    mgr = build_slice_manager(api._head.controller, cfg)
    if mgr is None:
        return None, None
    arbiter = build_slice_arbiter(mgr, cfg)
    if arbiter is not None:
        api._head.controller.slice_arbiter = arbiter
    monitor = AutoscalerMonitor(arbiter if arbiter is not None
                                else mgr, interval_s=interval_s)
    monitor.start()
    print(f"ray_tpu head: slice monitor up "
          f"({', '.join(sorted(mgr.slice_types))})"
          + (" + arbiter" if arbiter is not None else ""))
    sys.stdout.flush()
    return monitor, mgr


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--session-dir", default=None)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default="{}")
    p.add_argument("--initial-workers", type=int, default=2)
    p.add_argument("--cluster-config", default=None,
                   help="validated cluster YAML; a slices: section "
                        "auto-starts the SliceManager monitor")
    p.add_argument("--slice-monitor-interval-s", type=float,
                   default=1.0)
    args = p.parse_args()

    import ray_tpu
    # A head daemon must not inherit a driver's RAY_TPU_ADDRESS: it IS
    # the cluster. --session-dir pins the session path if given.
    os.environ.pop("RAY_TPU_ADDRESS", None)
    info = ray_tpu.init(
        num_cpus=args.num_cpus, num_tpus=args.num_tpus,
        resources=json.loads(args.resources),
        _num_initial_workers=args.initial_workers,
        _session_dir=args.session_dir)
    # Publish the default address for `ray-tpu` subcommands and drivers.
    os.makedirs("/tmp/ray_tpu", exist_ok=True)
    with open("/tmp/ray_tpu/latest_session", "w") as f:
        f.write(info["session_dir"])
    print(f"ray_tpu head running; session_dir={info['session_dir']}")
    sys.stdout.flush()

    monitor = mgr = None
    if args.cluster_config:
        try:
            monitor, mgr = _start_slice_monitor(
                args.cluster_config, args.slice_monitor_interval_s)
        except Exception as e:  # noqa: BLE001 — head must still serve
            print(f"ray_tpu head: slice monitor failed to start: {e}")
            sys.stdout.flush()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    if monitor is not None:
        monitor.stop()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
