"""The ``ray-tpu`` CLI.

Reference: ``python/ray/scripts/scripts.py`` (``start`` :567, ``stop``
:1043, ``submit`` :1577, status/memory/timeline/microbenchmark and the
``ray list``/``ray summary`` state commands from ``state_cli.py``).
Run as ``python -m ray_tpu.scripts.cli <command>``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

LATEST = "/tmp/ray_tpu/latest_session"
PIDFILE = "/tmp/ray_tpu/head.pid"


def _default_address() -> str:
    addr = os.environ.get("RAY_TPU_ADDRESS")
    if addr:
        return addr
    if os.path.exists(LATEST):
        with open(LATEST) as f:
            return f.read().strip()
    raise SystemExit(
        "No running cluster found (start one with `ray-tpu start --head`"
        " or set RAY_TPU_ADDRESS)")


def _connect():
    import ray_tpu
    ray_tpu.init(address=_default_address())
    return ray_tpu


def cmd_start(args) -> None:
    os.makedirs("/tmp/ray_tpu", exist_ok=True)
    if args.head:
        cmd = [sys.executable, "-m", "ray_tpu.scripts.head",
               "--initial-workers", str(args.initial_workers)]
        if args.num_cpus is not None:
            cmd += ["--num-cpus", str(args.num_cpus)]
        if args.num_tpus is not None:
            cmd += ["--num-tpus", str(args.num_tpus)]
        if args.resources:
            cmd += ["--resources", args.resources]
        log = open("/tmp/ray_tpu/head.log", "ab")
        proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                                start_new_session=True)
        with open(PIDFILE, "w") as f:
            f.write(str(proc.pid))
        for _ in range(100):
            if os.path.exists(LATEST):
                mtime = os.path.getmtime(LATEST)
                if mtime >= time.time() - 60:
                    break
            time.sleep(0.2)
        print(f"Started head (pid {proc.pid}); "
              f"address: {_default_address()}")
    else:
        address = args.address or _default_address()
        cmd = [sys.executable, "-m", "ray_tpu.core.node",
               "--session-dir", address,
               "--initial-workers", str(args.initial_workers)]
        if args.num_cpus is not None:
            cmd += ["--num-cpus", str(args.num_cpus)]
        if args.num_tpus is not None:
            cmd += ["--num-tpus", str(args.num_tpus)]
        if getattr(args, "labels", None):
            # "k=v,k2=v2" — the cluster launcher stamps
            # ray-tpu-node-id=<slice> here so the autoscaler can join
            # provider slices to registered nodes
            labels = {}
            for kv in args.labels.split(","):
                if "=" not in kv:
                    raise SystemExit(
                        f"--labels: {kv!r} is not k=v (values must "
                        f"not contain commas)")
                k, v = kv.split("=", 1)
                labels[k] = v
            cmd += ["--labels", json.dumps(labels)]
        log = open("/tmp/ray_tpu/node.log", "ab")
        proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                                start_new_session=True)
        print(f"Started node (pid {proc.pid}) joined to {address}")


def cmd_stop(args) -> None:
    if os.path.exists(PIDFILE):
        with open(PIDFILE) as f:
            pid = int(f.read())
        try:
            os.kill(pid, signal.SIGTERM)
            print(f"Stopped head (pid {pid})")
        except ProcessLookupError:
            print("Head already stopped")
        os.remove(PIDFILE)
    for f in (LATEST,):
        if os.path.exists(f):
            os.remove(f)


def cmd_status(args) -> None:
    ray_tpu = _connect()
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    nodes = ray_tpu.nodes()
    print(f"Nodes: {sum(1 for n in nodes if n['alive'])} alive "
          f"/ {len(nodes)} total")
    print("Resources:")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0):g}/{total[k]:g} available")


def cmd_list(args) -> None:
    _connect()
    from ray_tpu.util import state
    fn = getattr(state, f"list_{args.what}", None)
    if fn is None:
        raise SystemExit(f"Cannot list {args.what!r}")
    filters = []
    for f in args.filter or []:
        if "!=" in f:
            k, v = f.split("!=", 1)
            filters.append((k, "!=", v))
        else:
            k, v = f.split("=", 1)
            filters.append((k, "=", v))
    rows = fn(filters=filters, limit=args.limit)
    print(json.dumps(rows, indent=2, default=str))


def cmd_summary(args) -> None:
    _connect()
    from ray_tpu.util import state
    fn = getattr(state, f"summarize_{args.what}")
    print(json.dumps(fn(), indent=2, default=str))


def cmd_memory(args) -> None:
    _connect()
    from ray_tpu.util import state
    print(json.dumps(state.summarize_objects(), indent=2))


def cmd_top(args) -> None:
    """`ray-tpu top` — live fleet view from the cluster metrics plane
    (tools/top.py renders; the dashboard's /api/v0/metrics/fleet
    serves)."""
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.path.insert(0, repo_root)
    try:
        from tools.top import main as top_main
    except ImportError:
        raise SystemExit(
            "ray-tpu top needs tools/top.py from the repository "
            "checkout (run `python tools/top.py` directly)")
    argv = []
    if args.dashboard:
        argv += ["--dashboard", args.dashboard]
    if args.once:
        argv += ["--once"]
    argv += ["--interval", str(args.interval),
             "--window", str(args.window)]
    raise SystemExit(top_main(argv))


def cmd_trace(args) -> None:
    """`ray-tpu trace <request_id>` — one serve request's waterfall
    from the controller's tail-sampled trace store (tools/trace.py
    renders; the dashboard's /api/v0/requests/<id> serves)."""
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.path.insert(0, repo_root)
    try:
        from tools.trace import main as trace_main
    except ImportError:
        raise SystemExit(
            "ray-tpu trace needs tools/trace.py from the repository "
            "checkout (run `python tools/trace.py` directly)")
    argv = []
    if args.request_id:
        argv.append(args.request_id)
    if args.dashboard:
        argv += ["--dashboard", args.dashboard]
    if args.input:
        argv += ["--input", args.input]
    if args.perfetto:
        argv += ["--perfetto", args.perfetto]
    if not args.dashboard and not args.input:
        _connect()
    raise SystemExit(trace_main(argv))


def cmd_timeline(args) -> None:
    ray_tpu = _connect()
    out = args.output or f"/tmp/ray_tpu/timeline_{int(time.time())}.json"
    ray_tpu.timeline(filename=out)
    print(f"Wrote Chrome trace to {out}")


def cmd_submit(args) -> None:
    env = dict(os.environ)
    env["RAY_TPU_ADDRESS"] = args.address or _default_address()
    raise SystemExit(subprocess.call(
        [sys.executable, args.script] + args.script_args, env=env))


def cmd_microbenchmark(args) -> None:
    import ray_tpu
    from ray_tpu.scripts.perf import main as perf_main
    perf_main()


def _job_client(args):
    from ray_tpu.job_submission import JobSubmissionClient
    addr = getattr(args, "address", None)
    if addr is None and not os.environ.get("RAY_TPU_DASHBOARD_ADDRESS"):
        os.environ.setdefault("RAY_TPU_SESSION_DIR", _default_address())
    return JobSubmissionClient(addr)


def cmd_job(args) -> None:
    """`ray-tpu job ...` — REST job API (reference: `ray job` CLI,
    dashboard/modules/job/cli.py)."""
    import shlex
    client = _job_client(args)
    if args.job_cmd == "submit":
        jid = client.submit_job(
            # shlex.join keeps each argv element intact through the job
            # manager's `sh -c` re-parse (plain join would corrupt
            # arguments with spaces/quotes)
            entrypoint=shlex.join(args.entrypoint),
            runtime_env=json.loads(args.runtime_env)
            if args.runtime_env else None,
            priority=args.priority, elastic=args.elastic)
        print(jid)
        if not args.no_wait:
            try:
                status = client.wait_until_status(
                    jid, timeout_s=args.timeout)
            except TimeoutError:
                print(f"Job {jid} still running after {args.timeout}s "
                      f"(check later with `ray-tpu job status {jid}`)")
                raise SystemExit(2)
            sys.stdout.write(client.get_job_logs(jid))
            print(f"Job {jid}: {status}")
            raise SystemExit(0 if status == "SUCCEEDED" else 1)
    elif args.job_cmd == "list":
        print(json.dumps(client.list_jobs(), indent=2, default=str))
        try:
            arb = client.get_arbiter_status()
        except RuntimeError:
            arb = None   # head runs without an arbiter: section
        if arb and arb.get("rows"):
            print("-- slice arbitration "
                  f"(pressure={'yes' if arb.get('pressure') else 'no'},"
                  f" preemptions={arb.get('preemptions', 0)},"
                  f" returns={arb.get('returns', 0)}) --")
            for r in arb["rows"]:
                print(f"  {r['slice_id']}  {r['kind']:<5}  "
                      f"prio={r['priority']:<3} {r['state']:<9} "
                      f"owner={r['owner']}  {r['why']}")
    elif args.job_cmd == "status":
        print(client.get_job_status(args.submission_id))
    elif args.job_cmd == "logs":
        sys.stdout.write(client.get_job_logs(args.submission_id))
    elif args.job_cmd == "stop":
        print(client.stop_job(args.submission_id))


def _cluster_config(args) -> str:
    path = getattr(args, "config_opt", None) or args.config
    if not path:
        raise SystemExit("a cluster YAML is required "
                         "(ray-tpu up --config cluster.yaml)")
    return path


def cmd_up(args) -> None:
    """Create/bootstrap a cluster from YAML (reference: `ray up`,
    commands.py:create_or_update_cluster). Fake providers
    (`type: fake_slice`) get the local round-trip: head daemon + every
    slice's host VMs as local node-manager processes."""
    from ray_tpu.autoscaler.launcher import (
        load_cluster_config, make_launcher)
    cfg = load_cluster_config(_cluster_config(args))
    if not args.yes:
        ans = input(f"Launch cluster {cfg['cluster_name']!r} "
                    f"({cfg['provider']['type']})? [y/N] ")
        if ans.strip().lower() not in ("y", "yes"):
            print("aborted")
            return
    out = make_launcher(cfg).up()
    print(json.dumps(out))


def cmd_down(args) -> None:
    from ray_tpu.autoscaler.launcher import (
        load_cluster_config, make_launcher)
    cfg = load_cluster_config(_cluster_config(args))
    if not args.yes:
        ans = input(f"Tear down cluster {cfg['cluster_name']!r}? [y/N] ")
        if ans.strip().lower() not in ("y", "yes"):
            print("aborted")
            return
    out = make_launcher(cfg).down(keep_head=args.keep_head)
    if isinstance(out, list):  # ClusterLauncher returns the node list
        out = {"terminated": out}
    print(json.dumps(out))


def cmd_attach(args) -> None:
    import subprocess as sp_mod
    from ray_tpu.autoscaler.launcher import (
        ClusterLauncher, load_cluster_config)
    cfg = load_cluster_config(args.config)
    cmd = ClusterLauncher(cfg).attach_command()
    if args.dry_run:
        print(" ".join(cmd))
        return
    sp_mod.run(cmd)


def main() -> None:
    p = argparse.ArgumentParser(prog="ray-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start head or worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", default=None)
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    sp.add_argument("--resources", default=None)
    sp.add_argument("--labels", default=None,
                    help="k=v,k2=v2 node labels (worker mode)")
    sp.add_argument("--initial-workers", type=int, default=2)
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop the head started here")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster resource summary")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("list", help="list cluster state")
    sp.add_argument("what", choices=[
        "actors", "tasks", "objects", "nodes", "placement_groups",
        "jobs", "workers"])
    sp.add_argument("--filter", action="append")
    sp.add_argument("--limit", type=int, default=100)
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("summary", help="summarize cluster state")
    sp.add_argument("what", choices=["tasks", "actors", "objects"])
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("memory", help="object store summary")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser("timeline", help="dump Chrome trace")
    sp.add_argument("--output", default=None)
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser(
        "trace", help="render a serve request's trace waterfall")
    sp.add_argument("request_id", nargs="?", default=None,
                    help="request id (X-Request-Id header / 429 body; "
                    "omit to list the captured tail)")
    sp.add_argument("--dashboard", default=None,
                    help="dashboard address (defaults to the running "
                    "session's)")
    sp.add_argument("--input", default=None,
                    help="waterfall JSON dump instead of a live "
                    "cluster")
    sp.add_argument("--perfetto", default=None, metavar="OUT",
                    help="also export Chrome-trace JSON")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("top", help="live fleet metrics view")
    sp.add_argument("--dashboard", default=None,
                    help="dashboard address (defaults to the running "
                    "session's)")
    sp.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    sp.add_argument("--interval", type=float, default=2.0)
    sp.add_argument("--window", type=float, default=30.0)
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser("submit", help="run a script against the cluster")
    sp.add_argument("script")
    sp.add_argument("script_args", nargs="*")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_submit)

    sp = sub.add_parser("up", help="launch a cluster from YAML config")
    sp.add_argument("config", nargs="?", default=None)
    sp.add_argument("--config", dest="config_opt", default=None,
                    help="cluster YAML (alias of the positional)")
    sp.add_argument("-y", "--yes", action="store_true")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a YAML-config cluster")
    sp.add_argument("config", nargs="?", default=None)
    sp.add_argument("--config", dest="config_opt", default=None,
                    help="cluster YAML (alias of the positional)")
    sp.add_argument("-y", "--yes", action="store_true")
    sp.add_argument("--keep-head", action="store_true")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("attach", help="ssh to the cluster head")
    sp.add_argument("config")
    sp.add_argument("--dry-run", action="store_true",
                    help="print the ssh command instead of running it")
    sp.set_defaults(fn=cmd_attach)

    sp = sub.add_parser("microbenchmark", help="core perf suite")
    sp.set_defaults(fn=cmd_microbenchmark)

    sp = sub.add_parser("job", help="job submission REST API")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    jp = jsub.add_parser("submit", help="submit an entrypoint command")
    jp.add_argument("entrypoint", nargs="+")
    jp.add_argument("--address", default=None)
    jp.add_argument("--runtime-env", default=None,
                    help='JSON, e.g. {"env_vars": {"K": "V"}}')
    jp.add_argument("--priority", default="normal",
                    choices=["low", "normal", "high"],
                    help="slice-arbitration priority: under serve "
                    "pressure the lowest-priority training job's "
                    "slice is preempted first")
    jp.add_argument("--elastic", action="store_true",
                    help="driver survives losing a slice mid-run "
                    "(ElasticTrainer re-lowers instead of dying)")
    jp.add_argument("--no-wait", action="store_true")
    jp.add_argument("--timeout", type=float, default=600.0)
    for name in ("status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("submission_id")
        jp.add_argument("--address", default=None)
    jp = jsub.add_parser("list")
    jp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_job)

    args = p.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()
