"""Core microbenchmark suite.

Reference: ``python/ray/_private/ray_perf.py`` (run as ``ray
microbenchmark``) — the numbers in BASELINE.md §"scalability envelope":
sync/async task throughput, actor call throughput, put throughput.
Prints one JSON line per metric with the reference baseline ratio.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

import numpy as np

# Reference measured numbers (BASELINE.md, release_logs/2.9.2)
BASELINES = {
    "tasks_sync_per_s": 1046.0,
    "tasks_async_per_s": 8159.0,
    "multi_client_tasks_async_per_s": 26697.0,
    "actor_calls_sync_per_s": 2138.0,
    "actor_calls_async_per_s": 9183.0,
    "put_gib_per_s": 19.5,
    "multi_client_put_gib_per_s": 33.6,
}


_MULTI_CLIENT_SRC = """
import sys, time, os
sys.path.insert(0, {repo!r})
import ray_tpu
ray_tpu.init(address={session!r}, log_to_driver=False)
mode = {mode!r}
if mode == "tasks":
    @ray_tpu.remote
    def nop():
        return b"ok"
    ray_tpu.get([nop.remote() for _ in range(100)])
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range({n})])
    print("RESULT", {n} / (time.perf_counter() - t0))
else:
    import numpy as np
    data = np.random.default_rng(0).integers(
        0, 255, size=({mb} << 20,), dtype=np.uint8)
    ray_tpu.put(data)
    t0 = time.perf_counter()
    for _ in range({iters}):
        ray_tpu.put(data)
    print("RESULT", ({mb} * {iters} / 1024.0) / (time.perf_counter() - t0))
ray_tpu.shutdown()
"""


def _run_clients(ray_tpu, mode: str, num_clients: int, **fmt) -> float:
    """Aggregate throughput of N driver processes attached to this
    cluster (reference: multi_client_* phases of ray_perf.py run 4+
    drivers against one cluster)."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from ray_tpu.core.global_state import global_worker
    src = _MULTI_CLIENT_SRC.format(
        repo=repo, session=global_worker().session_dir,
        mode=mode, **fmt)
    procs = [subprocess.Popen(
        [sys.executable, "-c", src], stdout=subprocess.PIPE, text=True,
        env={**os.environ, "RAY_TPU_JAX_PLATFORM": "cpu"})
        for _ in range(num_clients)]
    total = 0.0
    for p in procs:
        out, _ = p.communicate(timeout=300)
        if p.returncode != 0:
            raise RuntimeError(f"client failed rc={p.returncode}")
        vals = [ln.split()[1] for ln in out.splitlines()
                if ln.startswith("RESULT ")]
        total += float(vals[-1])
    return total


def bench_multi_client_tasks(ray_tpu, clients=4, n=1500) -> float:
    return _run_clients(ray_tpu, "tasks", clients, n=n, mb=0, iters=0)


def bench_multi_client_put(ray_tpu, clients=4, mb=32, iters=6) -> float:
    return _run_clients(ray_tpu, "put", clients, n=0, mb=mb, iters=iters)


def bench_rllib_env_steps(ray_tpu, iters=3) -> Optional[float]:
    """PPO sampling+training throughput in env-steps/s. Pipeline shape
    follows the reference's Atari tuned example
    (``rllib/tuned_examples/ppo/atari-ppo.yaml:1-35``: 10 workers x 5
    envs, train_batch 5000) with the worker count scaled to this host's
    CPUs and CartPole standing in for ALE (not in the image). The
    reference publishes no steps/s number for it, so vs_baseline is
    null — the JSON records the trend across rounds."""
    try:
        import gymnasium  # noqa: F401
    except ImportError:
        return None
    from ray_tpu.rllib import PPOConfig
    cpus = int(ray_tpu.cluster_resources().get("CPU", 0))
    if cpus < 3:
        # each runner is a 1-CPU actor; with <2 schedulable runners the
        # pipeline shape is meaningless (and actors would never place)
        return None
    n_runners = min(10, cpus - 1)
    config = (PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=n_runners,
                           num_envs_per_env_runner=5)
              .training(train_batch_size=5000, minibatch_size=500,
                        num_epochs=1, lr=3e-4)
              .debugging(seed=0))
    try:
        algo = config.build()
    except RuntimeError as e:
        if "unable to initialize backend" in str(e).lower():
            # jax can't initialize a device in this process (e.g. the
            # TPU tunnel backend is driver-exclusive): skip rather than
            # fail the whole perf suite
            return None
        raise
    try:
        steps0 = algo.train()["num_env_steps_sampled_lifetime"]
        t0 = time.perf_counter()   # first train() warmed jit + workers
        for _ in range(iters):
            steps = algo.train()["num_env_steps_sampled_lifetime"]
        return (steps - steps0) / (time.perf_counter() - t0)
    finally:
        algo.cleanup()


def bench_tasks_sync(ray_tpu, n=200) -> float:
    @ray_tpu.remote
    def nop():
        return b"ok"

    ray_tpu.get(nop.remote())  # warm worker + export
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(nop.remote())
    return n / (time.perf_counter() - t0)


def bench_tasks_async(ray_tpu, n=2000) -> float:
    @ray_tpu.remote
    def nop():
        return b"ok"

    # warm the worker pool + leases to steady state (the reference's
    # ray_perf phases also run against a warm cluster)
    ray_tpu.get([nop.remote() for _ in range(200)])
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n)])
    return n / (time.perf_counter() - t0)


def bench_actor_sync(ray_tpu, n=500) -> float:
    @ray_tpu.remote
    class A:
        def m(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(a.m.remote())
    dt = time.perf_counter() - t0
    ray_tpu.kill(a)
    return n / dt


def bench_actor_async(ray_tpu, n=5000) -> float:
    @ray_tpu.remote
    class A:
        def m(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get([a.m.remote() for _ in range(100)])
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    ray_tpu.kill(a)
    return n / dt


def bench_put(ray_tpu, mb=64, iters=8) -> float:
    """Matches the reference's single_client_put_gigabytes workload
    (ray_perf.py puts numpy arrays; pickle-5 ships them out-of-band)."""
    data = np.random.default_rng(0).integers(
        0, 255, size=(mb << 20,), dtype=np.uint8)
    ray_tpu.put(data)  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        ray_tpu.put(data)
    dt = time.perf_counter() - t0
    return (mb * iters / 1024.0) / dt


def bench_put_bytes(ray_tpu, mb=64, iters=8) -> float:
    data = np.random.default_rng(0).bytes(mb << 20)
    ray_tpu.put(data)  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        ray_tpu.put(data)
    dt = time.perf_counter() - t0
    return (mb * iters / 1024.0) / dt


def main() -> Dict[str, float]:
    import ray_tpu
    started = False
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4, _num_initial_workers=2)
        started = True
    def settle():
        # let ref-delta GC churn from the previous phase drain so phases
        # are isolated (the reference runs each ray_perf phase separately)
        import gc
        gc.collect()
        time.sleep(1.0)

    results = {}
    for name, fn in (
            ("tasks_sync_per_s", bench_tasks_sync),
            ("tasks_async_per_s", bench_tasks_async),
            ("multi_client_tasks_async_per_s", bench_multi_client_tasks),
            ("actor_calls_sync_per_s", bench_actor_sync),
            ("actor_calls_async_per_s", bench_actor_async),
            ("put_gib_per_s", bench_put),
            ("put_bytes_gib_per_s", bench_put_bytes),
            ("multi_client_put_gib_per_s", bench_multi_client_put),
            ("rllib_env_steps_per_s", bench_rllib_env_steps),
    ):
        out = fn(ray_tpu)
        if out is None:
            continue
        results[name] = out
        settle()
    for name, value in results.items():
        base = BASELINES.get(name)
        print(json.dumps({
            "metric": name, "value": round(value, 1),
            "unit": "GiB/s" if "gib" in name else "1/s",
            "vs_baseline": round(value / base, 3) if base else None,
        }))
    if started:
        ray_tpu.shutdown()
    return results


if __name__ == "__main__":
    main()
