"""Core microbenchmark suite.

Reference: ``python/ray/_private/ray_perf.py`` (run as ``ray
microbenchmark``) — the numbers in BASELINE.md §"scalability envelope":
sync/async task throughput, actor call throughput, put throughput.
Prints one JSON line per metric with the reference baseline ratio.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

import numpy as np

# Reference measured numbers (BASELINE.md, release_logs/2.9.2)
BASELINES = {
    "tasks_sync_per_s": 1046.0,
    "tasks_async_per_s": 8159.0,
    "multi_client_tasks_async_per_s": 26697.0,
    "actor_calls_sync_per_s": 2138.0,
    "actor_calls_async_per_s": 9183.0,
    "put_gib_per_s": 19.5,
    "multi_client_put_gib_per_s": 33.6,
}


_MULTI_CLIENT_SRC = """
import sys, time, os
sys.path.insert(0, {repo!r})
import ray_tpu
from ray_tpu.core.global_state import global_worker
ray_tpu.init(address={session!r}, log_to_driver=False)
mode = {mode!r}

def barrier(name, n):
    # All clients finish booting (python + numpy imports burn whole
    # seconds of the shared core) BEFORE any client starts its timed
    # section — otherwise client A times its work against client B's
    # interpreter startup. The reference's multi-client ray_perf phases
    # get this isolation by aggregating steady-state rates.
    w = global_worker()
    me = w.worker_id.hex().encode()
    w.kv_put(b"perfbar:" + name + b":" + me, b"1", ns="perf")
    deadline = time.monotonic() + 60
    while len(w.kv_keys(b"perfbar:" + name, ns="perf")) < n:
        if time.monotonic() > deadline:
            break
        time.sleep(0.05)

if mode == "tasks":
    @ray_tpu.remote
    def nop():
        return b"ok"
    ray_tpu.get([nop.remote() for _ in range(100)])
    barrier(b"tasks", {clients})
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range({n})])
    print("RESULT", {n} / (time.perf_counter() - t0))
else:
    import numpy as np
    data = np.random.default_rng(0).integers(
        0, 255, size=({mb} << 20,), dtype=np.uint8)
    for _ in range(3):
        ray_tpu.put(data)
    barrier(b"put", {clients})
    t0 = time.perf_counter()
    for _ in range({iters}):
        ray_tpu.put(data)
    print("RESULT", ({mb} * {iters} / 1024.0) / (time.perf_counter() - t0))
ray_tpu.shutdown()
"""


def _run_clients(ray_tpu, mode: str, num_clients: int, **fmt) -> float:
    """Aggregate throughput of N driver processes attached to this
    cluster (reference: multi_client_* phases of ray_perf.py run 4+
    drivers against one cluster)."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from ray_tpu.core.global_state import global_worker
    src = _MULTI_CLIENT_SRC.format(
        repo=repo, session=global_worker().session_dir,
        mode=mode, clients=num_clients, **fmt)
    procs = [subprocess.Popen(
        [sys.executable, "-c", src], stdout=subprocess.PIPE, text=True,
        env={**os.environ, "RAY_TPU_JAX_PLATFORM": "cpu"})
        for _ in range(num_clients)]
    total = 0.0
    for p in procs:
        out, _ = p.communicate(timeout=300)
        if p.returncode != 0:
            raise RuntimeError(f"client failed rc={p.returncode}")
        vals = [ln.split()[1] for ln in out.splitlines()
                if ln.startswith("RESULT ")]
        total += float(vals[-1])
    return total


def bench_multi_client_tasks(ray_tpu, clients=4, n=1500) -> float:
    return _run_clients(ray_tpu, "tasks", clients, n=n, mb=0, iters=0)


def bench_multi_client_put(ray_tpu, clients=4, mb=32, iters=6) -> float:
    return _run_clients(ray_tpu, "put", clients, n=0, mb=mb, iters=iters)


def bench_rllib_env_steps(ray_tpu, iters=3) -> Optional[float]:
    """PPO sampling+training throughput in env-steps/s. Pipeline shape
    follows the reference's Atari tuned example
    (``rllib/tuned_examples/ppo/atari-ppo.yaml:1-35``: 10 workers x 5
    envs, train_batch 5000) with the worker count scaled to this host's
    CPUs and CartPole standing in for ALE (not in the image). The
    reference publishes no steps/s number for it, so vs_baseline is
    null — the JSON records the trend across rounds."""
    try:
        import gymnasium  # noqa: F401
    except ImportError:
        return None
    from ray_tpu.rllib import PPOConfig
    cpus = int(ray_tpu.cluster_resources().get("CPU", 0))
    if cpus < 3:
        # each runner is a 1-CPU actor; with <2 schedulable runners the
        # pipeline shape is meaningless (and actors would never place)
        return None
    n_runners = min(10, cpus - 1)
    config = (PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=n_runners,
                           num_envs_per_env_runner=5)
              .training(train_batch_size=5000, minibatch_size=500,
                        num_epochs=1, lr=3e-4)
              .debugging(seed=0))
    try:
        algo = config.build()
    except RuntimeError as e:
        if "unable to initialize backend" in str(e).lower():
            # jax can't initialize a device in this process (e.g. the
            # TPU tunnel backend is driver-exclusive): skip rather than
            # fail the whole perf suite
            return None
        raise
    try:
        steps0 = algo.train()["num_env_steps_sampled_lifetime"]
        t0 = time.perf_counter()   # first train() warmed jit + workers
        for _ in range(iters):
            steps = algo.train()["num_env_steps_sampled_lifetime"]
        return (steps - steps0) / (time.perf_counter() - t0)
    finally:
        algo.cleanup()


def bench_tasks_sync(ray_tpu, n=200) -> float:
    @ray_tpu.remote
    def nop():
        return b"ok"

    ray_tpu.get(nop.remote())  # warm worker + export
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(nop.remote())
    return n / (time.perf_counter() - t0)


def bench_tasks_async(ray_tpu, n=2000) -> float:
    @ray_tpu.remote
    def nop():
        return b"ok"

    # warm the worker pool + leases to steady state (the reference's
    # ray_perf phases also run against a warm cluster)
    ray_tpu.get([nop.remote() for _ in range(200)])
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n)])
    return n / (time.perf_counter() - t0)


def bench_actor_sync(ray_tpu, n=500) -> float:
    @ray_tpu.remote
    class A:
        def m(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(a.m.remote())
    dt = time.perf_counter() - t0
    ray_tpu.kill(a)
    return n / dt


def bench_actor_async(ray_tpu, n=5000) -> float:
    @ray_tpu.remote
    class A:
        def m(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get([a.m.remote() for _ in range(100)])
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    ray_tpu.kill(a)
    return n / dt


def bench_put(ray_tpu, mb=64, iters=8) -> float:
    """Matches the reference's single_client_put_gigabytes workload
    (ray_perf.py puts numpy arrays; pickle-5 ships them out-of-band)."""
    data = np.random.default_rng(0).integers(
        0, 255, size=(mb << 20,), dtype=np.uint8)
    for _ in range(3):
        ray_tpu.put(data)  # warm: fault pages + settle extent recycling
    t0 = time.perf_counter()
    for _ in range(iters):
        ray_tpu.put(data)
    dt = time.perf_counter() - t0
    return (mb * iters / 1024.0) / dt


def bench_put_bytes(ray_tpu, mb=64, iters=8) -> float:
    data = np.random.default_rng(0).bytes(mb << 20)
    for _ in range(3):
        ray_tpu.put(data)  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        ray_tpu.put(data)
    dt = time.perf_counter() - t0
    return (mb * iters / 1024.0) / dt


def main() -> Dict[str, float]:
    import ray_tpu
    started = False
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4, _num_initial_workers=2)
        started = True
    @ray_tpu.remote
    def _nop():
        return b"ok"

    def settle():
        # Phase isolation (the reference runs each ray_perf phase as its
        # own process): drain our GC churn, then flush every FIFO the
        # previous phase filled — a nop round-trip through the workers
        # pushes their queued TASK_DONE batches ahead of it, and a
        # controller request drains our own submit/ref-delta stream.
        # Without the drain, phase N's backlog steals phase N+1's core.
        import gc
        gc.collect()
        try:
            ray_tpu.get([_nop.remote() for _ in range(4)], timeout=30)
            from ray_tpu.core.global_state import global_worker
            # FIFO flush: this reply can only arrive after the
            # controller processed everything we sent before it
            global_worker().kv_exists(b"__perf_settle__")
        except Exception:
            pass
        time.sleep(1.0)

    # Cluster warmup: worker subprocesses spend seconds importing on a
    # small host; timing anything against that boot burns the phase
    # (the reference's ray_perf also runs against a warm cluster).
    ray_tpu.get([_nop.remote() for _ in range(200)])
    time.sleep(3.0)
    ray_tpu.get([_nop.remote() for _ in range(100)])

    # Single-client phases FIRST (multi-client forks 4 driver processes
    # whose boot/teardown churn would pollute them), each best-of-2:
    # phases are seconds long and this box's effective CPU swings ~2x.
    results = {}
    for name, fn, reps in (
            ("tasks_sync_per_s", bench_tasks_sync, 3),
            ("tasks_async_per_s", bench_tasks_async, 3),
            ("actor_calls_sync_per_s", bench_actor_sync, 3),
            ("actor_calls_async_per_s", bench_actor_async, 2),
            ("put_gib_per_s", bench_put, 3),
            ("put_bytes_gib_per_s", bench_put_bytes, 2),
            ("multi_client_tasks_async_per_s", bench_multi_client_tasks,
             1),
            ("multi_client_put_gib_per_s", bench_multi_client_put, 1),
            ("rllib_env_steps_per_s", bench_rllib_env_steps, 1),
    ):
        best = None
        for _ in range(reps):
            out = fn(ray_tpu)
            if out is None:
                break
            best = out if best is None else max(best, out)
            settle()
        if best is None:
            continue
        results[name] = best
    for name, value in results.items():
        base = BASELINES.get(name)
        print(json.dumps({
            "metric": name, "value": round(value, 1),
            "unit": "GiB/s" if "gib" in name else "1/s",
            "vs_baseline": round(value / base, 3) if base else None,
        }))
    if started:
        ray_tpu.shutdown()
    return results


if __name__ == "__main__":
    main()
