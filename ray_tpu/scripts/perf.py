"""Core microbenchmark suite.

Reference: ``python/ray/_private/ray_perf.py`` (run as ``ray
microbenchmark``) — the numbers in BASELINE.md §"scalability envelope":
sync/async task throughput, actor call throughput, put throughput.
Prints one JSON line per metric with the reference baseline ratio.
"""

from __future__ import annotations

import json
import time
from typing import Dict

import numpy as np

# Reference measured numbers (BASELINE.md, release_logs/2.9.2)
BASELINES = {
    "tasks_sync_per_s": 1046.0,
    "tasks_async_per_s": 8159.0,
    "actor_calls_sync_per_s": 2138.0,
    "actor_calls_async_per_s": 9183.0,
    "put_gib_per_s": 19.5,
}


def bench_tasks_sync(ray_tpu, n=200) -> float:
    @ray_tpu.remote
    def nop():
        return b"ok"

    ray_tpu.get(nop.remote())  # warm worker + export
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(nop.remote())
    return n / (time.perf_counter() - t0)


def bench_tasks_async(ray_tpu, n=2000) -> float:
    @ray_tpu.remote
    def nop():
        return b"ok"

    # warm the worker pool + leases to steady state (the reference's
    # ray_perf phases also run against a warm cluster)
    ray_tpu.get([nop.remote() for _ in range(200)])
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n)])
    return n / (time.perf_counter() - t0)


def bench_actor_sync(ray_tpu, n=500) -> float:
    @ray_tpu.remote
    class A:
        def m(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(a.m.remote())
    dt = time.perf_counter() - t0
    ray_tpu.kill(a)
    return n / dt


def bench_actor_async(ray_tpu, n=5000) -> float:
    @ray_tpu.remote
    class A:
        def m(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get([a.m.remote() for _ in range(100)])
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    ray_tpu.kill(a)
    return n / dt


def bench_put(ray_tpu, mb=64, iters=8) -> float:
    """Matches the reference's single_client_put_gigabytes workload
    (ray_perf.py puts numpy arrays; pickle-5 ships them out-of-band)."""
    data = np.random.default_rng(0).integers(
        0, 255, size=(mb << 20,), dtype=np.uint8)
    ray_tpu.put(data)  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        ray_tpu.put(data)
    dt = time.perf_counter() - t0
    return (mb * iters / 1024.0) / dt


def bench_put_bytes(ray_tpu, mb=64, iters=8) -> float:
    data = np.random.default_rng(0).bytes(mb << 20)
    ray_tpu.put(data)  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        ray_tpu.put(data)
    dt = time.perf_counter() - t0
    return (mb * iters / 1024.0) / dt


def main() -> Dict[str, float]:
    import ray_tpu
    started = False
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4, _num_initial_workers=2)
        started = True
    def settle():
        # let ref-delta GC churn from the previous phase drain so phases
        # are isolated (the reference runs each ray_perf phase separately)
        import gc
        gc.collect()
        time.sleep(1.0)

    results = {}
    for name, fn in (
            ("tasks_sync_per_s", bench_tasks_sync),
            ("tasks_async_per_s", bench_tasks_async),
            ("actor_calls_sync_per_s", bench_actor_sync),
            ("actor_calls_async_per_s", bench_actor_async),
            ("put_gib_per_s", bench_put),
            ("put_bytes_gib_per_s", bench_put_bytes),
    ):
        results[name] = fn(ray_tpu)
        settle()
    for name, value in results.items():
        base = BASELINES.get(name)
        print(json.dumps({
            "metric": name, "value": round(value, 1),
            "unit": "GiB/s" if "gib" in name else "1/s",
            "vs_baseline": round(value / base, 3) if base else None,
        }))
    if started:
        ray_tpu.shutdown()
    return results


if __name__ == "__main__":
    main()
