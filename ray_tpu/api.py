"""Public API: init/shutdown/remote/get/put/wait/....

Equivalent of the reference's ``python/ray/_private/worker.py`` public
surface (``init`` :1219, ``get`` :2561, ``put`` :2679, ``wait`` :2744,
``get_actor`` :2890, ``remote`` :3137) and the bootstrap logic of
``python/ray/_private/node.py`` / ``services.py`` — for the default
single-node ``init()`` the controller and node manager run as threads in
the driver process, workers as subprocesses; multi-node clusters connect
additional node-manager processes to the same controller socket.
"""

from __future__ import annotations

import atexit
import inspect
import json
import logging
import os
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu.core.config import Config, get_config, set_config
from ray_tpu.core.global_state import (
    global_worker, set_global_worker, try_global_worker)
from ray_tpu.core.ids import ActorID, NodeID
from ray_tpu.core.object_ref import ObjectRef

_head = None  # _HeadProcess for the in-process controller+node
_log_monitor = None
_client = None  # ClientWorker when connected via ray:// (client mode)


def _client_or_none():
    return _client if _client is not None and _client.is_connected() \
        else None


class _HeadProcess:
    def __init__(self, session_dir: str, config: Config,
                 resources: Dict[str, float], labels: Dict[str, str],
                 num_initial_workers: int):
        from ray_tpu.core.controller import Controller
        from ray_tpu.core.node import NodeManager
        self.session_dir = session_dir
        self.controller = Controller(session_dir, config)
        self.controller.start()
        self.node = NodeManager(session_dir, resources, labels=labels,
                                num_initial_workers=num_initial_workers,
                                config=config)
        self.node.start()
        self.dashboard = None
        if config.dashboard_enabled:
            try:
                from ray_tpu.dashboard.head import DashboardHead
                self.dashboard = DashboardHead(
                    session_dir, self.controller,
                    port=config.dashboard_port)
            except Exception:
                logging.getLogger(__name__).exception(
                    "dashboard failed to start; continuing without it")

    def stop(self):
        try:
            if self.dashboard is not None:
                self.dashboard.stop()
        except Exception:
            pass
        try:
            self.node.stop()
        finally:
            self.controller.stop()


def init(address: Optional[str] = None,
         *,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         labels: Optional[Dict[str, str]] = None,
         object_store_memory: Optional[int] = None,
         namespace: str = "",
         ignore_reinit_error: bool = False,
         log_to_driver: bool = True,
         _system_config: Optional[Dict[str, Any]] = None,
         _num_initial_workers: Optional[int] = None,
         _session_dir: Optional[str] = None) -> Dict[str, Any]:
    """Start a cluster in-process (or connect to one via ``address``).

    ``address="ray://host:port"`` enters client mode (reference: Ray
    Client, ``python/ray/util/client/worker.py:81``): no local runtime is
    started; the public API proxies to a remote cluster's client server.
    """
    global _head, _client
    if address is None:
        # `ray-tpu submit` / external drivers point here via env var
        # (reference analog: RAY_ADDRESS).
        address = os.environ.get("RAY_TPU_ADDRESS") or None
    if address and address.startswith("ray://"):
        if _client is not None and _client.is_connected():
            if ignore_reinit_error:
                return {}
            raise RuntimeError("ray_tpu.init() called twice "
                               "(use ignore_reinit_error=True)")
        from ray_tpu.util.client import connect as _client_connect
        _client = _client_connect(address)
        atexit.register(_atexit_shutdown)
        return {"client": True, "address": address,
                **{k: v for k, v in _client.server_info.items()
                   if k != "ok"}}
    if try_global_worker() is not None:
        if ignore_reinit_error:
            return {}
        raise RuntimeError("ray_tpu.init() called twice "
                           "(use ignore_reinit_error=True)")
    config = Config()
    if object_store_memory:
        config.object_store_memory = int(object_store_memory)
    config.apply_system_config(_system_config or {})
    set_config(config)

    from ray_tpu.core.node import detect_resources
    from ray_tpu.core.runtime import Runtime

    if address and address != "local":
        session_dir = address
    else:
        session_dir = _session_dir or os.path.join(
            "/tmp/ray_tpu", f"session_{int(time.time())}_{uuid.uuid4().hex[:8]}")
        os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
        config.session_dir = session_dir
        res = detect_resources(num_cpus, num_tpus, resources)
        if _num_initial_workers is None:
            _num_initial_workers = min(int(res.get("CPU", 1)), 4)
        _head = _HeadProcess(session_dir, config, res, labels or {},
                             _num_initial_workers)
        with open(os.path.join(session_dir, "session.json"), "w") as f:
            json.dump({"shm_session": _head.node.shm_session,
                       "node_id": _head.node.node_id.hex()}, f)

    with open(os.path.join(session_dir, "session.json")) as f:
        session_info = json.load(f)
    runtime = Runtime("driver", session_dir,
                      NodeID.from_hex(session_info["node_id"]),
                      shm_session=session_info["shm_session"])
    runtime.namespace = namespace
    set_global_worker(runtime)
    reply = runtime.register()
    global _log_monitor
    if log_to_driver:
        from ray_tpu.core.log_monitor import LogMonitor
        _log_monitor = LogMonitor(session_dir)
        _log_monitor.start()
    atexit.register(_atexit_shutdown)
    return {"session_dir": session_dir, "job_id": runtime.job_id.hex()}


def _atexit_shutdown():
    try:
        shutdown()
    except Exception:
        pass


def shutdown() -> None:
    global _head, _log_monitor, _client
    if _client is not None:
        try:
            _client.disconnect()
        except Exception:
            pass
        _client = None
    if _log_monitor is not None:
        try:
            _log_monitor.stop()
        except Exception:
            pass
        _log_monitor = None
    w = try_global_worker()
    if w is not None:
        try:
            w.shutdown()
        except Exception:
            pass
        set_global_worker(None)
    if _head is not None:
        head, _head = _head, None
        head.stop()


def is_initialized() -> bool:
    return try_global_worker() is not None or _client_or_none() is not None


def remote(*args, **options):
    """``@remote`` decorator for functions and classes (reference:
    ``worker.py:3137``)."""
    c = _client_or_none()
    if c is not None:
        return c.remote(*args, **options)
    from ray_tpu.actor import ActorClass
    from ray_tpu.remote_function import RemoteFunction

    def make(target):
        if inspect.isclass(target):
            return ActorClass(target, **options)
        return RemoteFunction(target, **options)

    if len(args) == 1 and callable(args[0]) and not options:
        return make(args[0])
    if args:
        raise TypeError("@remote takes keyword options only")
    return make


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    # compiled-DAG channel results resolve locally (reference:
    # CompiledDAGRef is accepted by ray.get, scalar or in lists)
    if hasattr(refs, "__dag_local_value__"):
        return refs.__dag_local_value__(timeout)
    if isinstance(refs, (list, tuple)) and any(
            hasattr(r, "__dag_local_value__") for r in refs):
        return [get(r, timeout=timeout) for r in refs]
    c = _client_or_none()
    if c is not None:
        return c.get(refs, timeout=timeout)
    return global_worker().get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    c = _client_or_none()
    if c is not None:
        return c.put(value)
    return global_worker().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    c = _client_or_none()
    if c is not None:
        return c.wait(refs, num_returns=num_returns, timeout=timeout,
                      fetch_local=fetch_local)
    return global_worker().wait(refs, num_returns=num_returns,
                                timeout=timeout, fetch_local=fetch_local)


def kill(actor, *, no_restart: bool = True) -> None:
    c = _client_or_none()
    if c is not None:
        return c.kill(actor, no_restart=no_restart)
    global_worker().kill_actor(actor._id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    c = _client_or_none()
    if c is not None:
        return c.cancel(ref, force=force)
    global_worker().cancel(ref, force=force)


def get_actor(name: str, namespace: str = ""):
    c = _client_or_none()
    if c is not None:
        return c.get_actor(name, namespace=namespace)
    from ray_tpu.actor import ActorHandle
    from ray_tpu.core import protocol as P
    w = global_worker()
    reply = w.request(P.GET_ACTOR, {"name": name, "namespace": namespace})
    return ActorHandle(ActorID(reply["actor_id"]),
                       reply["spec_meta"]["qualname"])


def nodes() -> List[dict]:
    c = _client_or_none()
    if c is not None:
        return c.nodes()
    return global_worker().state_query("nodes")


def cluster_resources() -> Dict[str, float]:
    c = _client_or_none()
    if c is not None:
        return c.cluster_resources()
    return global_worker().state_query("cluster_resources")


def available_resources() -> Dict[str, float]:
    c = _client_or_none()
    if c is not None:
        return c.available_resources()
    return global_worker().state_query("available_resources")


def get_runtime_context():
    from ray_tpu.runtime_context import get_runtime_context as _grc
    return _grc()


def method(**opts):
    from ray_tpu.actor import method as _method
    return _method(**opts)


def timeline(filename: Optional[str] = None):
    """Dump the task timeline as a Chrome trace (reference:
    ``ray timeline`` / GcsTaskManager events)."""
    w = global_worker()
    w.flush_timeline()
    events = w.state_query("timeline")
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
        return filename
    return events
