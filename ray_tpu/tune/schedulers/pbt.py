"""Population Based Training.

Reference: ``python/ray/tune/schedulers/pbt.py`` — every
``perturbation_interval``, bottom-quantile trials EXPLOIT a top-quantile
trial (clone weights via checkpoint + copy config) and EXPLORE (mutate
hyperparams: resample with prob ``resample_probability``, else
perturb ×1.2/×0.8). The controller performs the actual clone via
save/restore on the trial actors.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional, Union

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler
from ray_tpu.tune.search.sample import Domain
from ray_tpu.tune.trainable import TRAINING_ITERATION


def _explore(config: Dict, mutations: Dict, resample_prob: float,
             rng: random.Random) -> Dict:
    new = dict(config)
    for key, spec in mutations.items():
        old = config.get(key)
        if rng.random() < resample_prob or old is None:
            if isinstance(spec, Domain):
                new[key] = spec.sample(rng)
            elif isinstance(spec, list):
                new[key] = rng.choice(spec)
            elif callable(spec):
                new[key] = spec()
        else:
            if isinstance(spec, list):
                # move to a neighboring listed value
                try:
                    i = spec.index(old)
                    j = max(0, min(len(spec) - 1,
                                   i + rng.choice([-1, 1])))
                    new[key] = spec[j]
                except ValueError:
                    new[key] = rng.choice(spec)
            elif isinstance(old, (int, float)):
                factor = rng.choice([0.8, 1.2])
                new[key] = type(old)(old * factor)
    return new


class PopulationBasedTraining(TrialScheduler):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = TRAINING_ITERATION,
                 perturbation_interval: float = 10,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 synch: bool = False,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.synch = synch
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = {}
        self._latest: Dict[str, float] = {}  # trial_id -> score
        # synch mode: trial_id -> score for trials waiting at the
        # current perturbation boundary.
        self._at_boundary: Dict[str, float] = {}
        self._round = 1
        self.perturbation_count = 0

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        t = result.get(self.time_attr)
        score = self._score(result)
        if t is None or score is None:
            return self.CONTINUE
        self._latest[trial.trial_id] = score
        if self.synch:
            return self._synch_step(controller, trial, t, score)
        last = self._last_perturb.get(trial.trial_id, 0.0)
        if t - last < self.interval:
            return self.CONTINUE

        # Exploit sources are any scored trial we can still clone from:
        # live ones (checkpointed on demand) or terminated ones that left
        # a checkpoint behind. Restricting to live trials deadlocks PBT
        # when population members run serially (a fast trial can finish
        # before a slow one produces its first score).
        candidates = {}
        for tid, s in self._latest.items():
            other = controller.get_trial(tid)
            if other is None:
                continue
            if controller.is_live(tid) or other.checkpoint is not None:
                candidates[tid] = s
        if len(candidates) < 2:
            # Population not comparable yet — keep the perturbation slot
            # so the next report retries instead of waiting a full
            # interval.
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t
        live = candidates
        ordered = sorted(live, key=live.get)
        n_q = max(1, int(len(ordered) * self.quantile))
        bottom = set(ordered[:n_q])
        top = ordered[-n_q:]
        if trial.trial_id not in bottom:
            return self.CONTINUE
        source_id = self._rng.choice(
            [tid for tid in top if tid != trial.trial_id] or top)
        source = controller.get_trial(source_id)
        if source is None or source is trial:
            return self.CONTINUE
        new_config = self._make_exploit_config(source.config, t)
        controller.exploit_trial(trial, source, new_config)
        self.perturbation_count += 1
        return self.CONTINUE

    def _make_exploit_config(self, source_config: Dict,
                             t: float) -> Dict:
        """EXPLORE hook: PBT mutates randomly; PB2 overrides with its
        GP-UCB selection (reference: pb2.py explore())."""
        return _explore(source_config, self.mutations,
                        self.resample_prob, self._rng)

    # -- synchronous mode (reference pbt.py `synch=True`) --------------
    # Trials PAUSE at each perturbation boundary (t >= round*interval)
    # until the whole live population has arrived; the last arrival
    # runs the exploit/explore round, everyone resumes together. This
    # makes PBT deterministic under any trial interleaving.
    def _synch_step(self, controller, trial, t: float,
                    score: float) -> str:
        if t < self._round * self.interval:
            return self.CONTINUE
        self._at_boundary[trial.trial_id] = score
        if self._outstanding(controller):
            return self.PAUSE
        self._run_round(controller)
        # The caller resumes via the controller's CONTINUE path; the
        # paused cohort was resumed inside _run_round.
        return self.CONTINUE

    def _outstanding(self, controller) -> bool:
        """Any live trial that has not reached the boundary yet?"""
        for other in controller.trials:
            if other.trial_id in self._at_boundary:
                continue
            if controller.is_live(other.trial_id):
                return True
        return False

    def _run_round(self, controller) -> None:
        cohort = dict(self._at_boundary)
        self._at_boundary.clear()
        self._round += 1
        if len(cohort) >= 2:
            ordered = sorted(cohort, key=cohort.get)
            n_q = max(1, int(len(ordered) * self.quantile))
            bottom = [tid for tid in ordered[:n_q]
                      if controller.is_live(tid)]
            top = ordered[-n_q:]
            for tid in bottom:
                target = controller.get_trial(tid)
                pool = [s for s in top if s != tid]
                if target is None or not pool:
                    continue
                source = controller.get_trial(self._rng.choice(pool))
                if source is None:
                    continue
                new_config = self._make_exploit_config(
                    source.config, self._round * self.interval)
                controller.exploit_trial(target, source, new_config)
                self.perturbation_count += 1
        for tid in cohort:
            other = controller.get_trial(tid)
            if other is not None:
                controller.unpause_trial(other)

    def on_trial_complete(self, controller, trial, result: Dict) -> None:
        if not self.synch:
            return
        # A finished trial can no longer block the boundary; if it was
        # the straggler, run the round now so the paused cohort resumes.
        self._at_boundary.pop(trial.trial_id, None)
        if self._at_boundary and not self._outstanding(controller):
            self._run_round(controller)
