"""Population Based Training.

Reference: ``python/ray/tune/schedulers/pbt.py`` — every
``perturbation_interval``, bottom-quantile trials EXPLOIT a top-quantile
trial (clone weights via checkpoint + copy config) and EXPLORE (mutate
hyperparams: resample with prob ``resample_probability``, else
perturb ×1.2/×0.8). The controller performs the actual clone via
save/restore on the trial actors.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional, Union

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler
from ray_tpu.tune.search.sample import Domain
from ray_tpu.tune.trainable import TRAINING_ITERATION


def _explore(config: Dict, mutations: Dict, resample_prob: float,
             rng: random.Random) -> Dict:
    new = dict(config)
    for key, spec in mutations.items():
        old = config.get(key)
        if rng.random() < resample_prob or old is None:
            if isinstance(spec, Domain):
                new[key] = spec.sample(rng)
            elif isinstance(spec, list):
                new[key] = rng.choice(spec)
            elif callable(spec):
                new[key] = spec()
        else:
            if isinstance(spec, list):
                # move to a neighboring listed value
                try:
                    i = spec.index(old)
                    j = max(0, min(len(spec) - 1,
                                   i + rng.choice([-1, 1])))
                    new[key] = spec[j]
                except ValueError:
                    new[key] = rng.choice(spec)
            elif isinstance(old, (int, float)):
                factor = rng.choice([0.8, 1.2])
                new[key] = type(old)(old * factor)
    return new


class PopulationBasedTraining(TrialScheduler):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = TRAINING_ITERATION,
                 perturbation_interval: float = 10,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = {}
        self._latest: Dict[str, float] = {}  # trial_id -> score
        self.perturbation_count = 0

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        t = result.get(self.time_attr)
        score = self._score(result)
        if t is None or score is None:
            return self.CONTINUE
        self._latest[trial.trial_id] = score
        last = self._last_perturb.get(trial.trial_id, 0.0)
        if t - last < self.interval:
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t

        live = {tid: s for tid, s in self._latest.items()
                if controller.is_live(tid)}
        if len(live) < 2:
            return self.CONTINUE
        ordered = sorted(live, key=live.get)
        n_q = max(1, int(len(ordered) * self.quantile))
        bottom = set(ordered[:n_q])
        top = ordered[-n_q:]
        if trial.trial_id not in bottom:
            return self.CONTINUE
        source_id = self._rng.choice(
            [tid for tid in top if tid != trial.trial_id] or top)
        source = controller.get_trial(source_id)
        if source is None or source is trial:
            return self.CONTINUE
        new_config = _explore(source.config, self.mutations,
                              self.resample_prob, self._rng)
        controller.exploit_trial(trial, source, new_config)
        self.perturbation_count += 1
        return self.CONTINUE
