"""BOHB: Bayesian Optimization + HyperBand.

Reference: ``python/ray/tune/schedulers/hb_bohb.py`` (HyperBandForBOHB
— HyperBand bracketing whose next-trial configs come from the paired
model-based searcher) and ``python/ray/tune/search/bohb/`` (TuneBOHB —
ConfigSpace KDE model). The reference depends on the external ``hpbandster``
package; here the BOHB model itself (per-dimension KDE split into
good/bad sets, sample from good, rank by good/bad density ratio —
Falkner et al. 2018, Algorithm 2) is implemented directly, so no
dependency. The scheduler side reuses the ASHA rung machinery: BOHB's
asynchronous variant (the reference docs recommend it at scale).
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.tune.schedulers.async_hyperband import (
    AsyncHyperBandScheduler)
from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


class HyperBandForBOHB(AsyncHyperBandScheduler):
    """HyperBand bracketing that feeds rung results back into a paired
    TuneBOHB searcher so its KDE model trains on partial-budget scores
    (reference: hb_bohb.py links scheduler rungs to searcher budgets)."""

    def __init__(self, searcher: Optional["TuneBOHB"] = None, **kwargs):
        super().__init__(**kwargs)
        self._searcher = searcher

    def link_searcher(self, searcher: "TuneBOHB") -> None:
        self._searcher = searcher

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        decision = super().on_trial_result(controller, trial, result)
        if self._searcher is not None:
            score = self._score(result)
            t = result.get(self.time_attr)
            if score is not None and t is not None:
                self._searcher.observe(trial.config, float(t), score)
        return decision


class TuneBOHB(Searcher):
    """Model-based suggestions: TPE/KDE over the search space.

    After ``min_points`` observations at the largest budget with data,
    splits them into good/bad by ``top_fraction``, fits per-dimension
    kernel densities, samples candidates from the good KDE and keeps
    the best good/bad likelihood ratio. Before that: random sampling.
    """

    def __init__(self, space: Dict[str, Domain],
                 metric: Optional[str] = None,
                 mode: str = "max",
                 min_points: int = 8,
                 top_fraction: float = 0.25,
                 num_candidates: int = 64,
                 random_fraction: float = 0.2,
                 bandwidth: float = 0.15,
                 seed: Optional[int] = None):
        super().__init__(metric=metric, mode=mode)
        self.space = dict(space)
        self.min_points = min_points
        self.top_fraction = top_fraction
        self.num_candidates = num_candidates
        self.random_fraction = random_fraction
        self.bw = bandwidth
        self._rng = random.Random(seed)
        self._np = np.random.default_rng(seed)
        #: budget -> list of (encoded config, score); the model trains
        #: on the LARGEST budget with >= min_points (BOHB Algorithm 2)
        self._data: Dict[float, List] = {}

    # ------------------------------------------------------- encoding
    def _encode_val(self, key: str, v) -> float:
        d = self.space[key]
        if isinstance(d, Categorical):
            return d.categories.index(v) / max(1, len(d.categories) - 1)
        lo, hi = float(d.lower), float(d.upper)
        if getattr(d, "log", False):
            return (math.log(float(v)) - math.log(lo)) / \
                (math.log(hi) - math.log(lo))
        return (float(v) - lo) / (hi - lo)

    def _encode(self, config: Dict) -> np.ndarray:
        return np.asarray([
            self._encode_val(k, config[k])
            for k in self.space if k in config])

    # ------------------------------------------------------ observing
    def observe(self, config: Dict, budget: float, score: float) -> None:
        if not all(k in config for k in self.space):
            return
        self._data.setdefault(budget, []).append(
            (self._encode(config), score))

    # ----------------------------------------------------- suggesting
    def _kde_logpdf(self, pts: np.ndarray, x: np.ndarray) -> float:
        # product of per-dimension gaussian KDEs (BOHB's factorized KDE)
        d2 = (pts - x[None, :]) ** 2
        per_dim = np.exp(-0.5 * d2 / self.bw ** 2).mean(0) + 1e-12
        return float(np.log(per_dim).sum())

    def suggest(self, trial_id: str) -> Optional[Dict]:
        budgets = sorted(
            (b for b, rows in self._data.items()
             if len(rows) >= self.min_points), reverse=True)
        if not budgets or self._rng.random() < self.random_fraction:
            return {k: d.sample(self._rng)
                    for k, d in self.space.items()}
        rows = self._data[budgets[0]]
        rows_sorted = sorted(rows, key=lambda r: r[1], reverse=True)
        n_good = max(2, int(len(rows_sorted) * self.top_fraction))
        good = np.stack([r[0] for r in rows_sorted[:n_good]])
        bad = np.stack([r[0] for r in rows_sorted[n_good:]]) \
            if len(rows_sorted) > n_good else None

        best_x, best_ratio = None, -math.inf
        for _ in range(self.num_candidates):
            # sample around a random good point (KDE sampling)
            center = good[self._np.integers(len(good))]
            x = np.clip(center + self._np.normal(
                0, self.bw, size=center.shape), 0.0, 1.0)
            ratio = self._kde_logpdf(good, x) - (
                self._kde_logpdf(bad, x) if bad is not None else 0.0)
            if ratio > best_ratio:
                best_x, best_ratio = x, ratio
        return self._decode(best_x)

    def _decode(self, x: np.ndarray) -> Dict:
        cfg = {}
        for i, (k, d) in enumerate(self.space.items()):
            u = float(np.clip(x[i], 0.0, 1.0))
            if isinstance(d, Categorical):
                cfg[k] = d.categories[
                    int(round(u * (len(d.categories) - 1)))]
                continue
            lo, hi = float(d.lower), float(d.upper)
            if getattr(d, "log", False):
                v = math.exp(math.log(lo)
                             + u * (math.log(hi) - math.log(lo)))
            else:
                v = lo + u * (hi - lo)
            if isinstance(d, Integer):
                v = int(round(v))
            cfg[k] = v
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[Dict]
                          = None, error: bool = False) -> None:
        pass
