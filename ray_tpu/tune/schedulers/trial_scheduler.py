"""TrialScheduler interface + FIFO.

Reference: ``python/ray/tune/schedulers/trial_scheduler.py`` —
``on_trial_result`` returns CONTINUE/PAUSE/STOP; the controller enacts
the decision.
"""

from __future__ import annotations

from typing import Dict, Optional


class TrialScheduler:
    CONTINUE = "CONTINUE"
    PAUSE = "PAUSE"
    STOP = "STOP"
    #: the scheduler already enacted its own lifecycle change (e.g. a
    #: resource reallocation restarted the actor): the controller must
    #: take no further action on this result
    NOOP = "NOOP"

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def on_trial_add(self, controller, trial) -> None:
        pass

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        return self.CONTINUE

    def on_trial_complete(self, controller, trial, result: Dict) -> None:
        pass

    def on_trial_error(self, controller, trial) -> None:
        pass

    def _score(self, result: Dict) -> Optional[float]:
        if self.metric is None or self.metric not in result:
            return None
        v = float(result[self.metric])
        return v if self.mode != "min" else -v


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (reference default)."""
