from ray_tpu.tune.schedulers.trial_scheduler import (
    FIFOScheduler, TrialScheduler)
from ray_tpu.tune.schedulers.async_hyperband import (
    ASHAScheduler, AsyncHyperBandScheduler)
from ray_tpu.tune.schedulers.bohb import HyperBandForBOHB, TuneBOHB
from ray_tpu.tune.schedulers.hyperband import HyperBandScheduler
from ray_tpu.tune.schedulers.median_stopping import MedianStoppingRule
from ray_tpu.tune.schedulers.pb2 import PB2
from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining
from ray_tpu.tune.schedulers.resource_changing import (
    DistributeResources, ResourceChangingScheduler)

__all__ = [
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "DistributeResources",
    "FIFOScheduler",
    "HyperBandForBOHB",
    "HyperBandScheduler",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "ResourceChangingScheduler",
    "TrialScheduler",
    "TuneBOHB",
]
