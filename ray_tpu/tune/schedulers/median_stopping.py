"""Median stopping rule.

Reference: ``python/ray/tune/schedulers/median_stopping_rule.py`` — stop
a trial at time t if its best result so far is worse than the median of
other trials' running averages up to t.
"""

from __future__ import annotations

import statistics
from typing import Dict, List, Optional

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler
from ray_tpu.tune.trainable import TRAINING_ITERATION


class MedianStoppingRule(TrialScheduler):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = TRAINING_ITERATION,
                 grace_period: float = 5, min_samples_required: int = 3,
                 hard_stop: bool = True):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.hard_stop = hard_stop
        # trial_id -> list of (t, score)
        self._history: Dict[str, List[tuple]] = {}

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        t = result.get(self.time_attr)
        score = self._score(result)
        if t is None or score is None:
            return self.CONTINUE
        self._history.setdefault(trial.trial_id, []).append((t, score))
        if t < self.grace_period:
            return self.CONTINUE
        medians = []
        for other_id, hist in self._history.items():
            if other_id == trial.trial_id:
                continue
            upto = [s for (tt, s) in hist if tt <= t]
            if upto:
                medians.append(sum(upto) / len(upto))
        if len(medians) < self.min_samples:
            return self.CONTINUE
        best = max(s for (_, s) in self._history[trial.trial_id])
        if best < statistics.median(medians):
            return self.STOP if self.hard_stop else self.PAUSE
        return self.CONTINUE
