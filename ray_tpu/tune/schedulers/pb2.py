"""PB2: Population Based Bandits.

Reference: ``python/ray/tune/schedulers/pb2.py`` — PBT's
exploit/explore loop, but EXPLORE selects new hyperparameters with a
Gaussian-process bandit (GP-UCB) fit on the population's observed
(time, config) → reward-change data, instead of PBT's random
×0.8/×1.2 perturbation. Sample-efficient for small populations. The
reference uses GPy; here the GP (RBF kernel, fixed noise, UCB
acquisition over random candidates) is ~60 lines of numpy — same
algorithm, no dependency.
"""

from __future__ import annotations

import math

from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining
from ray_tpu.tune.trainable import TRAINING_ITERATION


class _GP:
    """Minimal RBF-kernel GP regression (zero mean, fixed noise)."""

    def __init__(self, lengthscale: float = 0.3, noise: float = 1e-3):
        self.ls = lengthscale
        self.noise = noise
        self._X: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._L: Optional[np.ndarray] = None

    def _k(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / self.ls ** 2)

    def fit(self, X: np.ndarray, y: np.ndarray) -> None:
        self._X = X
        K = self._k(X, X) + self.noise * np.eye(len(X))
        self._L = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, y))

    def predict(self, Xs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        Ks = self._k(Xs, self._X)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-9, None)
        return mu, np.sqrt(var)


class PB2(PopulationBasedTraining):
    """PBT with GP-UCB explore. ``hyperparam_bounds`` maps each tuned
    key to ``[low, high]`` (continuous; log-scaled when both bounds are
    positive and span >=2 decades, matching the reference's guidance to
    pass log-spaced bounds)."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = TRAINING_ITERATION,
                 perturbation_interval: float = 10,
                 hyperparam_bounds: Optional[Dict[str, List[float]]] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 2.0,
                 num_candidates: int = 256,
                 seed: Optional[int] = None):
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds")
        super().__init__(
            metric=metric, mode=mode, time_attr=time_attr,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations={}, quantile_fraction=quantile_fraction,
            seed=seed)
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self._log_keys = {
            k for k, (lo, hi) in self.bounds.items()
            if lo > 0 and hi / lo >= 100}
        self.kappa = ucb_kappa
        self.num_candidates = num_candidates
        self._np_rng = np.random.default_rng(seed)
        #: observations: (t, config-vector, score) per report; reward
        #: CHANGE between consecutive reports of one trial is the GP
        #: target (the reference models score deltas, pb2_utils.py)
        self._obs: List[Tuple[float, np.ndarray, float]] = []
        self._prev_score: Dict[str, float] = {}
        self._t_max = 1.0

    # -- encoding ------------------------------------------------------
    def _encode(self, t: float, config: Dict) -> np.ndarray:
        out = [t / max(1.0, self._t_max)]
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            if k in self._log_keys:
                out.append((math.log(v) - math.log(lo))
                           / (math.log(hi) - math.log(lo)))
            else:
                out.append((v - lo) / (hi - lo))
        return np.clip(np.asarray(out), 0.0, 1.0)

    def _decode_candidate(self, x: np.ndarray) -> Dict:
        cfg = {}
        for i, (k, (lo, hi)) in enumerate(self.bounds.items()):
            u = float(np.clip(x[i], 0.0, 1.0))
            if k in self._log_keys:
                cfg[k] = math.exp(math.log(lo)
                                  + u * (math.log(hi) - math.log(lo)))
            else:
                cfg[k] = lo + u * (hi - lo)
        return cfg

    # -- data collection ----------------------------------------------
    def on_trial_result(self, controller, trial, result: Dict) -> str:
        t = result.get(self.time_attr)
        score = self._score(result)
        if t is not None and score is not None:
            self._t_max = max(self._t_max, float(t))
            prev = self._prev_score.get(trial.trial_id)
            self._prev_score[trial.trial_id] = score
            if prev is not None:
                self._obs.append((float(t),
                                  self._encode(t, trial.config),
                                  score - prev))
                del self._obs[:-512]
        return super().on_trial_result(controller, trial, result)

    # -- explore: GP-UCB over candidates ------------------------------
    def _gp_explore(self, base_config: Dict, t: float) -> Dict:
        new = dict(base_config)
        if len(self._obs) < 4:
            # cold start: uniform sample inside bounds (reference
            # behavior before the GP has data)
            x = self._np_rng.uniform(size=len(self.bounds))
            new.update(self._decode_candidate(x))
            return new
        X = np.stack([np.concatenate(([o[0] / max(1.0, self._t_max)],
                                      o[1][1:]))
                      for o in self._obs])
        y = np.asarray([o[2] for o in self._obs])
        y_std = y.std() or 1.0
        gp = _GP()
        gp.fit(X, (y - y.mean()) / y_std)
        cand = self._np_rng.uniform(
            size=(self.num_candidates, len(self.bounds)))
        t_col = np.full((self.num_candidates, 1),
                        t / max(1.0, self._t_max))
        mu, sd = gp.predict(np.hstack([t_col, cand]))
        best = cand[int(np.argmax(mu + self.kappa * sd))]
        new.update(self._decode_candidate(best))
        return new

    def _make_exploit_config(self, source_config: Dict,
                             t: float) -> Dict:
        return self._gp_explore(source_config, t)
