"""ResourceChangingScheduler: grow trial resources as the population
thins out.

Reference: ``python/ray/tune/schedulers/resource_changing_scheduler.py``
— wraps a base scheduler; after each result, a ``resources_allocation_
function`` may return new per-trial resources, and the trial is paused
so the controller restarts its actor with the new allocation (restore
from checkpoint). ``DistributeResources`` is the reference's built-in
policy: split the cluster's CPU/TPU budget evenly over live trials,
growing survivors as ASHA/PBT kill the rest.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ray_tpu.tune.schedulers.trial_scheduler import (
    FIFOScheduler, TrialScheduler)


class DistributeResources:
    """Even split of the total budget over live trials (reference:
    ``DistributeResources`` in resource_changing_scheduler.py)."""

    def __init__(self, total_cpus: Optional[float] = None,
                 total_tpus: Optional[float] = None):
        self.total_cpus = total_cpus
        self.total_tpus = total_tpus

    def __call__(self, controller, trial) -> Optional[Dict[str, float]]:
        live = [t for t in controller.trials
                if controller.is_live(t.trial_id)]
        n = max(1, len(live))
        if self.total_cpus is None:
            try:
                import ray_tpu
                self.total_cpus = ray_tpu.cluster_resources().get(
                    "CPU", 1.0)
                self.total_tpus = self.total_tpus or \
                    ray_tpu.cluster_resources().get("TPU", 0.0)
            except Exception:
                return None
        out = {"CPU": max(1.0, self.total_cpus // n)}
        if self.total_tpus:
            out["TPU"] = self.total_tpus // n
        return out


class ResourceChangingScheduler(TrialScheduler):
    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function: Optional[
                     Callable] = None):
        base = base_scheduler or FIFOScheduler()
        super().__init__(base.metric, base.mode)
        self.base = base
        self.alloc = resources_allocation_function or \
            DistributeResources()
        #: trial_id -> last allocation we applied (avoid churn)
        self._current: Dict[str, Dict[str, float]] = {}
        self.reallocation_count = 0

    def set_search_properties(self, metric, mode) -> bool:
        super().set_search_properties(metric, mode)
        return self.base.set_search_properties(metric, mode)

    def on_trial_add(self, controller, trial) -> None:
        self.base.on_trial_add(controller, trial)

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        decision = self.base.on_trial_result(controller, trial, result)
        if decision != self.CONTINUE:
            return decision
        want = None
        try:
            want = self.alloc(controller, trial)
        except Exception:
            pass
        if not want:
            return decision
        have = self._current.get(trial.trial_id) \
            or dict(getattr(trial, "resources", None) or {"CPU": 1.0})
        if any(want.get(k, 0) != have.get(k, 0) for k in want):
            # the controller checkpoints, stops the actor, and restarts
            # it under the new allocation (reference: trial is paused
            # with new placement-group factory, then unpaused). Record
            # the allocation only on success so a declined reallocation
            # (no checkpoint yet) retries on the next result.
            if controller.reallocate_trial(trial, want):
                self._current[trial.trial_id] = dict(want)
                self.reallocation_count += 1
                return self.NOOP
        return decision

    def on_trial_complete(self, controller, trial, result: Dict) -> None:
        self._current.pop(trial.trial_id, None)
        self.base.on_trial_complete(controller, trial, result)

    def on_trial_error(self, controller, trial) -> None:
        self._current.pop(trial.trial_id, None)
        self.base.on_trial_error(controller, trial)
