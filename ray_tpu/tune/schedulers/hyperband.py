"""HyperBand (bracketed successive halving).

Reference: ``python/ray/tune/schedulers/hyperband.py``. This build
implements the multi-bracket *asynchronous* formulation (the reference
docs themselves recommend ASHA over synchronous HyperBand because
stragglers stall whole bands); brackets differ in their grace period,
matching HyperBand's exploration/exploitation spread without PAUSE
barriers.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.tune.schedulers.async_hyperband import AsyncHyperBandScheduler
from ray_tpu.tune.trainable import TRAINING_ITERATION


class HyperBandScheduler(AsyncHyperBandScheduler):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = TRAINING_ITERATION,
                 max_t: float = 81, reduction_factor: float = 3):
        super().__init__(
            metric=metric, mode=mode, time_attr=time_attr, max_t=max_t,
            grace_period=1, reduction_factor=reduction_factor,
            brackets=3)
