"""ASHA: asynchronous successive halving.

Reference: ``python/ray/tune/schedulers/async_hyperband.py`` — rungs at
``grace_period * reduction_factor**k``; a trial reaching a rung is
stopped unless its metric is in the top ``1/reduction_factor`` quantile
of everything recorded at that rung.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler
from ray_tpu.tune.trainable import TRAINING_ITERATION


class _Bracket:
    def __init__(self, min_t: float, max_t: float, rf: float, s: int):
        self.rf = rf
        # rung milestones, ascending
        self.rungs: List[tuple] = []
        t = min_t * rf ** s
        milestones = []
        while t < max_t:
            milestones.append(t)
            t *= rf
        self.rungs = [(m, {}) for m in sorted(milestones)]

    def on_result(self, trial_id: str, cur_iter: float,
                  score: Optional[float]) -> str:
        decision = TrialScheduler.CONTINUE
        for milestone, recorded in self.rungs:
            if cur_iter < milestone or trial_id in recorded:
                continue
            if score is None:
                recorded[trial_id] = None
                continue
            others = [v for v in recorded.values() if v is not None]
            recorded[trial_id] = score
            if others:
                others_sorted = sorted(others)
                k = int(len(others_sorted) * (1 - 1 / self.rf))
                cutoff = others_sorted[min(k, len(others_sorted) - 1)]
                if score < cutoff:
                    decision = TrialScheduler.STOP
        return decision


class AsyncHyperBandScheduler(TrialScheduler):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = TRAINING_ITERATION,
                 max_t: float = 100, grace_period: float = 1,
                 reduction_factor: float = 4, brackets: int = 1):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self._brackets = [
            _Bracket(grace_period, max_t, reduction_factor, s)
            for s in range(brackets)]
        self._trial_bracket: Dict[str, _Bracket] = {}
        self._counter = 0

    def on_trial_add(self, controller, trial) -> None:
        b = self._brackets[self._counter % len(self._brackets)]
        self._counter += 1
        self._trial_bracket[trial.trial_id] = b

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        cur = result.get(self.time_attr)
        if cur is None:
            return self.CONTINUE
        if cur >= self.max_t:
            return self.STOP
        b = self._trial_bracket.get(trial.trial_id)
        if b is None:
            self.on_trial_add(controller, trial)
            b = self._trial_bracket[trial.trial_id]
        return b.on_result(trial.trial_id, cur, self._score(result))


ASHAScheduler = AsyncHyperBandScheduler
