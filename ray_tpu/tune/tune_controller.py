"""TuneController: the trial-driving event loop.

Reference: ``python/ray/tune/execution/tune_controller.py:72`` (``step``
:718) — maintain a population of trial actors, drain their results,
consult searcher + scheduler, enact CONTINUE/STOP decisions, checkpoint,
and restart failed trials. One trial = one ``_TrialActor`` wrapping the
user Trainable; resources come from ``default_resource_request``
(placement-group factory) or a flat CPU bundle.
"""

from __future__ import annotations

import os
import pickle
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, ActorError, TaskError
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.storage import StorageContext
from ray_tpu.tune import _trial_context
from ray_tpu.tune.experiment import (
    ERROR, PAUSED, PENDING, RUNNING, TERMINATED, Trial)
from ray_tpu.tune.placement_groups import PlacementGroupFactory
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.trainable import (
    DONE, TRAINING_ITERATION, TRIAL_ID, FunctionTrainable, Trainable)


class _TrialActor:
    """The actor hosting one trial's Trainable instance."""

    def __init__(self, trainable_cls, config, pg=None, trial_dir=None):
        if pg is not None:
            _trial_context.set_trial_placement_group(pg)
        if trial_dir:
            _trial_context.set_trial_dir(trial_dir)
        self._t = trainable_cls(config)

    def train(self):
        return self._t.train()

    def save(self, checkpoint_dir=None):
        return self._t.save(checkpoint_dir)

    def restore(self, checkpoint):
        self._t.restore(checkpoint)

    def reset(self, new_config):
        return self._t.reset(new_config)

    def stop(self):
        self._t.stop()


def _as_trainable_cls(trainable) -> type:
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        return trainable
    if callable(trainable):
        wrapped = FunctionTrainable.wrap(trainable)
        if hasattr(trainable, "default_resource_request"):
            wrapped.default_resource_request = (
                trainable.default_resource_request)
        return wrapped
    raise TypeError(f"not a trainable: {trainable!r}")


class TuneController:
    def __init__(self, trainable, param_space: Dict,
                 searcher: Optional[Searcher],
                 scheduler: Optional[TrialScheduler],
                 storage: StorageContext,
                 metric: Optional[str], mode: Optional[str],
                 num_samples: int = 1,
                 max_concurrent_trials: Optional[int] = None,
                 stop: Optional[Dict[str, float]] = None,
                 max_failures: int = 0,
                 checkpoint_frequency: int = 0,
                 checkpoint_at_end: bool = True,
                 callbacks: Optional[list] = None):
        self.trainable_cls = _as_trainable_cls(trainable)
        self.param_space = param_space or {}
        self.searcher = searcher or BasicVariantGenerator()
        self.scheduler = scheduler or FIFOScheduler()
        self.storage = storage
        self.metric = metric
        self.mode = mode or "max"
        self.num_samples = num_samples
        self.max_concurrent = max_concurrent_trials or 0
        self.stop_criteria = stop or {}
        self.max_failures = max_failures
        self.checkpoint_frequency = checkpoint_frequency
        self.checkpoint_at_end = checkpoint_at_end
        from ray_tpu.tune.callback import CallbackList
        self.callbacks = CallbackList(callbacks)
        self.callbacks.fire("setup", stop=stop, num_samples=num_samples)
        self._cb_iteration = 0

        self.searcher.set_search_properties(
            metric, self.mode, self.param_space, num_samples=num_samples)
        self.scheduler.set_search_properties(metric, self.mode)

        self.trials: List[Trial] = []
        self._futures: Dict[Any, Trial] = {}
        self._failures: Dict[str, int] = {}
        self._searcher_done = False
        self._trial_counter = 0

    # -- trial bookkeeping -------------------------------------------
    def get_trial(self, trial_id: str) -> Optional[Trial]:
        for t in self.trials:
            if t.trial_id == trial_id:
                return t
        return None

    def is_live(self, trial_id: str) -> bool:
        t = self.get_trial(trial_id)
        return t is not None and t.status in (RUNNING, PAUSED)

    def _trial_limit(self) -> int:
        """Total trials to create: the searcher's own count if it knows
        it (grid x num_samples for BasicVariant), else num_samples —
        bounding never-exhausting searchers like TPE."""
        total = getattr(self.searcher, "total_samples", None)
        return total if total else self.num_samples

    def _next_trial(self) -> Optional[Trial]:
        if self._searcher_done or self._trial_counter >= self._trial_limit():
            return None
        trial_id = f"{self._trial_counter:05d}"
        config = self.searcher.suggest(trial_id)
        if config is None:
            # Permanent exhaustion vs. "ask again later" (e.g. a
            # ConcurrencyLimiter at capacity).
            if self.searcher.is_finished():
                self._searcher_done = True
            return None
        self._trial_counter += 1
        trial = Trial(trial_id, config, self.storage.experiment_name)
        self.trials.append(trial)
        self.scheduler.on_trial_add(self, trial)
        return trial

    def _resource_request(self, config) -> Optional[PlacementGroupFactory]:
        req = getattr(self.trainable_cls, "default_resource_request", None)
        if req is None:
            return None
        factory = req(config)
        return factory if isinstance(factory, PlacementGroupFactory) \
            else None

    def _create_actor(self, trial: Trial, config: Dict, pg):
        """Build the trial's actor, honoring the resource request. With
        an empty head bundle the group holds only worker bundles and the
        trial actor runs outside it (reference tuner semantics)."""
        factory = self._resource_request(config)
        opts: Dict[str, Any] = {"num_cpus": 1.0}
        override = getattr(trial, "resource_override", None)
        if override:
            # ResourceChangingScheduler reallocation (reference:
            # resource_changing_scheduler.py swaps the trial's
            # PlacementGroupFactory): the override wins over the
            # trainable's static request
            opts["num_cpus"] = float(override.get("CPU", 1.0))
            if override.get("TPU"):
                opts["num_tpus"] = float(override["TPU"])
            actor_cls = ray_tpu.remote(**opts)(_TrialActor)
            return actor_cls.remote(
                self.trainable_cls, config, pg,
                self._trial_storage(trial).trial_dir)
        if factory is not None and pg is not None \
                and not factory.head_bundle_is_empty:
            head = factory.bundles[0]
            opts["num_cpus"] = float(head.get("CPU", 0.0))
            if "TPU" in head:
                opts["num_tpus"] = float(head["TPU"])
            from ray_tpu.util.scheduling_strategies import (
                PlacementGroupSchedulingStrategy)
            opts["scheduling_strategy"] = (
                PlacementGroupSchedulingStrategy(
                    pg, placement_group_bundle_index=0))
        actor_cls = ray_tpu.remote(**opts)(_TrialActor)
        return actor_cls.remote(
            self.trainable_cls, config, pg,
            self._trial_storage(trial).trial_dir)

    def _start_trial(self, trial: Trial) -> None:
        # a reallocation override replaces the trainable's static
        # request wholesale — reserving the factory's placement group
        # AND the override's CPUs would double-book the cluster
        factory = None if getattr(trial, "resource_override", None) \
            else self._resource_request(trial.config)
        pg = factory() if factory is not None else None
        trial.local_dir = self._trial_storage(trial).trial_dir
        first_start = trial.actor is None and trial.status == PENDING \
            and not getattr(trial, "_started_once", False)
        trial.actor = self._create_actor(trial, trial.config, pg)
        trial._pg = pg
        trial.status = RUNNING
        if first_start:
            trial._started_once = True
            self.callbacks.fire("on_trial_start", self._cb_iteration,
                                self.trials, trial)
        if trial.restore_pending is not None:
            trial.actor.restore.remote(trial.restore_pending)
            trial.restore_pending = None
        self._submit_train(trial)

    def _submit_train(self, trial: Trial) -> None:
        fut = trial.actor.train.remote()
        self._futures[fut] = trial

    def _trial_storage(self, trial: Trial) -> StorageContext:
        s = StorageContext(self.storage.storage_path,
                           self.storage.experiment_name,
                           trial_dir_name=f"trial_{trial.trial_id}")
        s.current_checkpoint_index = trial.iteration
        return s

    def _save_trial_checkpoint(self, trial: Trial) -> Optional[Checkpoint]:
        if trial.actor is None:
            return trial.checkpoint
        s = self._trial_storage(trial)
        dest = s.checkpoint_dir(trial.iteration)
        try:
            ckpt = ray_tpu.get(trial.actor.save.remote(dest))
        except (TaskError, ActorError, ActorDiedError):
            return trial.checkpoint
        if ckpt is not None:
            trial.checkpoint = ckpt
            self.callbacks.fire("on_checkpoint", self._cb_iteration,
                                self.trials, trial, ckpt)
        return trial.checkpoint

    def _release_trial_resources(self, trial: Trial) -> None:
        if trial.actor is not None:
            try:
                trial.actor.stop.remote()
            except Exception:
                pass
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        pg = getattr(trial, "_pg", None)
        if pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group
            try:
                remove_placement_group(pg)
            except Exception:
                pass
            trial._pg = None

    def _stop_trial(self, trial: Trial, status: str,
                    error: Optional[BaseException] = None) -> None:
        trial.status = status
        trial.error = error
        if trial.actor is not None and status == TERMINATED \
                and self.checkpoint_at_end:
            self._save_trial_checkpoint(trial)
        self._release_trial_resources(trial)
        self.searcher.on_trial_complete(
            trial.trial_id, result=trial.last_result,
            error=status == ERROR)
        self.scheduler.on_trial_complete(self, trial, trial.last_result)
        self.callbacks.fire(
            "on_trial_error" if status == ERROR else "on_trial_complete",
            self._cb_iteration, self.trials, trial)
        self._snapshot()

    # -- ResourceChangingScheduler hook -------------------------------
    def reallocate_trial(self, trial: Trial,
                         resources: Dict[str, float]) -> bool:
        """Restart the trial's actor under a new resource allocation,
        restoring from a fresh checkpoint (reference:
        resource_changing_scheduler.py pauses the trial with a new
        PlacementGroupFactory). Returns True when the restart cycle was
        performed — the scheduler then returns NOOP so the normal
        decision path doesn't double-submit."""
        if trial.actor is None:
            trial.resource_override = dict(resources)
            return False
        if self._save_trial_checkpoint(trial) is None:
            return False
        trial.resource_override = dict(resources)
        trial.restore_pending = trial.checkpoint
        self._release_trial_resources(trial)
        trial.status = PENDING
        self._start_trial(trial)
        return True

    # -- PBT hook -----------------------------------------------------
    def exploit_trial(self, target: Trial, source: Trial,
                      new_config: Dict) -> None:
        """Clone source's state into target with a mutated config."""
        src_ckpt = self._save_trial_checkpoint(source)
        if src_ckpt is None:
            return
        if target.actor is None:
            # Paused target (synch PBT rounds run while the cohort is
            # parked): stage config + checkpoint; _start_trial applies
            # both when the trial resumes. The exploit checkpoint also
            # becomes the trial's own latest checkpoint — otherwise a
            # post-resume failure-retry would restore pre-exploit
            # weights under the post-exploit config.
            target.config = new_config
            target.checkpoint = src_ckpt
            target.restore_pending = src_ckpt
            return
        try:
            ok = ray_tpu.get(target.actor.reset.remote(new_config))
        except (TaskError, ActorError, ActorDiedError):
            ok = False
        if not ok:
            try:
                ray_tpu.kill(target.actor)
            except Exception:
                pass
            target.actor = self._create_actor(
                target, new_config, getattr(target, "_pg", None))
        try:
            ray_tpu.get(target.actor.restore.remote(src_ckpt))
        except (TaskError, ActorError, ActorDiedError):
            # A dead target must not unwind the whole experiment; its
            # next train() future will fail and go through the normal
            # max_failures machinery.
            return
        target.config = new_config

    # -- stopping criteria -------------------------------------------
    def _should_stop(self, result: Dict) -> bool:
        for key, threshold in self.stop_criteria.items():
            v = result.get(key)
            if v is not None and v >= threshold:
                return True
        return False

    # -- main loop ----------------------------------------------------
    def _capacity(self) -> int:
        if self.max_concurrent <= 0:
            return 1 << 30
        running = sum(1 for t in self.trials if t.status == RUNNING)
        return max(0, self.max_concurrent - running)

    def run(self) -> List[Trial]:
        # Pre-create all pending trials the searcher can produce; start
        # up to capacity (the cluster queues actor creation beyond it).
        while True:
            self._fill()
            if not self._futures:
                if any(t.status in (PENDING, RUNNING) for t in self.trials):
                    continue
                paused = [t for t in self.trials if t.status == PAUSED]
                if paused:
                    # Nothing running and nothing pending: whatever
                    # paused these trials (soft stop, a synch barrier
                    # whose trigger died) will never fire again, so
                    # resume them rather than deadlock or strand them.
                    # Rescued trials run to completion — re-pausing in
                    # the experiment tail would thrash actor setup and
                    # teardown once per training step.
                    for t in paused:
                        t._rescued = True
                        self.unpause_trial(t)
                    continue
                break
            ready, _ = ray_tpu.wait(
                list(self._futures.keys()), num_returns=1, timeout=120.0)
            if not ready:
                continue
            fut = ready[0]
            trial = self._futures.pop(fut)
            try:
                result = ray_tpu.get(fut)
            except (TaskError, ActorError, ActorDiedError) as e:
                self._handle_failure(trial, e)
                continue
            self._handle_result(trial, result)
        self._snapshot()
        self.callbacks.fire("on_experiment_end", self.trials)
        return self.trials

    def _fill(self) -> None:
        while self._capacity() > 0:
            pending = next(
                (t for t in self.trials if t.status == PENDING), None)
            if pending is None:
                pending = self._next_trial()
            if pending is None:
                return
            self._start_trial(pending)

    def _handle_result(self, trial: Trial, result: Dict) -> None:
        if result.get(DONE):
            self._stop_trial(trial, TERMINATED)
            return
        result[TRIAL_ID] = trial.trial_id
        result["config"] = trial.config
        trial.last_result = result
        trial.results.append(result)
        trial.iteration = result.get(TRAINING_ITERATION, trial.iteration + 1)
        self._cb_iteration += 1
        self.callbacks.fire("on_trial_result", self._cb_iteration,
                            self.trials, trial, result)
        self.searcher.on_trial_result(trial.trial_id, result)
        if self.checkpoint_frequency and \
                trial.iteration % self.checkpoint_frequency == 0:
            self._save_trial_checkpoint(trial)
        if self._should_stop(result):
            self._stop_trial(trial, TERMINATED)
            return
        decision = self.scheduler.on_trial_result(self, trial, result)
        if decision == TrialScheduler.PAUSE \
                and getattr(trial, "_rescued", False):
            decision = TrialScheduler.CONTINUE
        if decision == TrialScheduler.STOP:
            self._stop_trial(trial, TERMINATED)
        elif decision == TrialScheduler.PAUSE:
            self._pause_trial(trial)
        elif decision == TrialScheduler.NOOP:
            pass  # scheduler already restarted/parked the trial itself
        else:
            self._submit_train(trial)

    def _pause_trial(self, trial: Trial) -> None:
        """Checkpoint and park the trial, releasing its actor, placement
        group, and concurrency slot (a paused trial must not pin compute
        — median-stopping's soft stop pauses precisely to free it).
        Resume goes through the normal restore path."""
        if self._save_trial_checkpoint(trial) is None:
            # No checkpoint means resuming would silently restart from
            # scratch; keep training instead of losing state.
            self._submit_train(trial)
            return
        trial.restore_pending = trial.checkpoint
        self._release_trial_resources(trial)
        trial.status = PAUSED

    def unpause_trial(self, trial: Trial) -> None:
        """Move a paused trial back to PENDING; _fill restarts it within
        the concurrency budget and restores its pause checkpoint."""
        if trial.status != PAUSED:
            return
        trial.status = PENDING

    def _handle_failure(self, trial: Trial, error: BaseException) -> None:
        n = self._failures.get(trial.trial_id, 0)
        if n < self.max_failures:
            self._failures[trial.trial_id] = n + 1
            trial.restore_pending = trial.checkpoint
            # Release the dead actor AND its placement group before the
            # retry reserves a fresh one — otherwise the old reservation
            # leaks and can starve the retry forever.
            self._release_trial_resources(trial)
            trial.status = PENDING  # re-started by _fill
            self._start_trial(trial)
        else:
            self.scheduler.on_trial_error(self, trial)
            self._stop_trial(trial, ERROR, error=error)

    # -- experiment state snapshot/resume -----------------------------
    @property
    def _state_file(self) -> str:
        return os.path.join(self.storage.experiment_dir,
                            "experiment_state.pkl")

    def _snapshot(self) -> None:
        state = [{
            "trial_id": t.trial_id,
            "config": t.config,
            "status": t.status,
            "last_result": t.last_result,
            "iteration": t.iteration,
            "checkpoint_path": t.checkpoint.path if t.checkpoint else None,
        } for t in self.trials]
        os.makedirs(self.storage.experiment_dir, exist_ok=True)
        with open(self._state_file, "wb") as f:
            pickle.dump(state, f)

    def load_snapshot(self) -> bool:
        if not os.path.exists(self._state_file):
            return False
        with open(self._state_file, "rb") as f:
            state = pickle.load(f)
        for s in state:
            trial = Trial(s["trial_id"], s["config"],
                          self.storage.experiment_name)
            trial.last_result = s["last_result"]
            trial.iteration = s["iteration"]
            if s["checkpoint_path"]:
                trial.checkpoint = Checkpoint(s["checkpoint_path"])
            if s["status"] in (TERMINATED, ERROR):
                trial.status = s["status"]
            else:
                trial.status = PENDING
                trial.restore_pending = trial.checkpoint
            self.trials.append(trial)
            self._trial_counter = max(self._trial_counter,
                                      int(s["trial_id"]) + 1)
        return True
