"""ResultGrid: the outcome of a Tuner.fit().

Reference: ``python/ray/tune/result_grid.py`` — a list of per-trial
Results with best-result selection.
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.train.result import Result
from ray_tpu.tune.experiment import ERROR, Trial


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str] = None,
                 mode: str = "max", experiment_path: str = ""):
        self._trials = trials
        self._metric = metric
        self._mode = mode
        self.experiment_path = experiment_path
        self._results = [
            Result(metrics=t.last_result or None,
                   checkpoint=t.checkpoint,
                   path=experiment_path,
                   error=t.error)
            for t in trials]

    def __len__(self) -> int:
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[BaseException]:
        return [r.error for r in self._results if r.error is not None]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    @property
    def num_terminated(self) -> int:
        return sum(1 for t in self._trials if t.status != ERROR)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("No metric given to get_best_result and none "
                             "set in TuneConfig.")
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise RuntimeError(f"No trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return (max if mode == "max" else min)(scored, key=key)

    def get_dataframe(self):
        import pandas as pd
        return pd.DataFrame([dict(r.metrics or {}) for r in self._results])
