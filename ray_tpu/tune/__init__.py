"""ray_tpu.tune: hyperparameter tuning (reference: ``python/ray/tune/``).

Public surface mirrors ``ray.tune``: Tuner/TuneConfig/ResultGrid, the
search-space DSL, searchers, trial schedulers, Trainable (class and
function APIs), ``tune.report``, and the classic ``tune.run``.
"""

from ray_tpu.tune.placement_groups import PlacementGroupFactory
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.search.sample import (
    choice, grid_search, lograndint, loguniform, qloguniform, qrandint,
    quniform, randint, randn, sample_from, uniform)
from ray_tpu.tune.trainable import (
    FunctionTrainable, Trainable, get_checkpoint, report, with_parameters,
    with_resources)
from ray_tpu.tune.tuner import TuneConfig, Tuner, run

__all__ = [
    "FunctionTrainable",
    "PlacementGroupFactory",
    "ResultGrid",
    "Trainable",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "lograndint",
    "loguniform",
    "qloguniform",
    "qrandint",
    "quniform",
    "randint",
    "randn",
    "report",
    "run",
    "sample_from",
    "uniform",
    "with_parameters",
    "with_resources",
]
