"""ray_tpu.tune: hyperparameter tuning (reference: ``python/ray/tune/``)."""

from ray_tpu.tune.placement_groups import PlacementGroupFactory

__all__ = ["PlacementGroupFactory"]
