"""Per-process trial context: the placement group a trial actor was
scheduled into, so nested worker groups (a Trainer running inside a Tune
trial) reuse the trial's reserved bundles instead of reserving twice.

Reference analog: placement groups with ``capture_child_tasks`` plumbed
through ``tune/execution``; here it's an explicit handoff.
"""

from __future__ import annotations

from typing import Optional

_trial_pg = None
_trial_dir: Optional[str] = None


def set_trial_placement_group(pg) -> None:
    global _trial_pg
    _trial_pg = pg


def get_trial_placement_group():
    return _trial_pg


def set_trial_dir(path: Optional[str]) -> None:
    global _trial_dir
    _trial_dir = path


def get_trial_dir() -> Optional[str]:
    return _trial_dir
