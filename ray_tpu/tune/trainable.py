"""Trainable: the unit of execution Tune schedules.

Reference: ``python/ray/tune/trainable/trainable.py`` — an actor with
``setup/step/save_checkpoint/load_checkpoint`` driven by repeated
``train()`` calls — and ``function_trainable.py`` (user function running
on a thread, ``tune.report`` feeding a bounded queue). Both styles run
inside a ``_TrainableActor`` here.
"""

from __future__ import annotations

import os
import queue
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint

# Result-dict autofilled keys (reference ``tune/result.py``)
TRAINING_ITERATION = "training_iteration"
DONE = "done"
TRIAL_ID = "trial_id"


class Trainable:
    """Class API: subclass with setup/step/save/load (reference :239)."""

    def __init__(self, config: Optional[Dict] = None):
        self.config = config or {}
        self._iteration = 0
        self.setup(self.config)

    # -- overridable ---------------------------------------------------
    def setup(self, config: Dict) -> None:
        pass

    def step(self) -> Dict:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[str]:
        return None

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def reset_config(self, new_config: Dict) -> bool:
        """Return True if the trainable supports in-place config swap
        (lets PBT exploit without actor teardown)."""
        return False

    def cleanup(self) -> None:
        pass

    # -- driver-facing -------------------------------------------------
    @property
    def iteration(self) -> int:
        return self._iteration

    def train(self) -> Dict:
        result = self.step() or {}
        self._iteration += 1
        result.setdefault(TRAINING_ITERATION, self._iteration)
        return result

    def save(self, checkpoint_dir: Optional[str] = None) -> Checkpoint:
        d = checkpoint_dir or tempfile.mkdtemp(prefix="tune_ckpt_")
        os.makedirs(d, exist_ok=True)
        out = self.save_checkpoint(d) or d
        return Checkpoint(out)

    def restore(self, checkpoint: Checkpoint) -> None:
        self.load_checkpoint(checkpoint.path)

    def reset(self, new_config: Dict) -> bool:
        ok = self.reset_config(new_config)
        if ok:
            self.config = new_config
        return ok

    def stop(self) -> None:
        self.cleanup()


class FunctionTrainable(Trainable):
    """Function API: runs ``fn(config)`` on a thread; ``tune.report``
    yields one result per train() call (reference function_trainable)."""

    _fn: Callable = None  # set by wrap()

    @classmethod
    def wrap(cls, fn: Callable) -> type:
        return type(f"func_{getattr(fn, '__name__', 'trainable')}",
                    (cls,), {"_fn": staticmethod(fn)})

    def setup(self, config: Dict) -> None:
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._thread: Optional[threading.Thread] = None
        self._restore_checkpoint: Optional[Checkpoint] = None
        self._last_checkpoint: Optional[Checkpoint] = None
        self._error: Optional[BaseException] = None

    def _run(self):
        global _fn_session
        _fn_session = _FunctionSession(
            self._queue, self._restore_checkpoint)
        try:
            self._fn(self.config)
            self._queue.put(("done", None, None))
        except BaseException as e:
            self._queue.put(("error", e, None))

    def step(self) -> Dict:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tune_fn", daemon=True)
            self._thread.start()
        kind, payload, ckpt = self._queue.get()
        if kind == "error":
            raise payload
        if kind == "done":
            return {DONE: True}
        if ckpt is not None:
            self._last_checkpoint = ckpt
        return payload

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[str]:
        if self._last_checkpoint is None:
            return None
        import shutil
        shutil.copytree(self._last_checkpoint.path, checkpoint_dir,
                        dirs_exist_ok=True)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        self._restore_checkpoint = Checkpoint(checkpoint_dir)


class _FunctionSession:
    def __init__(self, q: "queue.Queue",
                 checkpoint: Optional[Checkpoint]):
        self.queue = q
        self.loaded_checkpoint = checkpoint


_fn_session: Optional[_FunctionSession] = None


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """``ray_tpu.tune.report`` — inside a function trainable."""
    s = _fn_session
    if s is None:
        # Fall back to the train-session report (trainer inside tune).
        from ray_tpu.train._internal import session as train_session
        if train_session.get_session() is not None:
            train_session.report(metrics, checkpoint=checkpoint)
            return
        raise RuntimeError("tune.report() called outside a trial")
    s.queue.put(("result", dict(metrics), checkpoint))


def get_checkpoint() -> Optional[Checkpoint]:
    s = _fn_session
    if s is not None:
        return s.loaded_checkpoint
    from ray_tpu.train._internal import session as train_session
    return train_session.get_checkpoint()


def with_parameters(trainable, **kwargs):
    """Bind large constant objects into a trainable
    (reference ``tune/trainable/util.py:with_parameters``)."""
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        class _Bound(trainable):  # type: ignore[misc, valid-type]
            def setup(self, config):
                super().setup({**config, **kwargs})
        _Bound.__name__ = trainable.__name__
        return _Bound

    def _fn(config):
        return trainable(config, **kwargs)
    _fn.__name__ = getattr(trainable, "__name__", "trainable")
    if hasattr(trainable, "default_resource_request"):
        _fn.default_resource_request = trainable.default_resource_request
    return _fn


def with_resources(trainable, resources):
    """Attach a resource request (dict or PlacementGroupFactory)."""
    from ray_tpu.tune.placement_groups import PlacementGroupFactory
    if isinstance(resources, dict):
        resources = PlacementGroupFactory([resources])
    if isinstance(trainable, type):
        trainable.default_resource_request = classmethod(
            lambda cls, config: resources)
    else:
        trainable.default_resource_request = lambda config: resources
    return trainable
