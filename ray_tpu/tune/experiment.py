"""Trial: one (config, trainable) run tracked by the controller.

Reference: ``python/ray/tune/experiment/trial.py``.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any],
                 experiment_name: str = ""):
        self.trial_id = trial_id
        self.config = config
        self.experiment_name = experiment_name
        self.status = PENDING
        self.last_result: Dict[str, Any] = {}
        self.results: list = []
        self.checkpoint: Optional[Checkpoint] = None
        self.error: Optional[BaseException] = None
        self.actor = None
        self.iteration = 0
        self.restore_pending: Optional[Checkpoint] = None

    @property
    def trial_name(self) -> str:
        return f"{self.trial_id}"

    def metric_value(self, metric: str) -> Optional[float]:
        v = self.last_result.get(metric)
        return float(v) if v is not None else None

    def __repr__(self):
        return (f"Trial({self.trial_id}, status={self.status}, "
                f"iter={self.iteration})")
