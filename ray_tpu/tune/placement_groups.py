"""PlacementGroupFactory: deferred placement-group requests.

Reference: ``python/ray/tune/execution/placement_groups.py`` — a
picklable description of the bundles a trial/trainer needs; the actual
placement group is created at schedule time.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class PlacementGroupFactory:
    def __init__(self, bundles: List[Dict[str, float]],
                 strategy: str = "PACK"):
        if not bundles:
            raise ValueError("PlacementGroupFactory needs >= 1 bundle")
        # Drop empty bundles the way the reference does (head bundle may
        # legitimately be {} when the trainer itself needs no resources).
        self.bundles = [
            {k: float(v) for k, v in b.items() if v} for b in bundles]
        self.strategy = strategy

    @property
    def head_bundle_is_empty(self) -> bool:
        return not self.bundles[0]

    def required_resources(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for b in self.bundles:
            for k, v in b.items():
                total[k] = total.get(k, 0.0) + v
        return total

    def __call__(self):
        """Create the placement group. An empty HEAD bundle is omitted
        (consumers must then use bundle offset 0 for workers); empty
        non-head bundles are invalid — dropping them would silently
        shift every later bundle index."""
        from ray_tpu.util.placement_group import placement_group
        bundles = self.bundles[1:] if self.head_bundle_is_empty \
            else self.bundles
        if any(not b for b in bundles):
            raise ValueError(
                f"Empty non-head bundle in {self.bundles!r}")
        return placement_group(bundles, strategy=self.strategy)

    def __eq__(self, other):
        return (isinstance(other, PlacementGroupFactory)
                and self.bundles == other.bundles
                and self.strategy == other.strategy)

    def __repr__(self):
        return (f"PlacementGroupFactory(bundles={self.bundles!r}, "
                f"strategy={self.strategy!r})")
