"""Tuner: the public tuning entry point.

Reference: ``python/ray/tune/tuner.py:54`` (``fit`` :354) +
``tune_config.py`` (``TuneConfig``) + ``impl/tuner_internal.py``.
"""

from __future__ import annotations

import dataclasses
import os
import time
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.config import RunConfig
from ray_tpu.train._internal.storage import StorageContext
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import Searcher
from ray_tpu.tune.tune_controller import TuneController


@dataclasses.dataclass
class TuneConfig:
    """Reference: ``python/ray/tune/tune_config.py``."""

    metric: Optional[str] = None
    mode: Optional[str] = None
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    time_budget_s: Optional[float] = None
    reuse_actors: bool = False

    def __post_init__(self):
        if self.mode is not None and self.mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")


class Tuner:
    def __init__(self, trainable=None, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 _controller: Optional[TuneController] = None):
        self._trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._controller = _controller

    def _make_controller(self) -> TuneController:
        name = self.run_config.name or (
            f"tune_{time.strftime('%Y-%m-%d_%H-%M-%S')}"
            f"_{uuid.uuid4().hex[:6]}")
        self.run_config.name = name
        storage = StorageContext(self.run_config.storage_path, name)
        cc = self.run_config.checkpoint_config
        # When the trainable is a Trainer, unwrap to its tune trainable.
        trainable = self._trainable
        from ray_tpu.train.base_trainer import BaseTrainer
        if isinstance(trainable, BaseTrainer):
            trainable = trainable.as_trainable()
        return TuneController(
            trainable, self.param_space,
            searcher=self.tune_config.search_alg,
            scheduler=self.tune_config.scheduler,
            storage=storage,
            metric=self.tune_config.metric,
            mode=self.tune_config.mode,
            num_samples=self.tune_config.num_samples,
            max_concurrent_trials=self.tune_config.max_concurrent_trials,
            stop=self.run_config.stop,
            max_failures=self.run_config.failure_config.max_failures,
            checkpoint_frequency=cc.checkpoint_frequency,
            checkpoint_at_end=(cc.checkpoint_at_end
                               if cc.checkpoint_at_end is not None
                               else True),
            callbacks=self.run_config.callbacks)

    def fit(self) -> ResultGrid:
        if self._controller is None:
            self._controller = self._make_controller()
        trials = self._controller.run()
        return ResultGrid(
            trials, metric=self.tune_config.metric,
            mode=self.tune_config.mode or "max",
            experiment_path=self._controller.storage.experiment_dir)

    def get_results(self) -> ResultGrid:
        if self._controller is None:
            raise RuntimeError("fit() has not been called")
        return ResultGrid(
            self._controller.trials, metric=self.tune_config.metric,
            mode=self.tune_config.mode or "max",
            experiment_path=self._controller.storage.experiment_dir)

    @classmethod
    def restore(cls, path: str, trainable,
                param_space: Optional[Dict] = None,
                tune_config: Optional[TuneConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its directory
        (reference ``Tuner.restore``): terminated trials keep their
        results; unfinished ones restart from their last checkpoint."""
        path = os.path.abspath(os.path.expanduser(path))
        name = os.path.basename(path.rstrip("/"))
        storage_path = os.path.dirname(path.rstrip("/"))
        run_config = RunConfig(name=name, storage_path=storage_path)
        tuner = cls(trainable, param_space=param_space,
                    tune_config=tune_config, run_config=run_config)
        controller = tuner._make_controller()
        if not controller.load_snapshot():
            raise ValueError(f"No experiment state found at {path}")
        controller._searcher_done = True  # only resume existing trials
        tuner._controller = controller
        return tuner

    @classmethod
    def can_restore(cls, path: str) -> bool:
        return os.path.exists(
            os.path.join(path, "experiment_state.pkl"))


def run(trainable, *, config: Optional[Dict] = None, num_samples: int = 1,
        metric: Optional[str] = None, mode: Optional[str] = None,
        search_alg=None, scheduler=None, stop=None, storage_path=None,
        name=None, max_concurrent_trials=None, **_ignored) -> ResultGrid:
    """Classic ``tune.run`` API (reference ``python/ray/tune/tune.py``)."""
    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples,
            search_alg=search_alg, scheduler=scheduler,
            max_concurrent_trials=max_concurrent_trials),
        run_config=RunConfig(name=name, storage_path=storage_path,
                             stop=stop))
    return tuner.fit()
