"""Tune callback API + built-in loggers.

Reference: ``python/ray/tune/callback.py`` (Callback hooks driven by
the trial loop) and ``tune/logger/`` (``CSVLoggerCallback``,
``JsonLoggerCallback``). Experiment-tracking adapters
(wandb/mlflow/comet) build on this in ``ray_tpu.air.integrations``.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional


class Callback:
    """Hook points around the experiment loop. All optional."""

    def setup(self, stop=None, num_samples=None, **info) -> None:
        pass

    def on_trial_start(self, iteration: int, trials: List, trial,
                       **info) -> None:
        pass

    def on_trial_result(self, iteration: int, trials: List, trial,
                        result: Dict, **info) -> None:
        pass

    def on_trial_complete(self, iteration: int, trials: List, trial,
                          **info) -> None:
        pass

    def on_trial_error(self, iteration: int, trials: List, trial,
                       **info) -> None:
        pass

    def on_checkpoint(self, iteration: int, trials: List, trial,
                      checkpoint, **info) -> None:
        pass

    def on_experiment_end(self, trials: List, **info) -> None:
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self._cbs = list(callbacks or [])

    def __bool__(self):
        return bool(self._cbs)

    def fire(self, hook: str, *args, **kw) -> None:
        for cb in self._cbs:
            try:
                getattr(cb, hook)(*args, **kw)
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "callback %s.%s failed", type(cb).__name__, hook)


def _scrub(result: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten + drop non-scalar values for tabular sinks."""
    flat: Dict[str, Any] = {}

    def walk(prefix: str, obj: Any) -> None:
        if isinstance(obj, dict):
            for k, v in obj.items():
                walk(f"{prefix}{k}/" if isinstance(v, dict) else
                     f"{prefix}{k}", v)
        elif isinstance(obj, (int, float, str, bool)) or obj is None:
            flat[prefix.rstrip("/")] = obj

    walk("", result)
    return flat


class JsonLoggerCallback(Callback):
    """result.json per trial, one JSON line per result (reference:
    ``tune/logger/json.py``)."""

    def __init__(self):
        self._files: Dict[str, Any] = {}

    def _file(self, trial):
        f = self._files.get(trial.trial_id)
        if f is None:
            local_dir = getattr(trial, "local_dir", None)
            if not local_dir:
                return None
            os.makedirs(local_dir, exist_ok=True)
            f = self._files[trial.trial_id] = open(
                os.path.join(local_dir, "result.json"), "a")
        return f

    def on_trial_result(self, iteration, trials, trial, result, **info):
        f = self._file(trial)
        if f is None:
            return
        json.dump(_scrub(result), f, default=str)
        f.write("\n")
        f.flush()

    def on_experiment_end(self, trials, **info):
        for f in self._files.values():
            try:
                f.close()
            except Exception:
                pass
        self._files.clear()


class CSVLoggerCallback(Callback):
    """progress.csv per trial (reference: ``tune/logger/csv.py``)."""

    def __init__(self):
        self._writers: Dict[str, Any] = {}
        self._files: Dict[str, Any] = {}

    def on_trial_result(self, iteration, trials, trial, result, **info):
        if not getattr(trial, "local_dir", None):
            return
        flat = _scrub(result)
        w = self._writers.get(trial.trial_id)
        if w is None:
            os.makedirs(trial.local_dir, exist_ok=True)
            f = open(os.path.join(trial.local_dir, "progress.csv"),
                     "w", newline="")
            w = csv.DictWriter(f, fieldnames=sorted(flat))
            w.writeheader()
            self._files[trial.trial_id] = f
            self._writers[trial.trial_id] = w
        w.writerow({k: flat.get(k) for k in w.fieldnames})
        self._files[trial.trial_id].flush()

    def on_experiment_end(self, trials, **info):
        for f in self._files.values():
            try:
                f.close()
            except Exception:
                pass
        self._files.clear()
        self._writers.clear()
