"""Search-space DSL: tune.uniform / loguniform / choice / randint / ...

Reference: ``python/ray/tune/search/sample.py`` — Domain objects carried
in ``param_space`` dicts, resolved per-trial by the variant generator.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: Optional[float] = None):
        if log and lower <= 0:
            raise ValueError("loguniform needs lower > 0")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> float:
        if self.log:
            import math
            v = math.exp(rng.uniform(math.log(self.lower),
                                     math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(round(v / self.q) * self.q, 10)
        return v


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False,
                 q: Optional[int] = None):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> int:
        if self.log:
            import math
            v = int(math.exp(rng.uniform(math.log(self.lower),
                                         math.log(self.upper))))
        else:
            v = rng.randint(self.lower, self.upper - 1)
        if self.q:
            v = int(round(v / self.q) * self.q)
        return max(self.lower, min(v, self.upper - 1))


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:
        try:
            return self.fn(None)
        except TypeError:
            return self.fn()


class GridSearch:
    """Marker resolved exhaustively by the variant generator."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


# -- public constructors (reference API names) ------------------------
def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, log=True, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> Dict[str, List[Any]]:
    # Reference encodes grid_search as {"grid_search": [...]} in dicts.
    return {"grid_search": list(values)}


def randn(mean: float = 0.0, sd: float = 1.0) -> Function:
    return Function(lambda: random.gauss(mean, sd))
