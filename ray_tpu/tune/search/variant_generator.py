"""Resolve a param_space into concrete trial configs.

Reference: ``python/ray/tune/search/variant_generator.py`` —
``generate_variants``: cartesian product over every ``grid_search`` in
the (nested) space, with Domain objects sampled per variant.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Tuple

from ray_tpu.tune.search.sample import Domain, GridSearch


def _find_grids(space: Any, path: Tuple = ()) -> List[Tuple[Tuple, List]]:
    grids = []
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            return [(path, list(space["grid_search"]))]
        for k, v in space.items():
            grids.extend(_find_grids(v, path + (k,)))
    elif isinstance(space, GridSearch):
        grids.append((path, space.values))
    return grids


def _assign(config: Dict, path: Tuple, value: Any) -> None:
    d = config
    for k in path[:-1]:
        d = d[k]
    d[path[-1]] = value


def _resolve(space: Any, rng: random.Random) -> Any:
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            return space  # replaced by grid assignment
        return {k: _resolve(v, rng) for k, v in space.items()}
    if isinstance(space, Domain):
        return space.sample(rng)
    if isinstance(space, GridSearch):
        return space
    return space


def generate_variants(space: Dict, num_samples: int = 1,
                      seed: int = None) -> Iterator[Dict]:
    """Yield ``num_samples`` x (cartesian grid product) concrete configs.

    Reference semantics (``basic_variant.py``): num_samples repeats the
    whole grid; random Domains resample per repeat.
    """
    rng = random.Random(seed)
    grids = _find_grids(space)
    grid_values = [v for _, v in grids]
    for _ in range(num_samples):
        if grids:
            for combo in itertools.product(*grid_values):
                config = _resolve(space, rng)
                for (path, _), value in zip(grids, combo):
                    _assign(config, path, value)
                yield config
        else:
            yield _resolve(space, rng)
