from ray_tpu.tune.search.sample import (
    choice, grid_search, lograndint, loguniform, qloguniform, qrandint,
    quniform, randint, randn, sample_from, uniform)
from ray_tpu.tune.search.searcher import (
    BasicVariantGenerator, BayesOptSearch, ConcurrencyLimiter,
    HyperOptSearch, OptunaSearch, Searcher)

__all__ = [
    "BasicVariantGenerator", "BayesOptSearch", "ConcurrencyLimiter",
    "HyperOptSearch", "OptunaSearch",
    "Searcher", "choice", "grid_search", "lograndint", "loguniform",
    "qloguniform", "qrandint", "quniform", "randint", "randn",
    "sample_from", "uniform",
]
