"""Searcher interface + built-in search algorithms.

Reference: ``python/ray/tune/search/searcher.py`` (``Searcher`` ABC with
``suggest``/``on_trial_complete``), ``basic_variant.py``
(``BasicVariantGenerator``: grid + random, the default), and the wrapper
pattern of ``concurrency_limiter.py``. Third-party searchers (hyperopt,
optuna, …) follow the same interface; OptunaSearch is provided gated on
the optional dependency.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.tune.search.variant_generator import generate_variants


class Searcher:
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str],
                              config: Dict, **kwargs) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        """Next config; None = nothing available right now (the
        controller re-asks later unless ``is_finished()``)."""
        raise NotImplementedError

    def is_finished(self) -> bool:
        """True when this searcher will never produce another config."""
        return False

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid + random sampling (reference default searcher)."""

    def __init__(self, points_to_evaluate: Optional[List[Dict]] = None,
                 max_concurrent: int = 0,
                 random_state: Optional[int] = None):
        super().__init__()
        self._points = list(points_to_evaluate or [])
        self._space: Optional[Dict] = None
        self._num_samples = 1
        self._variants = None
        self._seed = random_state
        self._exhausted = False
        self.max_concurrent = max_concurrent

    def set_search_properties(self, metric, mode, config,
                              num_samples: int = 1) -> bool:
        super().set_search_properties(metric, mode, config)
        self._space = config
        self._num_samples = num_samples
        self._variants = iter(self._make())
        return True

    def _make(self):
        for p in self._points:
            yield dict(p)
        if self._space is not None:
            remaining = self._num_samples
            yield from generate_variants(
                self._space, num_samples=remaining, seed=self._seed)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._variants is None:
            self._variants = iter(self._make())
        try:
            return next(self._variants)
        except StopIteration:
            self._exhausted = True
            return None

    def is_finished(self) -> bool:
        return self._exhausted

    @property
    def total_samples(self) -> int:
        from ray_tpu.tune.search.variant_generator import _find_grids
        n_grid = 1
        for _, vals in _find_grids(self._space or {}):
            n_grid *= max(1, len(vals))
        return len(self._points) + n_grid * self._num_samples


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, config, **kw) -> bool:
        return self.searcher.set_search_properties(metric, mode, config, **kw)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if len(self._live) >= self.max_concurrent:
            return None  # transient — controller re-asks later
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def is_finished(self) -> bool:
        return self.searcher.is_finished()

    @property
    def total_samples(self):
        return getattr(self.searcher, "total_samples", None)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)


class OptunaSearch(Searcher):
    """TPE via optuna, if installed (reference ``search/optuna/``)."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None, seed: Optional[int] = None):
        super().__init__(metric, mode)
        try:
            import optuna  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires `optuna`, which is not installed."
            ) from e
        self._seed = seed
        self._study = None
        self._space = None
        self._live: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, config, **kw) -> bool:
        super().set_search_properties(metric, mode, config)
        import optuna
        self._space = config
        direction = "maximize" if self.mode == "max" else "minimize"
        sampler = optuna.samplers.TPESampler(seed=self._seed)
        self._study = optuna.create_study(
            direction=direction, sampler=sampler)
        return True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        from ray_tpu.tune.search import sample as s
        ot = self._study.ask()
        cfg = {}
        for k, v in (self._space or {}).items():
            if isinstance(v, s.Float):
                cfg[k] = ot.suggest_float(k, v.lower, v.upper, log=v.log)
            elif isinstance(v, s.Integer):
                cfg[k] = ot.suggest_int(k, v.lower, v.upper - 1, log=v.log)
            elif isinstance(v, s.Categorical):
                cfg[k] = ot.suggest_categorical(k, v.categories)
            elif isinstance(v, s.Domain):
                cfg[k] = v.sample(__import__("random").Random())
            else:
                cfg[k] = v
        self._live[trial_id] = ot
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        ot = self._live.pop(trial_id, None)
        if ot is None or self._study is None:
            return
        if error or not result or self.metric not in result:
            self._study.tell(ot, state=__import__(
                "optuna").trial.TrialState.FAIL)
        else:
            self._study.tell(ot, result[self.metric])


class HyperOptSearch(Searcher):
    """TPE via hyperopt, if installed (reference ``search/hyperopt/``).
    Tuned keys come from hyperopt; constants and unsupported domains
    pass through / sample from the DSL so the trial config is always
    complete."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 n_initial_points: int = 20,
                 random_state_seed: Optional[int] = None):
        super().__init__(metric, mode)
        try:
            import hyperopt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "HyperOptSearch requires `hyperopt`, which is not baked "
                "into the hermetic TPU image — add it to the image, or "
                "use the built-in BasicVariantGenerator / schedulers."
            ) from e
        self._n_initial = n_initial_points
        self._seed = random_state_seed
        self._space_cfg: Dict[str, Any] = {}
        self._domain = None
        self._trials = None
        self._live: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, config, **kw) -> bool:
        super().set_search_properties(metric, mode, config)
        import math

        import hyperopt as hpo

        from ray_tpu.tune.search import sample as s
        self._space_cfg = dict(config or {})
        space = {}
        for k, v in self._space_cfg.items():
            if isinstance(v, s.Float):
                space[k] = (hpo.hp.loguniform(k, math.log(v.lower),
                                              math.log(v.upper))
                            if v.log else hpo.hp.uniform(k, v.lower, v.upper))
            elif isinstance(v, s.Integer):
                if v.log:
                    # hyperopt has no log-int primitive: round a
                    # qloguniform sample (preserves the log intent)
                    space[k] = hpo.hp.qloguniform(
                        k, math.log(v.lower), math.log(v.upper - 1), 1)
                else:
                    space[k] = hpo.hp.randint(k, v.lower, v.upper)
            elif isinstance(v, s.Categorical):
                space[k] = hpo.hp.choice(k, v.categories)
            # constants / other domains stay out of the hyperopt space
        self._hpo_keys = set(space)
        self._domain = hpo.Domain(lambda spc: 0, space)
        self._trials = hpo.Trials()
        return True

    def _base_config(self) -> Dict[str, Any]:
        import random

        from ray_tpu.tune.search import sample as s
        rng = random.Random(self._seed)
        out = {}
        for k, v in self._space_cfg.items():
            if k in self._hpo_keys:
                continue
            out[k] = v.sample(rng) if isinstance(v, s.Domain) else v
        return out

    def suggest(self, trial_id: str) -> Optional[Dict]:
        import numpy as np

        import hyperopt as hpo

        from ray_tpu.tune.search import sample as s
        n = len(self._trials.trials)
        rng = np.random.default_rng(
            self._seed + n if self._seed is not None else None)
        new = hpo.tpe.suggest(
            [n], self._domain, self._trials,
            rng.integers(0, 2 ** 31 - 1),
            n_startup_jobs=self._n_initial)
        self._trials.insert_trial_docs(new)
        self._trials.refresh()
        vals = {k: v[0] for k, v in new[0]["misc"]["vals"].items() if v}
        cfg = self._base_config()
        for k in self._hpo_keys:
            if k not in vals:
                continue
            v = self._space_cfg[k]
            if isinstance(v, s.Categorical):
                cfg[k] = v.categories[int(vals[k])]  # hp.choice -> index
            elif isinstance(v, s.Integer):
                cfg[k] = max(v.lower, min(v.upper - 1, int(vals[k])))
            else:
                cfg[k] = float(vals[k])
        self._live[trial_id] = new[0]["tid"]
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        tid = self._live.pop(trial_id, None)
        if tid is None:
            return
        import hyperopt as hpo
        for t in self._trials.trials:
            if t["tid"] == tid:
                if error or not result or self.metric not in result:
                    t["state"] = hpo.JOB_STATE_ERROR
                else:
                    val = result[self.metric]
                    loss = -val if self.mode == "max" else val
                    t["result"] = {"loss": loss,
                                   "status": hpo.STATUS_OK}
                    t["state"] = hpo.JOB_STATE_DONE
        self._trials.refresh()


class BayesOptSearch(Searcher):
    """Gaussian-process search via bayesian-optimization, if installed
    (reference ``search/bayesopt/``). Like the reference, only
    continuous Float/Integer domains are optimizable — Categorical
    raises loudly; constants pass through."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 random_state: int = 42, **kwargs):
        super().__init__(metric, mode)
        try:
            import bayes_opt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "BayesOptSearch requires `bayesian-optimization`, which "
                "is not baked into the hermetic TPU image — add it to "
                "the image, or use the built-in searchers/schedulers."
            ) from e
        self._random_state = random_state
        self._kwargs = kwargs
        self._optimizer = None
        self._utility = None
        self._space_cfg: Dict[str, Any] = {}
        self._live: Dict[str, Dict] = {}

    def set_search_properties(self, metric, mode, config, **kw) -> bool:
        super().set_search_properties(metric, mode, config)
        from bayes_opt import BayesianOptimization

        from ray_tpu.tune.search import sample as s
        self._space_cfg = dict(config or {})
        bounds = {}
        for k, v in self._space_cfg.items():
            if isinstance(v, (s.Float, s.Integer)):
                bounds[k] = (v.lower, v.upper)
            elif isinstance(v, s.Domain):
                raise ValueError(
                    f"BayesOptSearch only supports continuous "
                    f"float/integer domains; {k!r} is "
                    f"{type(v).__name__} (reference behavior: bayesopt "
                    f"rejects non-continuous spaces)")
        self._optimizer = BayesianOptimization(
            f=None, pbounds=bounds, random_state=self._random_state,
            allow_duplicate_points=True, **self._kwargs)
        # UtilityFunction exists in <2.0 and suggest() requires it
        # there; 2.x suggests without one
        try:
            from bayes_opt import UtilityFunction
            try:
                self._utility = UtilityFunction(kind="ucb", kappa=2.576,
                                                xi=0.0)
            except TypeError:
                self._utility = UtilityFunction()
        except ImportError:
            self._utility = None
        return True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        import random

        from ray_tpu.tune.search import sample as s
        try:
            raw = (self._optimizer.suggest(self._utility)
                   if self._utility is not None
                   else self._optimizer.suggest())
        except TypeError:
            raw = self._optimizer.suggest()
        rng = random.Random(self._random_state)
        cfg = {}
        for k, v in self._space_cfg.items():
            if k in raw:
                if isinstance(v, s.Integer):
                    cfg[k] = max(v.lower,
                                 min(v.upper - 1, int(round(raw[k]))))
                else:
                    cfg[k] = float(raw[k])
            else:
                cfg[k] = v.sample(rng) if isinstance(v, s.Domain) else v
        self._live[trial_id] = dict(raw)
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._live.pop(trial_id, None)
        if cfg is None or error or not result \
                or self.metric not in result:
            return
        val = result[self.metric]
        target = val if self.mode == "max" else -val
        self._optimizer.register(params=cfg, target=target)
