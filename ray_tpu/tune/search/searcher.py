"""Searcher interface + built-in search algorithms.

Reference: ``python/ray/tune/search/searcher.py`` (``Searcher`` ABC with
``suggest``/``on_trial_complete``), ``basic_variant.py``
(``BasicVariantGenerator``: grid + random, the default), and the wrapper
pattern of ``concurrency_limiter.py``. Third-party searchers (hyperopt,
optuna, …) follow the same interface; OptunaSearch is provided gated on
the optional dependency.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.tune.search.variant_generator import generate_variants


class Searcher:
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str],
                              config: Dict, **kwargs) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        """Next config; None = nothing available right now (the
        controller re-asks later unless ``is_finished()``)."""
        raise NotImplementedError

    def is_finished(self) -> bool:
        """True when this searcher will never produce another config."""
        return False

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid + random sampling (reference default searcher)."""

    def __init__(self, points_to_evaluate: Optional[List[Dict]] = None,
                 max_concurrent: int = 0,
                 random_state: Optional[int] = None):
        super().__init__()
        self._points = list(points_to_evaluate or [])
        self._space: Optional[Dict] = None
        self._num_samples = 1
        self._variants = None
        self._seed = random_state
        self._exhausted = False
        self.max_concurrent = max_concurrent

    def set_search_properties(self, metric, mode, config,
                              num_samples: int = 1) -> bool:
        super().set_search_properties(metric, mode, config)
        self._space = config
        self._num_samples = num_samples
        self._variants = iter(self._make())
        return True

    def _make(self):
        for p in self._points:
            yield dict(p)
        if self._space is not None:
            remaining = self._num_samples
            yield from generate_variants(
                self._space, num_samples=remaining, seed=self._seed)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._variants is None:
            self._variants = iter(self._make())
        try:
            return next(self._variants)
        except StopIteration:
            self._exhausted = True
            return None

    def is_finished(self) -> bool:
        return self._exhausted

    @property
    def total_samples(self) -> int:
        from ray_tpu.tune.search.variant_generator import _find_grids
        n_grid = 1
        for _, vals in _find_grids(self._space or {}):
            n_grid *= max(1, len(vals))
        return len(self._points) + n_grid * self._num_samples


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, config, **kw) -> bool:
        return self.searcher.set_search_properties(metric, mode, config, **kw)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if len(self._live) >= self.max_concurrent:
            return None  # transient — controller re-asks later
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def is_finished(self) -> bool:
        return self.searcher.is_finished()

    @property
    def total_samples(self):
        return getattr(self.searcher, "total_samples", None)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)


class OptunaSearch(Searcher):
    """TPE via optuna, if installed (reference ``search/optuna/``)."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None, seed: Optional[int] = None):
        super().__init__(metric, mode)
        try:
            import optuna  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires `optuna`, which is not installed."
            ) from e
        self._seed = seed
        self._study = None
        self._space = None
        self._live: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, config, **kw) -> bool:
        super().set_search_properties(metric, mode, config)
        import optuna
        self._space = config
        direction = "maximize" if self.mode == "max" else "minimize"
        sampler = optuna.samplers.TPESampler(seed=self._seed)
        self._study = optuna.create_study(
            direction=direction, sampler=sampler)
        return True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        from ray_tpu.tune.search import sample as s
        ot = self._study.ask()
        cfg = {}
        for k, v in (self._space or {}).items():
            if isinstance(v, s.Float):
                cfg[k] = ot.suggest_float(k, v.lower, v.upper, log=v.log)
            elif isinstance(v, s.Integer):
                cfg[k] = ot.suggest_int(k, v.lower, v.upper - 1, log=v.log)
            elif isinstance(v, s.Categorical):
                cfg[k] = ot.suggest_categorical(k, v.categories)
            elif isinstance(v, s.Domain):
                cfg[k] = v.sample(__import__("random").Random())
            else:
                cfg[k] = v
        self._live[trial_id] = ot
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        ot = self._live.pop(trial_id, None)
        if ot is None or self._study is None:
            return
        if error or not result or self.metric not in result:
            self._study.tell(ot, state=__import__(
                "optuna").trial.TrialState.FAIL)
        else:
            self._study.tell(ot, result[self.metric])
