"""ray_tpu.dag: lazy task/actor DAGs (reference: ``python/ray/dag/``).

``fn.bind(...)`` / ``Actor.bind(...)`` build a DAG of nodes without
executing; ``dag.execute(input)`` walks it, submitting tasks/creating
actors and wiring ObjectRefs between them. ``InputNode`` marks the
per-execution input. A compiled DAG (``experimental_compile``)
pre-resolves the topology so repeated executions skip graph traversal
(the reference further lowers onto mutable-plasma channels —
``compiled_dag_node.py:141``; here compilation caches the topological
schedule and reuses created actors).
"""

from ray_tpu.dag.nodes import (
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "ClassMethodNode",
    "ClassNode",
    "DAGNode",
    "FunctionNode",
    "InputNode",
    "MultiOutputNode",
]
