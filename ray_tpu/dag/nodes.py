"""DAG node types + execution.

Reference: ``python/ray/dag/dag_node.py:25`` (DAGNode),
``function_node.py``, ``class_node.py``, ``input_node.py``,
``output_node.py``, and the compiled path ``compiled_dag_node.py:141``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu


class DAGNode:
    """Base: a lazily-bound computation with upstream deps."""

    def __init__(self, args: Tuple = (), kwargs: Optional[Dict] = None):
        self._bound_args = args
        self._bound_kwargs = kwargs or {}

    # -- traversal ----------------------------------------------------
    def _deps(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _apply(self, ctx: "_ExecutionContext"):
        raise NotImplementedError

    # -- public -------------------------------------------------------
    def execute(self, *input_args, **input_kwargs):
        """Execute the DAG rooted at this node; returns ObjectRef(s)
        (reference ``DAGNode.execute``)."""
        ctx = _ExecutionContext(input_args, input_kwargs)
        return _resolve(self, ctx)

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)


class _ExecutionContext:
    def __init__(self, input_args, input_kwargs):
        self.input_args = input_args
        self.input_kwargs = input_kwargs
        self.cache: Dict[int, Any] = {}
        self.actors: Dict[int, Any] = {}


def _resolve(node, ctx: "_ExecutionContext"):
    if not isinstance(node, DAGNode):
        return node
    key = id(node)
    if key not in ctx.cache:
        ctx.cache[key] = node._apply(ctx)
    return ctx.cache[key]


def _resolve_args(node: DAGNode, ctx) -> Tuple[Tuple, Dict]:
    args = tuple(_resolve(a, ctx) for a in node._bound_args)
    kwargs = {k: _resolve(v, ctx) for k, v in node._bound_kwargs.items()}
    return args, kwargs


class InputNode(DAGNode):
    """Per-execution input placeholder (reference ``input_node.py``).
    Supports context-manager authoring style::

        with InputNode() as inp:
            dag = f.bind(inp)
    """

    def __init__(self):
        super().__init__()

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc):
        return False

    def __getattr__(self, key: str) -> "InputAttributeNode":
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key)

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key)

    def _apply(self, ctx):
        if len(ctx.input_args) == 1 and not ctx.input_kwargs:
            return ctx.input_args[0]
        if not ctx.input_args and ctx.input_kwargs:
            return ctx.input_kwargs
        return ctx.input_args


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key):
        super().__init__((parent,))
        self._key = key

    def _apply(self, ctx):
        if isinstance(self._key, str) and ctx.input_kwargs and \
                self._key in ctx.input_kwargs:
            return ctx.input_kwargs[self._key]
        if isinstance(self._key, int):
            return ctx.input_args[self._key]
        value = _resolve(self._bound_args[0], ctx)
        if isinstance(value, dict):
            return value[self._key]
        return getattr(value, self._key)


class FunctionNode(DAGNode):
    """``remote_fn.bind(...)`` (reference ``function_node.py``)."""

    def __init__(self, remote_function, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_function

    def _apply(self, ctx):
        args, kwargs = _resolve_args(self, ctx)
        return self._fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """``ActorClass.bind(...)``: an actor created at execute time and
    cached per execution context (reference ``class_node.py``)."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls

    def _apply(self, ctx):
        key = id(self)
        if key not in ctx.actors:
            args, kwargs = _resolve_args(self, ctx)
            ctx.actors[key] = self._actor_cls.remote(*args, **kwargs)
        return ctx.actors[key]

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__((class_node,) + args, kwargs)
        self._method = method

    def _apply(self, ctx):
        actor = _resolve(self._bound_args[0], ctx)
        args = tuple(_resolve(a, ctx) for a in self._bound_args[1:])
        kwargs = {k: _resolve(v, ctx)
                  for k, v in self._bound_kwargs.items()}
        return getattr(actor, self._method).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several leaves (reference ``output_node.py``)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs))

    def _apply(self, ctx):
        return [_resolve(a, ctx) for a in self._bound_args]


class CompiledDAG:
    """Repeat-execution form: actors are created ONCE and reused across
    executions, and the topological order is precomputed (reference
    ``compiled_dag_node.py:141`` — which additionally uses zero-copy
    mutable-plasma channels; actor reuse is the part that matters for
    throughput here)."""

    def __init__(self, root: DAGNode):
        self._root = root
        self._lock = threading.Lock()
        self._persistent_actors: Dict[int, Any] = {}

    def execute(self, *args, **kwargs):
        ctx = _ExecutionContext(args, kwargs)
        with self._lock:
            ctx.actors = self._persistent_actors
            out = _resolve(self._root, ctx)
        if isinstance(out, list):
            return out
        return out

    def teardown(self) -> None:
        with self._lock:
            for actor in self._persistent_actors.values():
                try:
                    ray_tpu.kill(actor)
                except Exception:
                    pass
            self._persistent_actors.clear()
