"""DAG node types + execution.

Reference: ``python/ray/dag/dag_node.py:25`` (DAGNode),
``function_node.py``, ``class_node.py``, ``input_node.py``,
``output_node.py``, and the compiled path ``compiled_dag_node.py:141``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu


class DAGNode:
    """Base: a lazily-bound computation with upstream deps."""

    def __init__(self, args: Tuple = (), kwargs: Optional[Dict] = None):
        self._bound_args = args
        self._bound_kwargs = kwargs or {}

    # -- traversal ----------------------------------------------------
    def _deps(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _apply(self, ctx: "_ExecutionContext"):
        raise NotImplementedError

    # -- public -------------------------------------------------------
    def execute(self, *input_args, **input_kwargs):
        """Execute the DAG rooted at this node; returns ObjectRef(s)
        (reference ``DAGNode.execute``)."""
        ctx = _ExecutionContext(input_args, input_kwargs)
        return _resolve(self, ctx)

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)


class _ExecutionContext:
    def __init__(self, input_args, input_kwargs):
        self.input_args = input_args
        self.input_kwargs = input_kwargs
        self.cache: Dict[int, Any] = {}
        self.actors: Dict[int, Any] = {}


def _resolve(node, ctx: "_ExecutionContext"):
    if not isinstance(node, DAGNode):
        return node
    key = id(node)
    if key not in ctx.cache:
        ctx.cache[key] = node._apply(ctx)
    return ctx.cache[key]


def _resolve_args(node: DAGNode, ctx) -> Tuple[Tuple, Dict]:
    args = tuple(_resolve(a, ctx) for a in node._bound_args)
    kwargs = {k: _resolve(v, ctx) for k, v in node._bound_kwargs.items()}
    return args, kwargs


class InputNode(DAGNode):
    """Per-execution input placeholder (reference ``input_node.py``).
    Supports context-manager authoring style::

        with InputNode() as inp:
            dag = f.bind(inp)
    """

    def __init__(self):
        super().__init__()

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc):
        return False

    def __getattr__(self, key: str) -> "InputAttributeNode":
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key)

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key)

    def _apply(self, ctx):
        if len(ctx.input_args) == 1 and not ctx.input_kwargs:
            return ctx.input_args[0]
        if not ctx.input_args and ctx.input_kwargs:
            return ctx.input_kwargs
        return ctx.input_args


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key):
        super().__init__((parent,))
        self._key = key

    def _apply(self, ctx):
        if isinstance(self._key, str) and ctx.input_kwargs and \
                self._key in ctx.input_kwargs:
            return ctx.input_kwargs[self._key]
        if isinstance(self._key, int):
            return ctx.input_args[self._key]
        value = _resolve(self._bound_args[0], ctx)
        if isinstance(value, dict):
            return value[self._key]
        return getattr(value, self._key)


class FunctionNode(DAGNode):
    """``remote_fn.bind(...)`` (reference ``function_node.py``)."""

    def __init__(self, remote_function, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_function

    def _apply(self, ctx):
        args, kwargs = _resolve_args(self, ctx)
        return self._fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """``ActorClass.bind(...)``: an actor created at execute time and
    cached per execution context (reference ``class_node.py``)."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls

    def _apply(self, ctx):
        key = id(self)
        if key not in ctx.actors:
            args, kwargs = _resolve_args(self, ctx)
            ctx.actors[key] = self._actor_cls.remote(*args, **kwargs)
        return ctx.actors[key]

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__((class_node,) + args, kwargs)
        self._method = method

    def _apply(self, ctx):
        actor = _resolve(self._bound_args[0], ctx)
        args = tuple(_resolve(a, ctx) for a in self._bound_args[1:])
        kwargs = {k: _resolve(v, ctx)
                  for k, v in self._bound_kwargs.items()}
        return getattr(actor, self._method).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several leaves (reference ``output_node.py``)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs))

    def _apply(self, ctx):
        return [_resolve(a, ctx) for a in self._bound_args]


def _channel_stage_loop(instance, in_reader, out_chan, method):
    """Runs ON the stage actor for the pipeline's lifetime: read from the
    upstream channel, execute the bound method, write downstream — zero
    control-plane messages per item (reference: compiled-DAG actors block
    on mutable-object channels, experimental_mutable_object_manager.h).

    Items travel as ("ok", value) / ("err", exception) envelopes: a stage
    exception flows down the chain to the driver's get() instead of
    silently wedging the pipeline; the stage keeps serving later items."""
    from ray_tpu.experimental.channel import ChannelClosed
    fn = getattr(instance, method)
    try:
        while True:
            try:
                tag, value = in_reader.read()
            except ChannelClosed:
                out_chan.close()
                return "closed"
            if tag == "ok":
                try:
                    out_chan.write(("ok", fn(value)))
                    continue
                except ValueError:
                    raise  # oversized result: a channel-config error
                except BaseException as e:  # noqa: BLE001
                    out_chan.write(("err", e))
                    continue
            out_chan.write((tag, value))  # pass an upstream error along
    finally:
        in_reader.close()


class CompiledDAGRef:
    """Result handle of a channel-pipeline execute (reference:
    ``CompiledDAGRef`` — resolved via ``ray.get``)."""

    def __init__(self, pipeline: "_ChannelPipeline", seq: int):
        self._pipeline = pipeline
        self._seq = seq

    def __dag_local_value__(self, timeout: Optional[float] = None):
        return self._pipeline._value_for(self._seq, timeout)

    def get(self, timeout: Optional[float] = None):
        return self.__dag_local_value__(timeout)


class _ChannelPipeline:
    """Linear actor chain wired with mutable shm channels: one write at
    the head, one read at the tail, per execution — stages stream through
    shared memory with no per-hop RPC or object-store traffic."""

    def __init__(self, actors: List[Any], methods: List[str],
                 capacity: int):
        from ray_tpu.experimental.channel import Channel
        self.chans = [Channel(capacity) for _ in range(len(actors) + 1)]
        self._loops = []
        for i, (actor, method) in enumerate(zip(actors, methods)):
            self._loops.append(actor.__ray_call__.remote(
                _channel_stage_loop, self.chans[i].reader(0),
                self.chans[i + 1], method))
        self._out = self.chans[-1].reader(0)
        self._next_submit = 0
        self._next_read = 0
        self._done: Dict[int, Any] = {}

    #: results buffered for out-of-order gets; dropped refs must not
    #: accumulate forever
    _MAX_BUFFERED = 4096

    def execute(self, value, timeout: Optional[float] = None
                ) -> CompiledDAGRef:
        seq = self._next_submit
        self._next_submit += 1
        self.chans[0].write(("ok", value), timeout)
        return CompiledDAGRef(self, seq)

    def _value_for(self, seq: int, timeout: Optional[float]):
        while seq not in self._done:
            if self._next_read > seq:
                raise RuntimeError("compiled DAG result already consumed "
                                   "or evicted")
            tag, value = self._out.read(timeout)
            self._done[self._next_read] = (tag, value)
            self._next_read += 1
            if len(self._done) > self._MAX_BUFFERED:
                self._done.pop(min(self._done))  # oldest dropped ref
        tag, value = self._done.pop(seq)
        if tag == "err":
            raise value
        return value

    def teardown(self) -> None:
        try:
            self.chans[0].close()
        except TimeoutError:
            pass  # a wedged stage: actors are killed by CompiledDAG
        try:
            ray_tpu.get(self._loops, timeout=10)
        except Exception:
            pass
        self._out.close()
        for ch in self.chans:
            ch.destroy()


class CompiledDAG:
    """Repeat-execution form (reference ``compiled_dag_node.py:141``).
    A linear chain of bound actor methods over one input compiles to a
    mutable-channel pipeline: every hop moves through shared memory with
    zero per-call control-plane messages. Other shapes keep the
    persistent-actor fast path (actors created once, RPC per hop)."""

    #: per-value channel capacity for compiled pipelines
    channel_capacity: int = 1 << 20

    def __init__(self, root: DAGNode):
        self._root = root
        self._lock = threading.Lock()
        self._persistent_actors: Dict[int, Any] = {}
        self._pipeline: Optional[_ChannelPipeline] = None
        self._pipeline_checked = False

    def _try_build_pipeline(self) -> Optional[_ChannelPipeline]:
        """Detect InputNode -> m1 -> m2 -> ... (each stage a single-arg
        bound actor method whose data dependency is the previous stage)."""
        chain: List[ClassMethodNode] = []
        node = self._root
        while isinstance(node, ClassMethodNode):
            if node._bound_kwargs or len(node._bound_args) != 2:
                return None
            if not isinstance(node._bound_args[0], ClassNode):
                return None
            chain.append(node)
            node = node._bound_args[1]
        if not isinstance(node, InputNode) or not chain:
            return None
        # each stage needs its own actor: two loops on one serial actor
        # would deadlock (the second never starts)
        class_nodes = [id(n._bound_args[0]) for n in chain]
        if len(set(class_nodes)) != len(class_nodes):
            return None
        chain.reverse()
        ctx = _ExecutionContext((), {})
        ctx.actors = self._persistent_actors
        actors = [n._bound_args[0]._apply(ctx) for n in chain]
        methods = [n._method for n in chain]
        return _ChannelPipeline(actors, methods, self.channel_capacity)

    def execute(self, *args, **kwargs):
        with self._lock:
            if not self._pipeline_checked:
                self._pipeline_checked = True
                try:
                    self._pipeline = self._try_build_pipeline()
                except Exception:
                    self._pipeline = None
            if self._pipeline is not None:
                if len(args) != 1 or kwargs:
                    # the stage actors are now dedicated to their channel
                    # loops — an RPC fallback would queue behind them
                    # forever, so refuse loudly instead
                    raise TypeError(
                        "a compiled channel pipeline takes exactly one "
                        "positional input")
                return self._pipeline.execute(args[0])
            ctx = _ExecutionContext(args, kwargs)
            ctx.actors = self._persistent_actors
            out = _resolve(self._root, ctx)
        if isinstance(out, list):
            return out
        return out

    def teardown(self) -> None:
        with self._lock:
            if self._pipeline is not None:
                self._pipeline.teardown()
                self._pipeline = None
            for actor in self._persistent_actors.values():
                try:
                    ray_tpu.kill(actor)
                except Exception:
                    pass
            self._persistent_actors.clear()
