"""Internal KV helpers (reference: ``python/ray/experimental/
internal_kv.py`` — thin module-level functions over the GCS KV table,
used by libraries for small control-plane metadata)."""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.core.global_state import global_worker


def _kv_initialized() -> bool:
    from ray_tpu.core.global_state import try_global_worker
    return try_global_worker() is not None


def _internal_kv_put(key: bytes, value: bytes, overwrite: bool = True,
                     namespace: str = "") -> bool:
    """Returns True if the key already existed."""
    w = global_worker()
    if not overwrite and w.kv_exists(_b(key), ns=namespace):
        return True
    existed = w.kv_exists(_b(key), ns=namespace)
    w.kv_put(_b(key), _b(value), ns=namespace)
    return existed


def _internal_kv_get(key: bytes, namespace: str = "") -> Optional[bytes]:
    return global_worker().kv_get(_b(key), ns=namespace)


def _internal_kv_exists(key: bytes, namespace: str = "") -> bool:
    return global_worker().kv_exists(_b(key), ns=namespace)


def _internal_kv_del(key: bytes, namespace: str = "") -> bool:
    return global_worker().kv_del(_b(key), ns=namespace)


def _internal_kv_list(prefix: bytes, namespace: str = "") -> List[bytes]:
    return global_worker().kv_keys(_b(prefix), ns=namespace)


def _b(v) -> bytes:
    return v.encode() if isinstance(v, str) else bytes(v)
