"""Experimental: mutable-object channels + compiled-DAG fast path.

Reference: ``python/ray/experimental/channel.py`` and
``src/ray/core_worker/experimental_mutable_object_manager.h``.
"""

from ray_tpu.experimental.channel import Channel, ChannelClosed, ReaderHandle

__all__ = ["Channel", "ChannelClosed", "ReaderHandle"]
