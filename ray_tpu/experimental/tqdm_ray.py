"""Distributed progress bars (reference: ``python/ray/experimental/
tqdm_ray.py`` — workers emit structured progress records; the driver
renders them without interleaving worker stdout).

Worker side: ``tqdm_ray.tqdm(iterable, total=...)`` prints magic-token
JSON lines; they ride the normal worker-log stream. Driver side: the
log monitor recognizes the token and re-renders in place instead of
echoing raw lines.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Iterable, Iterator, Optional

MAGIC = "__ray_tpu_tqdm__:"


class tqdm:
    """Minimal tqdm-compatible facade that emits progress records."""

    def __init__(self, iterable: Optional[Iterable] = None, *,
                 desc: str = "", total: Optional[int] = None,
                 position: int = 0, flush_interval_s: float = 0.2,
                 **_ignored: Any):
        self._iterable = iterable
        self.desc = desc
        if total is None and iterable is not None:
            try:
                total = len(iterable)  # type: ignore[arg-type]
            except TypeError:
                total = None
        self.total = total
        self.position = position
        self.n = 0
        self._flush_interval = flush_interval_s
        self._last_flush = 0.0
        self._emit()

    def __iter__(self) -> Iterator:
        assert self._iterable is not None
        for item in self._iterable:
            yield item
            self.update(1)
        self.close()

    def update(self, n: int = 1) -> None:
        self.n += n
        now = time.monotonic()
        if now - self._last_flush >= self._flush_interval:
            self._emit()

    def set_description(self, desc: str) -> None:
        self.desc = desc
        self._emit()

    def close(self) -> None:
        self._emit(final=True)

    def _emit(self, final: bool = False) -> None:
        self._last_flush = time.monotonic()
        rec = {"desc": self.desc, "n": self.n, "total": self.total,
               "pos": self.position, "final": final}
        print(MAGIC + json.dumps(rec), flush=True)


def render_record(line: str, out=None) -> bool:
    """Driver-side: if ``line`` is a tqdm record, render it and return
    True (the log monitor then suppresses the raw line)."""
    if MAGIC not in line:
        return False
    out = out or sys.stderr
    try:
        rec = json.loads(line.split(MAGIC, 1)[1])
    except (ValueError, IndexError):
        return False
    total = rec.get("total")
    n = rec.get("n", 0)
    desc = rec.get("desc") or "progress"
    if total:
        pct = 100.0 * n / max(total, 1)
        bar = ("#" * int(pct // 5)).ljust(20)
        print(f"\r{desc}: [{bar}] {n}/{total} ({pct:.0f}%)",
              end="\n" if rec.get("final") else "",
              file=out, flush=True)
    else:
        print(f"\r{desc}: {n} it", end="\n" if rec.get("final") else "",
              file=out, flush=True)
    return True
