"""Mutable-object channels: zero-RPC shared-memory hand-off.

Reference: ``python/ray/experimental/channel.py:49`` (Channel over a
mutable plasma object) + ``src/ray/core_worker/
experimental_mutable_object_manager.h:63`` (seqno'd header, writer
blocks until readers release). The reference re-seals a special plasma
object per version; here the channel is its own mmapped file with an
inline header — one writer and a fixed set of readers synchronize
through aligned 8-byte fields (atomic loads/stores on every platform
CPython runs on) with adaptive spin-then-sleep waits instead of
cross-process semaphores, so a hand-off costs microseconds and no
control-plane message at all.

Layout (little-endian u64 fields, 4 KiB header):
  [0]  magic
  [1]  capacity (payload bytes)
  [2]  num_readers
  [3]  seqno          - version currently published (0 = nothing yet)
  [4]  payload_size   - bytes valid for this seqno; CLOSED sentinel ends
  [5..] reader acks   - reader i stores the seqno it finished consuming

Writer protocol: wait until every ack == seqno (previous value fully
consumed), memcpy payload, then publish seqno+1. Reader protocol: wait
until seqno > last consumed, read, store ack. Single-slot with
back-pressure, exactly the reference's semantics.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
import uuid
from typing import Any, Optional

from ray_tpu.core import protocol as P

_MAGIC = 0x52545055_4348414E  # "RTPUCHAN"
_HEADER = 4096
_CLOSED = 2 ** 64 - 1
_MAX_READERS = (_HEADER - 40) // 8
_U64 = struct.Struct("<Q")


class ChannelClosed(Exception):
    """The writer closed the channel."""


def _wait(predicate, timeout: Optional[float], what: str):
    """Adaptive spin: hot for ~50us, then escalate to short sleeps."""
    deadline = None if timeout is None else time.monotonic() + timeout
    spins = 0
    while True:
        if predicate():
            return
        spins += 1
        if spins < 200:
            if spins % 50 == 0:
                time.sleep(0)  # yield the GIL: the peer may be in-process
            continue
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(f"channel {what} timed out")
        time.sleep(0.000_05 if spins < 2000 else 0.001)


class _Mapped:
    def __init__(self, path: str, capacity: Optional[int]):
        self.path = path
        if capacity is not None:
            fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o600)
            try:
                os.ftruncate(fd, _HEADER + capacity)
                self.mm = mmap.mmap(fd, _HEADER + capacity)
            finally:
                os.close(fd)
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                total = os.fstat(fd).st_size
                self.mm = mmap.mmap(fd, total)
            finally:
                os.close(fd)

    def get(self, idx: int) -> int:
        return _U64.unpack_from(self.mm, idx * 8)[0]

    def put(self, idx: int, value: int) -> None:
        _U64.pack_into(self.mm, idx * 8, value)

    def close(self) -> None:
        try:
            self.mm.close()
        except Exception:
            pass


class Channel:
    """Writer end (also the creator). Picklable: unpickling yields a
    writer handle onto the same channel."""

    def __init__(self, capacity: int = 1 << 20, num_readers: int = 1,
                 _path: Optional[str] = None):
        if num_readers < 1 or num_readers > _MAX_READERS:
            raise ValueError(f"num_readers must be 1..{_MAX_READERS}")
        self.capacity = capacity
        self.num_readers = num_readers
        if _path is None:
            self.path = f"/dev/shm/raytpu-chan-{uuid.uuid4().hex[:16]}"
            self._m = _Mapped(self.path, capacity)
            self._m.put(1, capacity)
            self._m.put(2, num_readers)
            self._m.put(0, _MAGIC)  # publish last
        else:
            self.path = _path
            self._m = _Mapped(self.path, None)
            if self._m.get(0) != _MAGIC:
                raise ValueError(f"not a channel: {self.path}")

    # ------------------------------------------------------------ write
    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        blob = P.dumps(value)
        if len(blob) > self.capacity:
            raise ValueError(
                f"serialized value ({len(blob)} B) exceeds channel "
                f"capacity ({self.capacity} B)")
        self._write_raw(blob, len(blob), timeout)

    def _write_raw(self, blob: bytes, size: int,
                   timeout: Optional[float]) -> None:
        m = self._m
        seq = m.get(3)
        n = self.num_readers
        _wait(lambda: all(m.get(5 + i) >= seq for i in range(n)),
              timeout, "write (readers lagging)")
        if blob:
            m.mm[_HEADER:_HEADER + len(blob)] = blob
        m.put(4, size)
        m.put(3, seq + 1)

    def close(self, timeout: Optional[float] = 5.0) -> None:
        """Publish the CLOSED sentinel (readers raise ChannelClosed).
        Raises TimeoutError when a lagging reader never drains the last
        value — swallowing that would leave readers blocked forever with
        the caller believing the channel closed."""
        self._write_raw(b"", _CLOSED, timeout)

    def reader(self, reader_id: int = 0) -> "ReaderHandle":
        return ReaderHandle(self.path, reader_id)

    def destroy(self) -> None:
        self._m.close()
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    def __reduce__(self):
        return (_open_writer, (self.path,))


def _open_writer(path: str) -> Channel:
    ch = Channel.__new__(Channel)
    ch.path = path
    ch._m = _Mapped(path, None)
    if ch._m.get(0) != _MAGIC:
        raise ValueError(f"not a channel: {path}")
    ch.capacity = ch._m.get(1)
    ch.num_readers = ch._m.get(2)
    return ch


class ReaderHandle:
    """Reader end: each reader owns ack slot ``reader_id``. Picklable —
    ship it to the consuming actor/task."""

    def __init__(self, path: str, reader_id: int):
        self.path = path
        self.reader_id = reader_id
        self._m: Optional[_Mapped] = None
        self._last = 0

    def _map(self) -> _Mapped:
        if self._m is None:
            self._m = _Mapped(self.path, None)
            if self._m.get(0) != _MAGIC:
                raise ValueError(f"not a channel: {self.path}")
            # resume from our persisted ack (reader restarted)
            self._last = self._m.get(5 + self.reader_id)
        return self._m

    def read(self, timeout: Optional[float] = None) -> Any:
        m = self._map()
        _wait(lambda: m.get(3) > self._last, timeout, "read")
        size = m.get(4)
        if size == _CLOSED:
            raise ChannelClosed
        value = P.loads(bytes(m.mm[_HEADER:_HEADER + size]))
        self._last = m.get(3)
        m.put(5 + self.reader_id, self._last)
        return value

    def close(self) -> None:
        if self._m is not None:
            self._m.close()
            self._m = None

    def __reduce__(self):
        return (ReaderHandle, (self.path, self.reader_id))
